"""Driver benchmark: the SHIPPED backup data path on one TPU chip.

Measures the fused single-dispatch segment pipeline (ops/segment.py) that
``DeviceChunkHasher`` / ``stream_chunks`` / ``TreeBackup`` run per
segment: aligned gear-CDC candidates, the on-device FastCDC boundary
walk, strided Merkle leaf SHA-256 (Pallas on TPU), on-device root
assembly, and the ONE small result fetch (chunk table + 32-byte blob ids)
— the restic-engine replacement (SURVEY.md §2.2 #25) on its real code
path, not a kernel microbenchmark.

Shape of the run: N concurrent streams (the reference's concurrency unit
is a mover pod per ReplicationSource, up to MaxConcurrentReconciles=100;
here many CRs share one chip) each drive segments of a synthetic
50%-redundant volume (BASELINE.json configs[4]). Data is device-resident
and salted per iteration: the serving tunnel memoizes executions with
identical args and its host<->device link is not representative of a TPU
VM's DMA path, so upload is excluded — the same basis as the CPU number,
which also reads from RAM.

The CPU baseline is the identical computation on one core the way the
reference's mover pod would do it: gear-CDC scan + per-chunk blob ids via
hashlib.

Robustness contract (round-3 postmortem: the bench burned the driver's
whole budget dying in backend init):
  * The TPU backend is probed in a SUBPROCESS with a hard timeout before
    anything else — a hung ``jax.devices()`` can never stall this
    process.
  * Backend-init / UNAVAILABLE errors get a few quick retries and then a
    CPU-backend fallback (clearly labeled in the JSON) — never the slow
    config ladder; a smaller segment cannot fix a dead tunnel.
  * Only resource exhaustion (or a per-config deadline) walks the ladder
    down to smaller configs; each config runs under a SIGALRM deadline.
  * A global watchdog thread guarantees one JSON line before the driver's
    timeout no matter what wedges.
  * The persistent compilation cache is enabled so CPU-path retries
    (and future rounds) do not pay recompilation. NOTE: the serving
    tunnel's remote-compile path bypasses the local cache, so TPU
    configs pay their full compile inside the config deadline — the
    ladder is ordered by known compile cost for exactly this reason.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
diagnostics {"backend", "path", "config"}.
"""

from __future__ import annotations

import functools
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np

# envflags imports only os — safe before the JAX env setup below.
from volsync_tpu.envflags import (
    env_bool,
    env_int,
    env_str,
    no_pallas,
    session_backend,
    session_epoch,
    session_id,
)

# Persistent compilation cache: retries and later rounds reuse compiled
# executables instead of paying the 20-40s first compile again. Must be
# set before jax is imported anywhere in this process.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# Wall-clock budgets (seconds). The driver's historical kill is ~75 min.
# Consistency invariant: probe worst case (sum(PROBE_TIMEOUTS)+backoffs,
# ~330s) + the device measurement subprocess (MEASURE_TIMEOUT_S) + the
# CPU fallback subprocess (CPU_MEASURE_TIMEOUT_S) must fit inside
# GLOBAL_BUDGET_S, or the watchdog would kill a still-progressing run
# with no JSON emitted — the exact failure this file exists to prevent.
# The recovery phase (_recover_backend) self-limits against
# _budget_left() with a CPU-fallback reserve, and the device
# measurement's timeout shrinks to what recovery left over, so the
# invariant survives any recovery spend. Each subprocess's own ladder
# (configs x per-config deadline) must fit inside its timeout.
PROBE_TIMEOUTS = (120, 200)
PROBE_BACKOFF_S = 15
CONFIG_DEADLINE_S = env_int("VOLSYNC_BENCH_CONFIG_DEADLINE", 420)
CPU_CONFIG_DEADLINE_S = env_int("VOLSYNC_BENCH_CPU_CONFIG_DEADLINE", 240)
MEASURE_TIMEOUT_S = env_int("VOLSYNC_BENCH_MEASURE_TIMEOUT", 1800)
CPU_MEASURE_TIMEOUT_S = env_int("VOLSYNC_BENCH_CPU_MEASURE_TIMEOUT", 1200)
GLOBAL_BUDGET_S = env_int("VOLSYNC_BENCH_BUDGET_S", 3600)

_log = functools.partial(print, file=sys.stderr, flush=True)

# Best result seen so far: the watchdog prints this if the main thread
# wedges after a successful measurement (e.g. a stuck executor join).
_BEST: dict | None = None
_BEST_LOCK = threading.Lock()


def _emit(result: dict) -> None:
    """Print one result line — REFUSED unless it carries a provenance
    block. An unattributable number is worse than no number: round 4's
    CPU-fallback figures were only caught because provenance said so
    (docs/performance.md). Callers stamp ``bench_provenance()`` first."""
    if not result.get("provenance"):
        raise ValueError(
            "bench result refused: no provenance block "
            f"(keys: {sorted(result)})")
    print(json.dumps(result), flush=True)


# Copy-ratio regression thresholds (``bench.py copies-smoke``).
# copy_ratio = ledgered host copy bytes / payload bytes moved through
# the timed pipelined run; the smoke FAILS when a measured ratio
# exceeds its committed maximum, so a new unledgered copy path can't
# land silently. Raising a threshold is a reviewed change, like adding
# a record_copy site. Values carry ~20% headroom over the measured
# smoke-scale ratios — pipeline 2.0 (chunker.ingest for the read()-only
# bench reader + objstore.assemble for the contiguous Mem transport),
# restore 1.0 (verify.stage) — see docs/performance.md, "Zero-copy
# data movement" for what each remaining site pays.
COPY_RATIO_MAX = {"pipeline": 2.4, "restore": 1.2}


def _copy_report(total_bytes: int, kind: str, legacy_passes: float) -> dict:
    """Ledger snapshot for the timed window -> artifact block.

    ``copy_ratio`` is ledgered host copy bytes per payload byte;
    ``copy_ratio_pre`` is an ANALYTIC estimate (ratio + the full
    payload passes the legacy sites paid: monolithic pack-body
    assembly on backup, slice-of-pack-body segment extraction on
    restore) — documented in the artifact so the drop is visible
    without resurrecting the old code path."""
    from volsync_tpu.obs import copies_by_site

    sites = {k: int(v) for k, v in sorted(copies_by_site().items())}
    copied = sum(sites.values())
    ratio = round(copied / max(1, total_bytes), 3)
    return {
        "copy_bytes_by_site": sites,
        "copy_bytes_total": copied,
        "copy_ratio": ratio,
        "copy_ratio_pre_estimate": round(ratio + legacy_passes, 3),
        "copy_ratio_max": COPY_RATIO_MAX[kind],
    }


def bench_provenance(extra: Optional[dict] = None) -> dict:
    """Provenance block stamped into every bench JSON result: platform,
    git rev, the VOLSYNC_*/JAX_PLATFORMS knobs in effect, and — only
    when it can be read without side effects — the jax backend and
    device kind. A CPU-fallback number must never be mistakable for a
    chip number again (ROADMAP item 1).

    Never *initializes* jax: ``jax.default_backend()`` on an
    uninitialized import can hang on a wedged serving tunnel — the
    exact failure this file exists to contain. The backend is reported
    only if a backend already exists in this process or the env pins
    CPU; otherwise it is labeled honestly as not initialized."""
    import platform

    prov: dict = {
        "platform": platform.platform(),
        "python": platform.python_version(),
    }
    try:
        r = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        prov["git_rev"] = (r.stdout.strip() if r.returncode == 0
                           else "unknown")
    except OSError as e:
        _log(f"bench: git rev unavailable: {e}")
        prov["git_rev"] = "unknown"
    jx = sys.modules.get("jax")
    if jx is None:
        prov["jax_backend"] = "not-imported"
    else:
        bridge = getattr(getattr(jx, "_src", None), "xla_bridge", None)
        initialized = bool(getattr(bridge, "_backends", None))
        env = dict(os.environ)
        if initialized or env.get("JAX_PLATFORMS", "").strip() == "cpu":
            try:
                prov["jax_backend"] = jx.default_backend()
                prov["jax_device_kind"] = jx.devices()[0].device_kind
            except Exception as e:  # noqa: BLE001 — label, never hang/abort
                _log(f"bench: backend read failed: {e}")
                prov["jax_backend"] = f"error:{type(e).__name__}"
        else:
            prov["jax_backend"] = "imported-uninitialized"
    prov["volsync_flags"] = {
        k: v for k, v in sorted(dict(os.environ).items())
        if k.startswith("VOLSYNC_") or k == "JAX_PLATFORMS"}
    sid = session_id()
    if sid:
        # Stamped by the serialized bench queue (cluster/sessions.py)
        # into every job's environment: which supervised session, under
        # which fencing epoch, produced this number.
        prov["session"] = {"id": sid, "epoch": session_epoch(),
                           "backend": session_backend() or "unknown"}
    if extra:
        prov.update(extra)
    return prov


def _watchdog() -> None:
    time.sleep(GLOBAL_BUDGET_S)
    with _BEST_LOCK:
        best = _BEST
    if best is not None:
        _log("bench: WATCHDOG fired after measurement — emitting best result")
        try:
            _emit(best)
            os._exit(0)
        except ValueError as e:
            # Provenance refusal must not strand the watchdog short of
            # its os._exit — fall through to the no-result exit code.
            _log(f"bench: WATCHDOG result refused: {e}")
    _log(f"bench: WATCHDOG fired with no result after {GLOBAL_BUDGET_S}s")
    os._exit(75)


class _Deadline(Exception):
    """Per-config SIGALRM deadline expired."""


class _BackendDown(Exception):
    """Backend init / UNAVAILABLE — retrying smaller configs cannot help."""


def _classify(e: BaseException) -> str:
    s = f"{type(e).__name__}: {e}"
    if re.search(r"RESOURCE[_ ]EXHAUSTED|out of memory|OOM|"
                 r"[Aa]ttempting to allocate|[Aa]llocation.*failed", s):
        return "oom"
    if re.search(r"UNAVAILABLE|Unable to initialize|DEADLINE_EXCEEDED|"
                 r"failed to connect|[Cc]onnection|[Ss]ocket|INTERNAL:", s):
        return "backend"
    return "other"


_PROBE_SRC = """
import jax, jax.numpy as jnp
x = jnp.arange(64, dtype=jnp.float32)
y = jax.jit(lambda v: (v * 2 + 1).sum())(x)
y.block_until_ready()
print("probe-ok", jax.default_backend())
"""


def _force_cpu_backend():
    """Pin jax to the CPU backend IN CONFIG, not env: the container's
    sitecustomize registers the TPU plugin and overrides jax_platforms
    at interpreter start, so JAX_PLATFORMS=cpu in the environment is
    silently ineffective — config.update after import wins (same trick
    as tests/conftest.py)."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _probe_backend(timeouts=PROBE_TIMEOUTS) -> Optional[str]:
    """Probe backend init in a subprocess with a hard timeout; returns
    the default backend's platform name, or None if unreachable.

    A wedged ``jax.devices()`` (observed: >25 min inside backend setup in
    round 3) hangs in C++ where SIGALRM cannot reliably interrupt, so the
    probe must be a separate killable process."""
    for i, tmo in enumerate(timeouts):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=tmo, capture_output=True, text=True,
                env=os.environ.copy())
            dt = time.perf_counter() - t0
            if r.returncode == 0 and "probe-ok" in r.stdout:
                name = r.stdout.strip().split()[-1]
                _log(f"bench: backend probe ok in {dt:.1f}s ({name})")
                return name
            _log(f"bench: probe attempt {i + 1} rc={r.returncode} in "
                 f"{dt:.1f}s: {(r.stderr or '').strip()[-300:]}")
        except subprocess.TimeoutExpired:
            _log(f"bench: probe attempt {i + 1} timed out after {tmo}s")
        if i + 1 < len(timeouts):
            # Device-settle pacing between subprocess probes, not an
            # error-retry of a store call — RetryPolicy doesn't apply.
            time.sleep(PROBE_BACKOFF_S)  # lint: ignore[VL105]
    return None


def _kill_stale_bench_children(
        marker: str = "VOLSYNC_BENCH_INNER=1") -> int:
    """SIGKILL measurement processes leaked by PRIOR bench runs — the
    round-4 wedge cause was a leaked single-tenant session still holding
    the serving tunnel at bench time. Targeted: only processes whose
    environment carries ``marker`` (VOLSYNC_BENCH_INNER=1, set
    exclusively by this harness's measurement children — a concurrent
    second bench would itself be a single-tenant violation) and that
    are not this process or its parent. Never touches other TPU
    clients. ``marker`` is parameterized so tests can sweep a sentinel
    value without ever matching a real run.

    The /proc sweep itself lives in cluster/sessions.py now (it is the
    session supervisor's ``force_release`` action); this wrapper keeps
    the historical bench entry point. Imported lazily so the bench can
    still start if the cluster package is mid-refactor."""
    from volsync_tpu.cluster.sessions import kill_marked_children

    return kill_marked_children(marker, log_fn=_log)


def _recover_backend() -> Optional[str]:
    """Chip-recovery phase (the committed playbook, in-process): after
    the normal probes fail, (1) SIGKILL stale measurement children a
    previous bench leaked on the single-tenant tunnel, (2) go QUIET and
    re-probe sparsely over a longer horizon — killed probes each leave
    another dead queued session needing server-side GC, so hammering
    the tunnel extends the wedge (round-3/4 postmortems,
    docs/performance.md). Budget-aware: always leaves room for the CPU
    fallback + its labeling, so a never-recovering tunnel still emits
    an honest JSON line."""
    killed = _kill_stale_bench_children()
    reserve = CPU_MEASURE_TIMEOUT_S + 180  # fallback + parent overhead
    if killed and _budget_left() - reserve > 160:
        # Give the server a moment to GC the killed sessions, then one
        # immediate probe: this is the one recovery path with a known
        # cause-and-effect. Guarded by the same reserve as the quiet
        # loop — a tiny operator-set budget must still reach the
        # labeled CPU fallback.
        time.sleep(30)
        name = _probe_backend(timeouts=(120,))
        if name is not None:
            return name
    quiet_s = env_int("VOLSYNC_BENCH_RECOVERY_QUIET", 600)
    max_probes = env_int("VOLSYNC_BENCH_RECOVERY_PROBES", 2)
    for i in range(max_probes):
        wait = min(quiet_s, _budget_left() - reserve - 140)
        if wait <= 60:
            _log("bench: recovery window exhausted — falling back")
            break
        _log(f"bench: tunnel wedged — quiet {wait:.0f}s before recovery "
             f"probe {i + 1}/{max_probes}")
        time.sleep(wait)
        name = _probe_backend(timeouts=(120,))
        if name is not None:
            return name
    return None


def _host_gear_candidates(host: np.ndarray, p) -> tuple[np.ndarray, np.ndarray]:
    """Pure-numpy aligned gear scan -> (strict, lax) candidate cut
    positions. The host reference for the device kernel
    (ops/gearcdc.gear_at_aligned): table value per byte, 32-byte window
    weighted by shifts 31..0, mod 2^32. Shared by the golden self-check
    and the CPU baseline so the two can never desynchronize."""
    n = host.shape[0] // p.align * p.align
    rows = host[:n].reshape(-1, p.align)[:, -32:]
    g = p.table[rows].astype(np.uint64)
    shifts = np.arange(31, -1, -1, dtype=np.uint64)
    h = ((g << shifts[None, :]).sum(axis=1) & 0xFFFFFFFF).astype(np.uint32)
    pos = np.arange(h.shape[0], dtype=np.int64) * p.align + (p.align - 1)
    return (pos[(h & np.uint32(p.mask_s)) == 0],
            pos[(h & np.uint32(p.mask_l)) == 0])


def _make_data(total: int, redundancy: float = 0.5) -> np.ndarray:
    """BASELINE.json configs[4]-style synthetic volume: ``redundancy`` of
    the stream is a repeated region (dedup finds it; boundaries/digests
    are computed for every byte either way)."""
    rng = np.random.RandomState(7)
    uniq = rng.randint(0, 256, size=(int(total * (1 - redundancy)),),
                       dtype=np.uint8)
    rep = rng.randint(0, 256, size=(total - uniq.shape[0],), dtype=np.uint8)
    return np.concatenate([uniq, rep])


def _try_device_throughput(seg_mib: int, streams: int, iters: int) -> float:
    import jax
    import jax.numpy as jnp

    from volsync_tpu.engine.chunker import DeviceChunkHasher
    from volsync_tpu.ops.gearcdc import DEFAULT_PARAMS
    from volsync_tpu.ops.segment import chunk_hash_segment

    p = DEFAULT_PARAMS
    n = seg_mib * 1024 * 1024
    host_np = _make_data(n)
    data = jnp.asarray(host_np)
    jax.block_until_ready(data)

    # The salt is composed INTO the one fused dispatch (d ^ s traces
    # through the identical library program), so every iteration hashes
    # distinct content with no data-sized transfer. Dispatch, retry
    # logic, decode, and the blob-id assembly are the unmodified shipped
    # code (FusedSegmentHasher drives this via its override hook).
    @functools.partial(jax.jit, static_argnames=("eof", "cand_cap",
                                                 "chunk_cap"))
    def salted(d, s, vl, *, eof, cand_cap, chunk_cap):
        return chunk_hash_segment(
            d ^ s, vl, min_size=p.min_size, avg_size=p.avg_size,
            max_size=p.max_size, seed=p.seed, mask_s=p.mask_s,
            mask_l=p.mask_l, align=p.align, eof=eof, cand_cap=cand_cap,
            chunk_cap=chunk_cap)

    def make_hasher(stream_id: int) -> DeviceChunkHasher:
        h = DeviceChunkHasher(p)
        h.salt = jnp.uint8(stream_id & 0xFF)

        def fn(dev, length, **kw):
            return salted(dev, h.salt, length, eof=kw["eof"],
                          cand_cap=kw["cand_cap"], chunk_cap=kw["chunk_cap"])

        h.fused.segment_device_fn = fn
        return h

    # Distinct uint8 salt per (stream, iteration) — a collision would let
    # the tunnel memoize an execution and fake the measurement.
    assert streams * iters < 255, "salt space exhausted"

    # Deadline hygiene: a _Deadline fires in the MAIN thread; leaked
    # workers from the abandoned pool would keep dispatching and
    # contaminate the NEXT ladder config's measurement. They check this
    # flag between segments, so leakage is bounded to one in-flight
    # dispatch per worker.
    cancelled = threading.Event()

    def run_stream(stream_id: int) -> int:
        """One CR's backup loop over ``iters`` segments: dispatch + the
        single small fetch per segment (the shipped protocol)."""
        h = make_hasher(stream_id)
        emitted = 0
        for i in range(iters):
            if cancelled.is_set():
                break
            # Per-segment scalar salt upload is the shipped protocol
            # under measurement — batching it would change the workload.
            h.salt = jnp.uint8((stream_id - 1) * iters + i + 1)  # lint: ignore[VL502] measured protocol
            emitted += len(h.process_device(data, n))
        return emitted

    # Warm all shapes/compiles once — and use the (unsalted) warm run as
    # an on-TPU golden check against a PURE-HOST reference (numpy gear
    # scan + the scalar FastCDC walk + hashlib Merkle ids): no second
    # device program to compile, and nothing the device computes is
    # trusted to check itself.
    h0 = make_hasher(0)
    h0.salt = jnp.uint8(0)
    warm = h0.process_device(data, n)
    from volsync_tpu.ops.gearcdc import _select_boundaries_py
    from volsync_tpu.repo import blobid

    idx_s, idx_l = _host_gear_candidates(host_np, p)
    ref_bounds = _select_boundaries_py(idx_s, idx_l, n, p, eof=True)
    assert [(s, l) for s, l, _ in warm] == ref_bounds, "fused boundaries"
    view = host_np.tobytes()
    for s, l, d in warm[:4] + warm[-2:]:
        assert d == blobid.blob_id(view[s: s + l]), "fused blob id"

    from concurrent.futures import ThreadPoolExecutor

    t0 = time.perf_counter()
    pool = ThreadPoolExecutor(streams)
    try:
        emitted = sum(pool.map(run_stream, range(1, streams + 1)))
    finally:
        # Never join wedged workers under a deadline — the watchdog is
        # the backstop, not a hung interpreter exit.
        cancelled.set()
        pool.shutdown(wait=False, cancel_futures=True)
    dt = time.perf_counter() - t0
    assert emitted > 0
    return streams * iters * n / dt  # bytes/s, full shipped path


def _config_deadline_s() -> int:
    return (CPU_CONFIG_DEADLINE_S
            if env_bool("VOLSYNC_BENCH_CPU_FALLBACK")
            else CONFIG_DEADLINE_S)


def _try_batched_throughput(seg_mib: int, streams: int, iters: int,
                            pipelines: Optional[int] = None) -> float:
    """The cross-PVC batched dispatch (ops/segment.chunk_hash_segments):
    all streams' segments in ONE device program per iteration — no
    per-stream dispatch/fetch round-trips at all. Lane content is the
    shared base buffer xor a per-lane salt, composed on device.

    ``pipelines`` concurrent dispatch threads overlap the fixed
    per-dispatch cost (~7 ms execution overhead + ~80 ms result round
    trip through the serving tunnel, measured r4) with device compute —
    the same overlap the shipped SegmentMicroBatcher gets from
    concurrent movers. Default 2; VOLSYNC_BENCH_PIPELINES overrides so
    bench_self rungs can A/B the depth on hardware."""
    if pipelines is None:
        pipelines = env_int("VOLSYNC_BENCH_PIPELINES", 2)
    import functools as _ft
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp

    from volsync_tpu.ops.gearcdc import DEFAULT_PARAMS
    from volsync_tpu.ops.segment import chunk_hash_segments, segment_caps

    p = DEFAULT_PARAMS
    n = seg_mib * 1024 * 1024
    host_np = _make_data(n)
    base = jnp.asarray(host_np)
    jax.block_until_ready(base)
    cand_cap, chunk_cap = segment_caps(n, p)

    @_ft.partial(jax.jit, static_argnames=("cand_cap", "chunk_cap"))
    def salted(d, salts, vl, eof, *, cand_cap, chunk_cap):
        rows = d[None, :] ^ salts[:, None]  # [S, P] composed on device
        return chunk_hash_segments(
            rows, vl, eof, min_size=p.min_size, avg_size=p.avg_size,
            max_size=p.max_size, seed=p.seed, mask_s=p.mask_s,
            mask_l=p.mask_l, align=p.align, cand_cap=cand_cap,
            chunk_cap=chunk_cap)

    vl = jnp.full((streams,), n, jnp.int32)
    eof = jnp.ones((streams,), bool)
    # +1 round: run(iters) is the warm call, so salts reach
    # (iters+1)*streams; uint8 wraparound would let warm salts collide
    # with timed ones and the memoizing tunnel would inflate the number.
    assert streams * (iters + 1) < 255, "salt space exhausted"

    # On-TPU golden check, which doubles as the warm/compile run (its
    # salt range is disjoint from the timed ones): DISTINCT per-lane
    # salts — identical lanes would let a cross-lane indexing bug
    # (every row computed from lane 0) pass — with the first and last
    # lanes verified against the PURE-HOST reference (numpy gear scan,
    # scalar FastCDC walk, hashlib Merkle roots of head + tail chunks).
    from volsync_tpu.ops.gearcdc import _select_boundaries_py
    from volsync_tpu.ops.segment import decode_segment
    from volsync_tpu.repo import blobid

    salt0 = streams * (iters + 1) + 1
    assert salt0 + streams - 1 < 255, "golden salt space exhausted"
    g_out = np.asarray(salted(
        base, jnp.asarray(np.arange(salt0, salt0 + streams,
                                    dtype=np.uint8)), vl, eof,
        cand_cap=cand_cap, chunk_cap=chunk_cap))
    for lane in {0, streams - 1}:
        lane_np = host_np ^ np.uint8(salt0 + lane)
        idx_s, idx_l = _host_gear_candidates(lane_np, p)
        ref_bounds = _select_boundaries_py(idx_s, idx_l, n, p, eof=True)
        g_chunks, _, _, _ = decode_segment(g_out[lane], chunk_cap)
        assert [(s, l) for s, l, _ in g_chunks] == ref_bounds, \
            f"batched boundaries (lane {lane})"
        view = lane_np.tobytes()
        for s0, l0, d0 in g_chunks[:2] + g_chunks[-2:]:
            assert d0 == blobid.blob_id(view[s0:s0 + l0]), \
                f"batched blob id (lane {lane})"

    # Deadline hygiene (same contract as _try_device_throughput): a
    # _Deadline fires in the MAIN thread; never join possibly-wedged
    # workers — shutdown(wait=False) + a cancellation flag bound the
    # leakage to one in-flight dispatch per pipeline.
    cancelled = threading.Event()

    def run(i):
        if cancelled.is_set():
            return None
        salts = jnp.asarray(
            np.arange(1 + i * streams, 1 + (i + 1) * streams,
                      dtype=np.uint8))
        out = np.asarray(salted(base, salts, vl, eof, cand_cap=cand_cap,
                                chunk_cap=chunk_cap))
        assert int(out[0, 0]) > 0  # lanes produced chunks
        return out

    # (no separate warm run: the golden-check dispatch above compiled
    # and executed this exact program shape)
    t0 = time.perf_counter()
    if pipelines <= 1:
        for i in range(iters):
            run(i)
    else:
        pool = ThreadPoolExecutor(pipelines)
        try:
            done = sum(r is not None for r in pool.map(run, range(iters)))
            assert done == iters, "pipelined dispatches cancelled mid-run"
        finally:
            cancelled.set()
            pool.shutdown(wait=False, cancel_futures=True)
    dt = time.perf_counter() - t0
    return streams * iters * n / dt


def _with_deadline(fn, *args):
    """Run fn under a SIGALRM wall-clock deadline (main thread only)."""
    deadline = _config_deadline_s()

    def _alarm(signum, frame):
        raise _Deadline(f"config exceeded {deadline}s")

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, deadline)
    try:
        return fn(*args)
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


_START = time.monotonic()


def _budget_left() -> float:
    return GLOBAL_BUDGET_S - (time.monotonic() - _START)


def _try_config(kind: str, seg_mib: int, streams: int, iters: int) -> float:
    t0 = time.perf_counter()
    _log(f"bench: trying {kind}{seg_mib}x{streams}x{iters}")
    fn = (_try_batched_throughput if kind == "B"
          else _try_device_throughput)
    out = _with_deadline(fn, seg_mib, streams, iters)
    _log(f"bench: config ok -> {out / (1 << 30):.2f} GiB/s "
         f"({time.perf_counter() - t0:.0f}s)")
    return out


def _parse_config(s: str) -> tuple[str, int, int, int]:
    kind = "S"
    if s[:1] in ("B", "S"):
        kind, s = s[0], s[1:].lstrip(":")
    seg, st, it = map(int, s.split(","))
    return kind, seg, st, it


def _run_config_ladder() -> tuple[float, str]:
    # Primary metric: the cross-PVC batched program (shipped via the
    # mover-jax coalescer and VOLSYNC_BATCH_SEGMENTS) — measured r4:
    # ~7 ms fixed execution overhead + ~80 ms result round trip per
    # dispatch make bytes-per-dispatch, not kernel speed, the
    # first-order term. The first rung is the LARGEST shape with a
    # known-bounded compile: remote compile bypasses the local
    # persistent cache, compile time grows superlinearly with segment
    # size (64 MiB ~40 s, 256 MiB >9 min, measured r4), and compile
    # counts against the config deadline — bigger shapes belong to the
    # upsize probes, which can deadline without losing the number in
    # hand. The single-segment path is the fallback rung.
    # Three rungs, not four: worst case (every rung eating its full
    # 420 s deadline) must stay inside the measurement child's
    # 1740 s watchdog with headroom for the golden checks and the CPU
    # baseline — 3x420 + overhead fits, 4x420 could clip the last rung.
    configs = [("B", 64, 8, 6), ("B", 32, 8, 8), ("S", 32, 4, 4)]
    if env_bool("VOLSYNC_BENCH_CPU_FALLBACK"):
        # CPU-backend XLA scan is orders slower; tiny configs + the
        # per-config deadline still land an honest labeled number.
        configs = [("S", 8, 2, 1), ("S", 4, 1, 1), ("S", 2, 1, 1),
                   ("S", 1, 1, 1)]
    pinned_config = env_str("VOLSYNC_BENCH_CONFIG")
    pinned = bool(pinned_config)
    if pinned_config:
        configs = [_parse_config(pinned_config)]
    last_err: BaseException | None = None
    best: Optional[tuple[float, str]] = None
    for kind, seg_mib, streams, iters in configs:
        t0 = time.perf_counter()
        try:
            out = _try_config(kind, seg_mib, streams, iters)
            best = (out, f"{kind}{seg_mib}x{streams}x{iters}")
            break
        except AssertionError:
            raise  # golden-check failure is a correctness bug, not OOM
        except _Deadline as e:
            _log(f"bench: config deadline after "
                 f"{time.perf_counter() - t0:.0f}s — trying smaller")
            last_err = e
        except Exception as e:  # noqa: BLE001
            kind_e = _classify(e)
            _log(f"bench: config failed [{kind_e}] after "
                 f"{time.perf_counter() - t0:.0f}s: "
                 f"{type(e).__name__}: {str(e)[:300]}")
            if kind_e == "backend":
                # A smaller segment cannot fix a dead tunnel; round 3
                # burned 75 minutes learning this.
                raise _BackendDown(str(e)) from e
            if kind_e != "oom":
                raise
            last_err = e
    if best is None:
        raise last_err if last_err else RuntimeError("no bench configs")
    # Opportunistic upsizing: one real-hardware run per round, so while
    # budget clearly remains, probe bigger shapes and keep the max. A
    # failure here never loses the number already in hand.
    if not pinned and not env_bool("VOLSYNC_BENCH_CPU_FALLBACK"):
        kind, rest = best[1][0], best[1][1:]
        seg, streams, iters = map(int, rest.split("x"))
        for up in (
                # more bytes per dispatch first (the measured lever),
                (kind, seg * 2, streams, max(iters // 2, 1)),
                (kind, seg, streams * 2, max(iters // 2, 1)),
                # then the other program shape at the winning size
                ("S" if kind == "B" else "B", seg, streams, iters)):
            up_kind, up_seg, up_streams, up_iters = up
            if _budget_left() < 2 * CONFIG_DEADLINE_S:
                break
            if up_streams * (up_iters + 1) >= 255:
                continue  # salt space
            if (up_kind == "B"
                    and up_seg * (1 << 20) * up_streams >= 1 << 31):
                continue  # int32 gather index space (2 GiB batch cap)
            try:
                _log(f"bench: upsize probe {up_kind}{up_seg}x{up_streams}"
                     f"x{up_iters}")
                fn = (_try_batched_throughput if up_kind == "B"
                      else _try_device_throughput)
                out = _with_deadline(fn, up_seg, up_streams, up_iters)
                _log(f"bench: upsize ok -> {out / (1 << 30):.2f} GiB/s")
                if out > best[0]:
                    best = (out,
                            f"{up_kind}{up_seg}x{up_streams}x{up_iters}")
            except AssertionError as e:
                # The upsize shape FAILED its golden check: its number
                # is discarded (never emitted), the main config's
                # verified number stands — but this is a real kernel
                # correctness bug at that shape; flag it loudly.
                _log(f"bench: KERNEL BUG — golden check failed at "
                     f"{up_seg}x{up_streams}x{up_iters}: {e}; upsize "
                     f"result discarded, keeping verified {best[1]}")
            except _Deadline:
                _log("bench: upsize exceeded the config deadline — "
                     "keeping the measured number")
            except Exception as e:  # noqa: BLE001
                _log(f"bench: upsize failed [{_classify(e)}]: "
                     f"{str(e)[:200]}")
                if _classify(e) == "backend":
                    break  # keep the number we have; tunnel is dying
    return best


def device_throughput() -> tuple[float, str]:
    try:
        return _run_config_ladder()
    except AssertionError as e:
        if no_pallas():
            raise  # already on the XLA path: the math itself is wrong
        # A golden-check failure with Pallas enabled points at the
        # Mosaic kernels on this toolchain; the XLA scan path computes
        # identical digests by construction (golden-tested on CPU), so
        # retry once on it — a slower HONEST number beats no number,
        # and the stderr line flags the kernel bug for follow-up. The
        # retry runs a SHORTENED ladder (mid-size configs) so first
        # pass + retry stay inside the measurement child's timeout.
        _log(f"bench: golden check failed with Pallas enabled ({e}); "
             f"retrying on the XLA path (VOLSYNC_NO_PALLAS=1)")
        os.environ["VOLSYNC_NO_PALLAS"] = "1"
        if env_str("VOLSYNC_BENCH_CONFIG") is None:
            os.environ["VOLSYNC_BENCH_CONFIG"] = "64,8,6"
        import jax

        jax.clear_caches()  # cached executables still contain Pallas
        return _run_config_ladder()


def cpu_baseline(total_mib: int = 64) -> float:
    """The strongest plausible single-core implementation of the same
    work (the reference's unit of compute is one mover pod ~ one core):
    a numpy-vectorized gear candidate scan at aligned positions plus
    C-speed SHA-256 (hashlib, one call per ~avg-size chunk — no Python
    per-leaf loop, deliberately generous to the baseline)."""
    import hashlib

    from volsync_tpu.ops.gearcdc import DEFAULT_PARAMS

    p = DEFAULT_PARAMS
    n = total_mib * 1024 * 1024
    host = _make_data(n)
    t0 = time.perf_counter()
    _, cand = _host_gear_candidates(host, p)
    view = host.tobytes()
    pos = 0
    while pos < n:
        end = min(pos + p.avg_size, n)
        hashlib.sha256(view[pos:end]).digest()
        pos = end
    _ = cand
    dt = time.perf_counter() - t0
    return n / dt


class _HostSegmentHasher:
    """Fixed-grid host chunk+hash stand-in for the device stage, used by
    the pipeline bench: on a CPU backend the XLA sha256 path runs at
    ~4 MiB/s, which would drown the read/seal/upload overlap this bench
    exists to measure (on a TPU the device stage is sub-ms per segment
    and the same overlap applies). Conforms to stream_chunks' plain
    hasher protocol: process() -> [(start, length, digest)]."""

    def __init__(self, chunk_size: int = 1 << 20):
        self.chunk_size = chunk_size

    def process(self, buffer, *, eof: bool = True):
        import hashlib

        data = buffer.tobytes()
        end = (len(data) if eof
               else (len(data) // self.chunk_size) * self.chunk_size)
        out = []
        for pos in range(0, end, self.chunk_size):
            ln = min(self.chunk_size, end - pos)
            out.append((pos, ln,
                        hashlib.sha256(data[pos:pos + ln]).hexdigest()))
        return out


def _metric_value(name: str, labels: dict) -> float:
    """Read one sample from the global registry via the public text
    exposition (no private prometheus_client attribute access)."""
    from volsync_tpu.metrics import GLOBAL as M

    want = "{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())
                          ) + "}" if labels else ""
    for line in M.expose().decode().splitlines():
        if not line.startswith(name):
            continue
        head, _, val = line.rpartition(" ")
        if labels:
            lb = head[head.find("{"):]
            if sorted(lb.strip("{}").split(",")) != sorted(
                    want.strip("{}").split(",")):
                continue
        elif "{" in head:
            continue
        return float(val)
    return 0.0


def index_bench(entries: int = 1_000_000, queries: int = 200_000,
                batch: int = 4096, shards: Optional[int] = None) -> dict:
    """Metadata-plane microbench (``bench.py index``): batched
    vectorized dedup lookups vs the per-key scalar probe loop, and the
    sharded index + blocked-bloom prefilter vs the single flat table.

    Builds an index of ``entries`` random SHA-256-shaped keys, then
    measures (a) scalar ``lookup``/``in`` per-key rates, (b) batched
    ``lookup_many``/``contains_many`` rates in ``batch``-key slices for
    pure-hit, pure-miss, and mixed workloads, and (c) the sharded
    index's batched rates with prefilter skip/false-positive counts.
    The headline value is the batched-vs-scalar hit-lookup speedup.
    Host-side only — no jax, no device."""
    from volsync_tpu.repo.compactindex import CompactIndex
    from volsync_tpu.repo.shardedindex import ShardedBlobIndex

    rng = np.random.RandomState(11)
    raw = rng.bytes(32 * entries)
    ids = [raw[i * 32:(i + 1) * 32].hex() for i in range(entries)]
    raw_miss = rng.bytes(32 * queries)
    miss = [raw_miss[i * 32:(i + 1) * 32].hex() for i in range(queries)]
    hit_idx = rng.randint(0, entries, size=queries)
    hits = [ids[i] for i in hit_idx.tolist()]
    mixed = [h if i % 2 else m for i, (h, m) in
             enumerate(zip(hits, miss))]

    t0 = time.perf_counter()
    single = CompactIndex(capacity=entries)
    for i, h in enumerate(ids):
        single.insert(h, f"pack{i >> 12}", "data", i, 1024, 2048)
    build_single_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    sharded = ShardedBlobIndex(shards=shards, capacity=entries)
    for i, h in enumerate(ids):
        sharded.insert(h, f"pack{i >> 12}", "data", i, 1024, 2048)
    build_sharded_s = time.perf_counter() - t0

    nscalar = min(queries, 50_000)  # scalar loops are the slow side

    def rate(n, secs):
        return round(n / secs) if secs > 0 else 0

    def timed(fn):
        # One warmup pass first: the first touch of a ~66 MiB table
        # after build is page faults and cache fills, not probe cost,
        # and it would be billed to whichever workload ran first.
        fn()
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    def scalar_hits():
        for h in hits[:nscalar]:
            single.lookup(h)

    def scalar_misses():
        for m in miss[:nscalar]:
            m in single  # noqa: B015 — timing the membership probe

    scalar_hit_s = timed(scalar_hits)
    scalar_miss_s = timed(scalar_misses)

    def batched(index, keys, fn):
        def run():
            for i in range(0, len(keys), batch):
                fn(index, keys[i:i + batch])
        return timed(run)

    def lk(idx, ks):
        idx.lookup_many(ks)

    def ct(idx, ks):
        idx.contains_many(ks)

    batched_hit_s = batched(single, hits, lk)
    batched_miss_s = batched(single, miss, ct)
    batched_mixed_s = batched(single, mixed, ct)

    skip0 = _metric_value("volsync_index_prefilter_total",
                          {"outcome": "skip"})
    fp0 = _metric_value("volsync_index_prefilter_total",
                        {"outcome": "false_positive"})
    sh_hit_s = batched(sharded, hits, lk)
    sh_miss_s = batched(sharded, miss, ct)
    sh_mixed_s = batched(sharded, mixed, ct)
    # warmup+timed both ran: halve the counter deltas to report one pass
    skips = (_metric_value("volsync_index_prefilter_total",
                           {"outcome": "skip"}) - skip0) / 2
    fps = (_metric_value("volsync_index_prefilter_total",
                         {"outcome": "false_positive"}) - fp0) / 2

    scalar_rate = nscalar / scalar_hit_s if scalar_hit_s > 0 else 0.0
    batched_rate = queries / batched_hit_s if batched_hit_s > 0 else 0.0
    speedup = round(batched_rate / scalar_rate, 2) if scalar_rate else 0.0
    return {
        "metric": "index_batched_lookup_speedup",
        "value": speedup,
        "unit": "x",
        "entries": entries,
        "queries": queries,
        "batch": batch,
        "shards": sharded._nshards,
        "build": {
            "single_s": round(build_single_s, 3),
            "sharded_s": round(build_sharded_s, 3),
            "inserts_per_s": rate(entries, build_single_s),
        },
        "scalar": {
            "hit_lookup_per_s": rate(nscalar, scalar_hit_s),
            "miss_contains_per_s": rate(nscalar, scalar_miss_s),
        },
        "batched": {
            "hit_lookup_per_s": rate(queries, batched_hit_s),
            "miss_contains_per_s": rate(queries, batched_miss_s),
            "mixed_contains_per_s": rate(queries, batched_mixed_s),
        },
        "sharded_batched": {
            "hit_lookup_per_s": rate(queries, sh_hit_s),
            "miss_contains_per_s": rate(queries, sh_miss_s),
            "mixed_contains_per_s": rate(queries, sh_mixed_s),
            "prefilter_skips": int(skips),
            "prefilter_false_positives": int(fps),
            "prefilter_saturation": round(
                sharded.prefilter_saturation(), 4),
        },
        "index_mib": round(single.nbytes() / (1 << 20), 1),
        "provenance": bench_provenance(),
    }


def pipeline_bench(total_mib: int = 24, put_latency_s: float = 0.04,
                   segment_mib: int = 2,
                   fault_seed: Optional[int] = None) -> dict:
    """Serial-vs-pipelined backup data plane (``bench.py pipeline``).

    Streams a ``total_mib`` volume through stream_chunks ->
    Repository.add_blob -> flush twice — once with
    VOLSYNC_TPU_PIPELINE=0 semantics (inline seal, synchronous put) and
    once with the full pipeline (read-ahead thread, seal pool, bounded
    async upload window) — over a MemObjectStore wrapped in LatencyStore
    so every put costs ``put_latency_s`` like a real object store.
    Reports wall times, speedup, and the per-stage breakdown
    (read / device / seal / upload) from the obs span registry.

    Two measurement details matter on small hosts: a short pipelined
    warmup run is done first so thread-pool creation and module imports
    are not billed to the timed runs, and the interpreter switch
    interval is lowered for the duration of the bench — at the default
    5 ms a single-core box pays up to one full interval per cross-thread
    future/queue handoff, which swamps the IO latency the pipeline is
    hiding.

    ``fault_seed`` (``bench.py pipeline --faults SEED``) arms the
    deterministic fault-injection wrapper under the shared resilience
    layer — the reported number is then GOODPUT under the seeded fault
    schedule (VOLSYNC_FAULT_SPEC or the default transient+latency
    profile), not clean-path throughput.

    The serial run adds chunks one ``add_blob`` (one lock + one scalar
    probe) at a time; the pipelined run consumes per-segment batches
    through ``add_blobs`` (one lock + one vectorized dedup query per
    batch). ``dedup`` in the stage breakdown is the batched query time;
    ``dedup_compare`` re-times the same key set scalar-vs-batched on
    the finished repository."""
    from volsync_tpu.engine.chunker import stream_chunk_batches
    from volsync_tpu.objstore.store import LatencyStore, MemObjectStore
    from volsync_tpu.obs import (
        dump_trace,
        reset_copies,
        reset_spans,
        reset_trace,
        span_totals,
        trace_context,
    )
    from volsync_tpu.ops.gearcdc import GearParams
    from volsync_tpu.repo.repository import Repository

    total = total_mib << 20
    seg_size = segment_mib << 20
    data = _make_data(total, redundancy=0.0).tobytes()
    params = GearParams(min_size=256 * 1024, avg_size=512 * 1024,
                        max_size=1024 * 1024, seed=7, align=4096)

    def run(pipelined: bool, limit: int = 0):
        lat = LatencyStore(MemObjectStore(), put_latency=put_latency_s)
        if fault_seed is None:
            repo = Repository.init(lat)
        else:
            from volsync_tpu.objstore.faultstore import maybe_wrap
            from volsync_tpu.resilience import (
                CircuitBreaker,
                ResilientStore,
                RetryPolicy,
            )

            # init on the clean store (put_if_absent is single-attempt
            # by design), then run the data plane through the same
            # layering open_store builds: faults UNDER the retry layer.
            Repository.init(lat)
            store = ResilientStore(
                maybe_wrap(lat, seed=fault_seed),
                policy=RetryPolicy(site="bench.faults", max_attempts=10,
                                   base_delay=0.001, max_delay=0.01),
                breaker=CircuitBreaker("bench", threshold=10**9,
                                       reset_seconds=0.1))
            repo = Repository.open(store)
        repo.pipelined = pipelined
        repo.PACK_TARGET = 1024 * 1024
        end = limit or total
        pos = 0

        def reader(n):
            nonlocal pos
            piece = data[pos:min(pos + n, end)]
            pos += len(piece)
            return piece

        reset_spans()
        reset_trace()
        reset_copies()
        ids: list = []
        t0 = time.perf_counter()
        with trace_context(tenant="bench"):
            for chunks in stream_chunk_batches(
                    reader, params, segment_size=seg_size,
                    hasher=_HostSegmentHasher(),
                    readahead=(2 if pipelined else 0)):
                if pipelined:
                    repo.add_blobs(
                        "data",
                        [(digest, chunk) for chunk, digest in chunks])
                else:
                    for chunk, digest in chunks:
                        repo.add_blob("data", digest, chunk)
                ids.extend(digest for _, digest in chunks)
            repo.flush()
        elapsed = time.perf_counter() - t0
        injected = (len(repo.store.inner.injected)
                    if fault_seed is not None else 0)
        return elapsed, span_totals(), lat, injected, repo, ids

    prev_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    try:
        run(True, limit=4 << 20)  # warmup: pools, imports, first-call paths
        serial_s, serial_spans, _, _, _, _ = run(False)
        (pipe_s, pipe_spans, pipe_store, pipe_injected, pipe_repo,
         pipe_ids) = run(True)
        # snapshot the ledger before dedup_compare touches the repo —
        # legacy removed one full payload pass (monolithic pack-body
        # assembly), hence legacy_passes=1.0
        copies = _copy_report(total, "pipeline", legacy_passes=1.0)
    finally:
        sys.setswitchinterval(prev_switch)

    def stages(spans):
        return {name: round(spans.get(key, (0, 0.0))[1], 4)
                for name, key in (("read", "engine.read"),
                                  ("device", "engine.device"),
                                  ("dedup", "repo.dedup_query"),
                                  ("seal", "repo.seal"),
                                  ("upload", "repo.pack_upload"),
                                  ("upload_wait", "repo.upload_wait"))}

    def dedup_compare(repo, ids, rounds: int = 50):
        """Per-chunk locking (one repo-lock + scalar probe per key, the
        pre-batching dedup path) vs ONE has_blobs query per batch over
        the run's whole 50/50 hit/miss key set — the shape of a warm
        backup's unchanged-file check, which queries a file's entire
        content list at once."""
        rng = np.random.RandomState(5)
        absent = [rng.bytes(32).hex() for _ in range(len(ids))]
        keys = [k for pair in zip(ids, absent) for k in pair]
        repo.has_blobs(keys)  # warm both paths' caches
        t0 = time.perf_counter()
        for _ in range(rounds):
            for k in keys:
                repo.has_blob(k)
        scalar_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(rounds):
            repo.has_blobs(keys)
        batched_s = time.perf_counter() - t0
        n = rounds * len(keys)
        return {
            "keys_per_batch": len(keys),
            "scalar_us_per_key": round(scalar_s / n * 1e6, 3),
            "batched_us_per_key": round(batched_s / n * 1e6, 3),
            "speedup": (round(scalar_s / batched_s, 2)
                        if batched_s > 0 else 0.0),
        }

    result = {
        "metric": "pipeline_backup_speedup",
        "value": round(serial_s / pipe_s, 2),
        "unit": "x",
        "serial_s": round(serial_s, 3),
        "pipelined_s": round(pipe_s, 3),
        "throughput_mib_s": round(total_mib / pipe_s, 1),
        "segments": total_mib // segment_mib,
        "packs_uploaded": pipe_store.puts,
        "max_concurrent_puts": pipe_store.max_concurrent_puts,
        "put_latency_ms": round(put_latency_s * 1000, 1),
        "stages": stages(pipe_spans),
        "stages_serial": stages(serial_spans),
        "copy_ratio": copies["copy_ratio"],
        "copies": copies,
        "dedup_compare": dedup_compare(pipe_repo, pipe_ids),
        # ROADMAP item 1 follow-on: every bench JSON self-describes
        # where its time went. The flight recorder still holds the
        # pipelined (last) run; trace_file is null unless
        # VOLSYNC_TRACE_DUMP names a directory to export into.
        "provenance": bench_provenance(extra={"copies": copies, "trace": {
            "spans": {name: {"count": c, "seconds": round(s, 4)}
                      for name, (c, s) in sorted(pipe_spans.items())},
            "trace_file": dump_trace(trigger="bench_pipeline"),
        }}),
    }
    if fault_seed is not None:
        result["fault_seed"] = fault_seed
        result["faults_injected"] = pipe_injected
    return result


def restore_bench(total_mib: int = 24, get_latency_s: float = 0.04,
                  storm: int = 4, smoke: bool = False) -> dict:
    """Serial-vs-pipelined restore data plane (``bench.py restore``).

    Backs a synthetic tree into a MemObjectStore once, then restores it
    three ways through a LatencyStore where every GET costs
    ``get_latency_s`` like a real object store:

    - **serial**: the per-blob golden oracle (one ranged GET + host
      verify per blob, files in sequence);
    - **pipelined**: the pack-aware plane (engine/restorepipe.py) —
      whole-pack fetches through the PackCache, device-batched verify,
      positional writes;
    - **storm**: ``storm`` concurrent pipelined restores of the SAME
      snapshot sharing one PackCache (RestoreGroup) — the number that
      matters is pack fetches relative to a single restore (single-
      flight bound), reported as ``storm_fetch_ratio``.

    Same measurement hygiene as pipeline_bench: a warmup restore over
    a zero-latency store absorbs pool/JIT/first-call costs, and the
    interpreter switch interval is lowered for the timed runs."""
    import shutil
    import tempfile
    from pathlib import Path

    from volsync_tpu.engine import RestoreGroup, TreeBackup, TreeRestore
    from volsync_tpu.objstore.store import LatencyStore, MemObjectStore
    from volsync_tpu.obs import reset_copies, reset_spans, span_totals
    from volsync_tpu.repo.repository import Repository

    total = total_mib << 20
    file_mib = 2
    nfiles = max(1, total_mib // file_mib)
    data = _make_data(total, redundancy=0.0).tobytes()

    workdir = Path(tempfile.mkdtemp(prefix="volsync-restore-bench-"))
    try:
        src = workdir / "src"
        src.mkdir()
        step = len(data) // nfiles
        for i in range(nfiles):
            (src / f"f{i:03d}.bin").write_bytes(
                data[i * step:(i + 1) * step])

        mem = MemObjectStore()
        # restic-scale chunks (≈256 KiB) against 1 MiB packs: the
        # serial oracle pays one ranged GET per CHUNK, the pipelined
        # plane one whole GET per PACK — the batching this bench exists
        # to price. The default 1 MiB-avg chunker would make blobs ≈
        # packs and hide the difference.
        repo = Repository.init(mem, chunker={
            "min_size": 128 * 1024, "avg_size": 256 * 1024,
            "max_size": 512 * 1024, "seed": 7, "align": 4096})
        repo.PACK_TARGET = 1024 * 1024
        snap, _ = TreeBackup(repo, workers=1).run(src)
        assert snap
        npacks = len(list(mem.list("data/")))

        def run(pipelined: bool, latency: float, dest: Path,
                workers=None):
            lat = LatencyStore(mem, get_latency=latency)
            r = Repository.open(lat)
            reset_spans()
            reset_copies()
            t0 = time.perf_counter()
            with r.lock(exclusive=False):
                r.load_index()
                snap_id, manifest = r.select_snapshot()
                TreeRestore(r, workers=workers,
                            pipeline=pipelined)._run_locked(
                    snap_id, manifest, dest)
            return time.perf_counter() - t0, span_totals(), lat

        def run_storm(latency: float):
            lat = LatencyStore(mem, get_latency=latency)
            group = RestoreGroup()
            for i in range(storm):
                group.add(Repository.open(lat),
                          workdir / f"storm{i}")
            t0 = time.perf_counter()
            group.run()
            return time.perf_counter() - t0, group.stats()[0], lat

        prev_switch = sys.getswitchinterval()
        sys.setswitchinterval(0.0005)
        try:
            run(True, 0.0, workdir / "warmup")
            # the golden oracle really is serial: one ranged GET per
            # blob, one file at a time (workers=1); the file-concurrent
            # variant (default worker pool) is reported alongside
            serial_s, serial_spans, _ = run(False, get_latency_s,
                                            workdir / "serial",
                                            workers=1)
            serial_conc_s, _, _ = run(False, get_latency_s,
                                      workdir / "serial-conc")
            pipe_s, pipe_spans, pipe_lat = run(True, get_latency_s,
                                               workdir / "pipe")
            # ledger snapshot before the storm muddies attribution —
            # legacy sliced every segment out of a bytes pack body
            # (one full payload pass), hence legacy_passes=1.0
            copies = _copy_report(total, "restore", legacy_passes=1.0)
            storm_s, cache_stats, storm_lat = run_storm(get_latency_s)
        finally:
            sys.setswitchinterval(prev_switch)

        def stages(spans):
            return {name: round(spans.get(key, (0, 0.0))[1], 4)
                    for name, key in (("plan", "restore.plan"),
                                      ("fetch", "restore.fetch"),
                                      ("verify", "restore.verify"),
                                      ("write", "restore.write"))}

        demand = cache_stats["hits"] + cache_stats["misses"]
        return {
            "metric": "restore_pipeline_speedup",
            "value": round(serial_s / pipe_s, 2),
            "unit": "x",
            "serial_s": round(serial_s, 3),
            "serial_concurrent_s": round(serial_conc_s, 3),
            "pipelined_s": round(pipe_s, 3),
            "throughput_mib_s": round(total_mib / pipe_s, 1),
            "gib_s": round(total_mib / 1024 / pipe_s, 3),
            "get_latency_ms": round(get_latency_s * 1000, 1),
            "packs": npacks,
            "single_pack_fetches": pipe_lat.pack_fetches,
            "storm": {
                "restores": storm,
                "elapsed_s": round(storm_s, 3),
                "pack_fetches": storm_lat.pack_fetches,
                # single-flight bound: a storm of N restores should
                # cost about the SAME wire fetches as one restore
                "storm_fetch_ratio": round(
                    storm_lat.pack_fetches
                    / max(1, pipe_lat.pack_fetches), 2),
                "cache_hit_ratio": round(
                    cache_stats["hits"] / max(1, demand), 3),
                "cache": cache_stats,
            },
            "stages": stages(pipe_spans),
            "stages_serial": stages(serial_spans),
            "copy_ratio": copies["copy_ratio"],
            "copies": copies,
            "smoke": smoke,
            "provenance": bench_provenance(extra={
                "copies": copies,
                "restore": {"total_mib": total_mib, "files": nfiles}}),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def copies_smoke() -> dict:
    """Copy-ledger contract gate (``bench.py copies-smoke``, wired into
    scripts/static_check.sh via ``make copies-smoke``).

    Runs the backup and restore data planes at smoke scale and asserts
    the zero-copy contract on both artifacts:

    - every ledgered site is in ``obs.SANCTIONED_SITES`` — a new
      ``record_copy`` call must also amend the canonical set;
    - the measured ``copy_ratio`` stays at or under the committed
      ``COPY_RATIO_MAX`` threshold stamped into the artifact — a new
      unledgered full-payload copy shows up here as a ratio jump;
    - the artifact carries the copies block (``copy_bytes_by_site``,
      ``copy_ratio``, the threshold) in both the result and its
      provenance, so the contract is self-describing.

    Exits nonzero on any violation."""
    from volsync_tpu.obs import SANCTIONED_SITES

    pipe = pipeline_bench(total_mib=8, put_latency_s=0.005)
    rest = restore_bench(total_mib=6, get_latency_s=0.005, storm=2,
                         smoke=True)
    failures: list = []
    for kind, res in (("pipeline", pipe), ("restore", rest)):
        block = res.get("copies") or {}
        if not block or "copy_bytes_by_site" not in block:
            failures.append(f"{kind}: artifact missing copies block")
            continue
        if res.get("copy_ratio") != block["copy_ratio"]:
            failures.append(f"{kind}: top-level copy_ratio missing or "
                            f"inconsistent with copies block")
        if block != (res.get("provenance", {}).get("copies")):
            failures.append(f"{kind}: provenance missing copies block")
        unknown = sorted(set(block["copy_bytes_by_site"])
                         - SANCTIONED_SITES)
        if unknown:
            failures.append(f"{kind}: unsanctioned copy sites {unknown}")
        if block["copy_ratio"] > block["copy_ratio_max"]:
            failures.append(
                f"{kind}: copy_ratio {block['copy_ratio']} exceeds the "
                f"committed max {block['copy_ratio_max']}")
    return {
        "metric": "copy_ledger_smoke",
        "value": len(failures),
        "unit": "violations",
        "ok": not failures,
        "failures": failures,
        "pipeline": {"copy_ratio": pipe.get("copy_ratio"),
                     "copies": pipe.get("copies"),
                     "throughput_mib_s": pipe.get("throughput_mib_s")},
        "restore": {"copy_ratio": rest.get("copy_ratio"),
                    "copies": rest.get("copies"),
                    "throughput_mib_s": rest.get("throughput_mib_s")},
        "provenance": bench_provenance(),
    }


def syncplan_bench(smoke: bool = True) -> dict:
    """Protocol-planner replay: three canned workloads scored against a
    measured oracle (``bench.py syncplan``).

    Each workload builds real trees, measures the TRUE wire cost of
    every protocol with the real engines — DELTA through the batched
    device scan (engine/deltasync.delta_scan_batch), CDC_DEDUP through
    two real TreeBackup runs against one repository (the second run's
    dedup stats are the measured hit ratio) — then replays the
    workload's history into a SyncStatsBook and asks the planner to
    choose. ``regret_ratio`` is the true cost of the chosen protocol
    over the true cost of the cheapest (1.0 = planner matched the
    oracle); the gate is <= 1.05 per workload, asserted here so the
    smoke target fails loudly on a cost-model regression. All transfer
    costs are priced against one canned reference link so the replay is
    deterministic; device terms use the model's own conservative
    constants.
    """
    import tempfile
    from pathlib import Path

    from volsync_tpu.engine import deltasync, protoplan, syncstats
    from volsync_tpu.engine.backup import TreeBackup
    from volsync_tpu.metrics import GLOBAL as METRICS
    from volsync_tpu.objstore import MemObjectStore
    from volsync_tpu.repo.repository import Repository

    LINK_BPS = 100.0 * (1 << 20)   # canned reference link: 100 MiB/s
    LINK_LAT = 0.010               # 10 ms per round trip
    # Sized so the three workloads land in three different optimal
    # regimes on the reference link: files big enough that wire bytes
    # beat round trips when churn/dedup allow it.
    n_files = 4 if smoke else 8
    fsize = (4 << 20) if smoke else (8 << 20)
    rng = np.random.RandomState(0x5EED)
    # Small chunker so even smoke-sized files span many CDC chunks.
    chunker = {"min_size": 16 * 1024, "avg_size": 64 * 1024,
               "max_size": 256 * 1024, "seed": 7}
    DEV_BPS = {protoplan.FULL_COPY: 0.0,
               protoplan.DELTA: protoplan.DEVICE_DELTA_BPS,
               protoplan.CDC_DEDUP: protoplan.DEVICE_CDC_BPS}
    RT = {protoplan.FULL_COPY: 1, protoplan.DELTA: 2,
          protoplan.CDC_DEDUP: 2}

    def true_cost(proto: str, wire: float, nbytes: int) -> float:
        dev = nbytes / DEV_BPS[proto] if DEV_BPS[proto] else 0.0
        return (wire / LINK_BPS + n_files * RT[proto] * LINK_LAT + dev)

    def measure_cdc(base_files, new_files):
        """Measured CDC wire bytes for syncing ``new_files`` into a
        repository that already holds ``base_files``."""
        with tempfile.TemporaryDirectory() as td:
            root = Path(td)
            repo = Repository.init(MemObjectStore(), chunker=chunker)
            for sub, files in (("base", base_files), ("new", new_files)):
                d = root / sub
                d.mkdir()
                for i, data in enumerate(files):
                    (d / f"f{i}.bin").write_bytes(data)
            if base_files:
                TreeBackup(repo).run(root / "base")
            _snap, stats = TreeBackup(repo).run(root / "new")
            blobs = stats.blobs_new + stats.blobs_dedup
            wire = (stats.bytes_scanned - stats.bytes_dedup
                    + protoplan.CDC_CHUNK_META_BYTES * blobs)
            return wire, stats.blobs_dedup, blobs

    def measure_delta(base_files, new_files):
        """Measured DELTA wire bytes via the batched device scan."""
        items, sig_cost = [], 0
        for old, new in zip(base_files, new_files):
            sig = deltasync.build_file_signature(
                old, deltasync.pick_block_len(max(len(old), len(new))))
            geo = deltasync.signature_geometry(len(old), sig.block_len)
            sig_cost += (geo.sig_bytes
                         + protoplan.DELTA_OP_OVERHEAD_PER_BLOCK
                         * geo.n_blocks)
            items.append((new, sig))
        literal = 0
        ratios = []
        for (new, sig), ops in zip(items,
                                   deltasync.delta_scan_batch(items)):
            lit = deltasync.delta_stats(ops, sig.block_len)["literal_bytes"]
            literal += lit
            ratios.append((lit, len(new)))
        return sig_cost + literal, ratios

    def replay_and_decide(book, *, basis_exists: bool):
        """One planner decision per (homogeneous) file; every file must
        agree, so the workload verdict is the per-file verdict."""
        chosen = {
            protoplan.decide(fsize, book.snapshot(),
                             basis_exists=basis_exists).protocol
            for _ in range(n_files)}
        assert len(chosen) == 1, f"unstable decisions: {chosen}"
        return chosen.pop()

    workloads: dict = {}

    # -- workload 1: cold full copy (fresh dest, zero history) ---------
    new = [rng.bytes(fsize) for _ in range(n_files)]
    total = n_files * fsize
    cdc_wire, _hits, _blobs = measure_cdc([], new)
    costs = {protoplan.FULL_COPY: true_cost("full", total, total),
             protoplan.CDC_DEDUP: true_cost("cdc", cdc_wire, total)}
    book = syncstats.SyncStatsBook()
    workloads["cold_full"] = (costs,
                              replay_and_decide(book, basis_exists=False))

    # -- workload 2: 1%-churn incremental (delta territory) ------------
    base = [rng.bytes(fsize) for _ in range(n_files)]
    new = []
    for data in base:
        buf = bytearray(data)
        for _ in range(4):  # ~1% of bytes across 4 scattered spots
            at = int(rng.randint(0, fsize - fsize // 400))
            buf[at:at + fsize // 400] = rng.bytes(fsize // 400)
        new.append(bytes(buf))
    delta_wire, ratios = measure_delta(base, new)
    cdc_wire, hits, blobs = measure_cdc(base, new)
    costs = {protoplan.FULL_COPY: true_cost("full", total, total),
             protoplan.DELTA: true_cost("delta", delta_wire, total),
             protoplan.CDC_DEDUP: true_cost("cdc", cdc_wire, total)}
    book = syncstats.SyncStatsBook()
    for lit, nbytes in ratios:        # replay: prior delta runs
        book.observe_delta(lit, nbytes)
    book.observe_dedup(hits, blobs)   # ... and the measured dedup rate
    book.observe_link(total, total / LINK_BPS)
    book.observe_rtt(LINK_LAT)
    workloads["churn_1pct"] = (costs,
                               replay_and_decide(book, basis_exists=True))

    # -- workload 3: high-dedup re-ingest (cdc territory) --------------
    # same content under new names: no per-file basis for delta, but
    # nearly every chunk already lives in the repository
    new = list(base)
    cdc_wire, hits, blobs = measure_cdc(base, new)
    costs = {protoplan.FULL_COPY: true_cost("full", total, total),
             protoplan.CDC_DEDUP: true_cost("cdc", cdc_wire, total)}
    book = syncstats.SyncStatsBook()
    book.observe_dedup(hits, blobs)
    book.observe_link(total, total / LINK_BPS)
    book.observe_rtt(LINK_LAT)
    workloads["high_dedup"] = (costs,
                               replay_and_decide(book, basis_exists=False))

    out: dict = {"bench": "syncplan", "smoke": smoke,
                 "link": {"bandwidth_bps": LINK_BPS, "latency_s": LINK_LAT},
                 "files": n_files, "file_bytes": fsize, "workloads": {}}
    worst = 0.0
    for name, (costs, chosen) in workloads.items():
        oracle = min(costs, key=costs.get)
        regret = costs[chosen] / costs[oracle]
        worst = max(worst, regret)
        out["workloads"][name] = {
            "chosen": chosen, "oracle": oracle,
            "regret_ratio": round(regret, 4),
            "cost_s": {p: round(c, 6) for p, c in costs.items()},
        }
        assert regret <= 1.05, (
            f"workload {name}: planner chose {chosen} "
            f"(regret {regret:.3f}) over oracle {oracle}")
    out["regret_ratio_max"] = round(worst, 4)
    METRICS.plan_regret.set(worst)
    out["provenance"] = bench_provenance(extra={
        "syncplan": {"files": n_files, "file_bytes": fsize}})
    return out


def ec_bench(smoke: bool = True, k: int = 4, m: int = 2) -> dict:
    """Erasure-coding data plane (``bench.py ec``, smoke wired into
    scripts/static_check.sh via ``make ec-bench-smoke``).

    Four numbers, one artifact (docs/robustness.md, "Erasure coding &
    online repack"):

    - **encode / decode throughput** — the batched GF(2^8) device
      matmul (ops/rs.py page grid) vs the pure-NumPy golden oracle,
      GiB/s over the same payload;
    - **reconstruct latency vs mirror fetch** — the read-path cost of
      losing m shards (any-k reconstruction + content-addressed proof)
      against the 2x-mirror alternative it replaces (fetch + sha256
      proof), both from a Mem store;
    - **measured storage overhead** — stored shard bytes (headers and
      page padding included) over the logical pack bytes, asserted at
      or under the committed 1.5x the scheme promises.
    """
    from volsync_tpu.ops import rs
    from volsync_tpu.repo import erasure

    total = (8 if smoke else 64) * (1 << 20) + 12_345  # off page grid
    iters = 3 if smoke else 8
    rng = np.random.RandomState(4242)
    body = rng.bytes(total)
    shard_len = (total + k - 1) // k
    flat = np.zeros(k * shard_len, dtype=np.uint8)
    flat[:total] = np.frombuffer(body, dtype=np.uint8)
    data2d = flat.reshape(k, shard_len)
    shard_bufs = [data2d[i].tobytes() for i in range(k)]

    def timed(fn, n=iters):
        fn()  # warm (device path: compile + transfer once)
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n

    grid, _L = rs.rs_pack_host(shard_bufs)
    enc_dev_s = timed(
        lambda: np.asarray(rs.rs_encode_device(grid, m)))
    enc_np_s = timed(lambda: rs.rs_encode_np(data2d, m), n=1)
    parity = np.asarray(rs.rs_encode_np(data2d, m))

    # decode with the first m DATA shards lost — the worst case: every
    # recovered row pays real field math, no identity passthrough
    have = {i: shard_bufs[i] for i in range(m, k)}
    have.update({k + i: parity[i].tobytes() for i in range(m)})
    have_np = {i: np.frombuffer(b, dtype=np.uint8)
               for i, b in have.items()}
    dec_dev_s = timed(
        lambda: rs.rs_reconstruct_device(have, k, m, shard_len))
    dec_np_s = timed(lambda: rs.rs_reconstruct_np(have_np, k, m), n=1)
    assert (rs.rs_reconstruct_np(have_np, k, m).reshape(-1)[:total]
            .tobytes() == body), "oracle decode mismatch"

    # read-path latency: any-k reconstruction vs mirror fetch, both
    # ending in the same content-addressed sha256 proof
    import hashlib

    pack_id = hashlib.sha256(body).hexdigest()
    shards = erasure.encode_pack_shards([body], k, m)
    stored = sum(len(s) for s in shards)
    surviving = {i: shards[i] for i in range(m, k + m)}

    def reconstruct():
        out = erasure.reconstruct_verified(surviving, pack_id)
        assert out is not None

    def mirror_fetch():
        assert hashlib.sha256(body).hexdigest() == pack_id

    rec_s = timed(reconstruct)
    mir_s = timed(mirror_fetch)

    gib = total / (1 << 30)
    overhead = stored / total
    result = {
        "metric": "ec_encode_throughput",
        "value": round(gib / enc_dev_s, 3),
        "unit": "GiB/s",
        "scheme": f"{k}+{m}",
        "payload_bytes": total,
        "encode": {
            "device_gib_s": round(gib / enc_dev_s, 3),
            "numpy_gib_s": round(gib / enc_np_s, 3),
            "speedup": round(enc_np_s / enc_dev_s, 1),
        },
        "decode": {
            "device_gib_s": round(gib / dec_dev_s, 3),
            "numpy_gib_s": round(gib / dec_np_s, 3),
            "speedup": round(dec_np_s / dec_dev_s, 1),
        },
        "reconstruct_vs_mirror": {
            "reconstruct_ms": round(rec_s * 1e3, 2),
            "mirror_fetch_ms": round(mir_s * 1e3, 2),
            "slowdown": round(rec_s / max(mir_s, 1e-9), 1),
        },
        "storage_overhead": {
            "measured": round(overhead, 4),
            "theoretical": erasure.storage_overhead(k, m),
            "mirror_alternative": 2.0,
        },
        "smoke": smoke,
        "provenance": bench_provenance(
            extra={"ec": {"k": k, "m": m, "iters": iters}}),
    }
    assert round(overhead, 3) <= 1.5, (
        f"measured EC overhead {overhead} exceeds the 1.5x contract")
    return result


def _pipeline_child(timeout_s: int = 180):
    """Run ``bench.py pipeline`` in a killable CPU-pinned subprocess and
    parse its JSON line; None on any failure (the main metric must
    never be lost to the stage-breakdown extra)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("VOLSYNC_BENCH_INNER", None)
    try:
        r = subprocess.run([sys.executable, __file__, "pipeline"],
                           timeout=timeout_s, capture_output=True,
                           text=True, env=env)
    except subprocess.TimeoutExpired:
        return None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def _inner_main():
    """Measure in THIS process. The parent decided the backend
    (VOLSYNC_BENCH_CPU_FALLBACK selects the CPU path); any failure —
    including a _BackendDown mid-run — simply exits nonzero and the
    parent applies the next fallback. The inner watchdog still emits a
    completed result if the interpreter wedges on the way out."""
    global _BEST
    threading.Thread(target=_watchdog, name="bench-watchdog",
                     daemon=True).start()
    backend = "default"
    if env_bool("VOLSYNC_BENCH_CPU_FALLBACK"):
        _force_cpu_backend()
        backend = "cpu-fallback"
    dev, config = device_throughput()

    import jax

    from volsync_tpu.ops import sha256 as _sha

    if backend == "default":
        backend = jax.default_backend()
    cpu = cpu_baseline()
    gib = dev / (1 << 30)
    result = {
        "metric": "backup_path_throughput_single_chip",
        "value": round(gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev / cpu, 2),
        "backend": backend,
        "path": "pallas" if _sha.use_pallas_leaves() else "xla",
        "config": config,
        "provenance": bench_provenance(),
    }
    with _BEST_LOCK:
        _BEST = result
    _emit(result)


def _run_measurement_child(extra_env: dict, timeout_s: int) -> Optional[dict]:
    """Run the measurement in a KILLABLE subprocess. SIGALRM cannot
    interrupt a C-blocked device call (a grpc upload wedging mid-run
    would ride out every in-process deadline), so the only hang-proof
    boundary is a process the parent can kill."""
    # The child's own watchdog must fire BEFORE the parent kill so a
    # completed-but-wedged measurement still emits its result; and a
    # result printed before a timeout kill is recovered from the
    # exception's captured stdout.
    env = dict(os.environ, VOLSYNC_BENCH_INNER="1",
               VOLSYNC_BENCH_BUDGET_S=str(max(timeout_s - 60, 60)),
               **extra_env)

    def parse(stdout) -> Optional[dict]:
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        return None

    try:
        r = subprocess.run([sys.executable, __file__], timeout=timeout_s,
                           capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired as e:
        out = parse(e.stdout)
        _log(f"bench: measurement subprocess exceeded {timeout_s}s — "
             f"killed (salvaged result: {out is not None})")
        return out
    tail = (r.stderr or "").strip()[-600:]
    if tail:
        _log(f"bench: child stderr tail:\n{tail}")
    if r.returncode == 0 and r.stdout.strip():
        out = parse(r.stdout)
        if out is None:
            _log(f"bench: child stdout unparsable: {r.stdout[-200:]!r}")
        return out
    _log(f"bench: measurement subprocess rc={r.returncode}")
    return None


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "pipeline":
        # Standalone stage-breakdown mode; host-side only, so pin the
        # backend to CPU before anything imports jax. ``--faults SEED``
        # arms the deterministic fault-injection wrapper so the number
        # is goodput under a seeded fault schedule.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        fault_seed = None
        if "--faults" in sys.argv[2:]:
            i = sys.argv.index("--faults")
            try:
                fault_seed = int(sys.argv[i + 1])
            except (IndexError, ValueError):
                print("usage: bench.py pipeline [--faults SEED]",
                      file=sys.stderr)
                return 2
        _emit(pipeline_bench(fault_seed=fault_seed))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "restore":
        # Restore data plane: serial vs pipelined vs storm; host-side
        # (the verify kernel runs on the CPU backend).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        smoke = "--smoke" in sys.argv[2:]
        storm = 4
        if "--storm" in sys.argv[2:]:
            i = sys.argv.index("--storm")
            try:
                storm = int(sys.argv[i + 1])
            except (IndexError, ValueError):
                print("usage: bench.py restore [--smoke] [--storm N]",
                      file=sys.stderr)
                return 2
        _emit(restore_bench(total_mib=6 if smoke else 24,
                            storm=storm, smoke=smoke))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "copies-smoke":
        # Zero-copy contract gate: both data planes at smoke scale,
        # site sanction + copy_ratio threshold asserted; host-side.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        res = copies_smoke()
        _emit(res)
        return 0 if res["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "ec":
        # Erasure-coding data plane: device vs NumPy GF(2^8) kernels,
        # reconstruct-vs-mirror latency, measured storage overhead;
        # host-side (the RS matmul runs on the CPU backend).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _emit(ec_bench(smoke="--smoke" in sys.argv[2:]))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "syncplan":
        # Protocol-planner replay: host + CPU device kernels only.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _emit(syncplan_bench(smoke="--smoke" in sys.argv[2:]))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "index":
        # Metadata-plane microbench; host-side only (numpy, no device).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        kw: dict = {}
        argv = sys.argv[2:]
        spec = {"--entries": "entries", "--queries": "queries",
                "--batch": "batch", "--shards": "shards"}
        i = 0
        while i < len(argv):
            name = spec.get(argv[i])
            try:
                kw[name] = int(argv[i + 1])
            except (TypeError, IndexError, ValueError):
                print("usage: bench.py index [--entries N] [--queries N]"
                      " [--batch N] [--shards N]", file=sys.stderr)
                return 2
            i += 2
        _emit(index_bench(**kw))
        return 0
    if env_bool("VOLSYNC_BENCH_INNER"):
        return _inner_main()
    threading.Thread(target=_watchdog, name="bench-watchdog",
                     daemon=True).start()

    if not env_bool("VOLSYNC_BENCH_CPU_FALLBACK"):
        probed = _probe_backend()
        if probed is None:
            probed = _recover_backend()
        if probed is not None and probed != "cpu":
            # Recovery may have spent real budget: the measurement
            # child gets what remains minus the CPU-fallback reserve,
            # so a late recovery still lands SOME accelerator number.
            measure_s = int(min(MEASURE_TIMEOUT_S,
                                _budget_left() - CPU_MEASURE_TIMEOUT_S
                                - 120))
            if measure_s >= 300:
                out = _run_measurement_child({}, measure_s)
                if out is not None:
                    if _budget_left() > 300:
                        pipe = _pipeline_child()
                        if pipe is not None:
                            out["pipeline"] = pipe
                    _emit(out)
                    return 0
                _log("bench: device measurement failed — CPU-backend "
                     "fallback")
            else:
                _log(f"bench: only {measure_s}s left for a device "
                     f"measurement — CPU-backend fallback")
        else:
            _log(f"bench: accelerator unavailable (probe={probed}) — "
                 f"CPU-backend fallback")

    # Terminal fallback: CPU backend, tiny configs, clearly labeled —
    # the driver records an honest number instead of rc=124 and nothing.
    out = _run_measurement_child({"VOLSYNC_BENCH_CPU_FALLBACK": "1"},
                                 CPU_MEASURE_TIMEOUT_S)
    if out is not None:
        if _budget_left() > 300:
            pipe = _pipeline_child()
            if pipe is not None:
                out["pipeline"] = pipe
        out["backend"] = "cpu-fallback"
        out["note"] = ("TPU backend unreachable at bench time (see "
                       "docs/performance.md: single-tenant tunnel "
                       "session leak); this is the labeled CPU-backend "
                       "fallback, not an accelerator number. The last "
                       "builder-run LIVE-chip measurement with full "
                       "provenance is the newest BENCH_SELF_r*.json")
        _emit(out)
        return 0
    _log("bench: every measurement path failed")
    raise SystemExit(70)


if __name__ == "__main__":
    # os._exit everywhere: a wedged device call on a pool thread would
    # otherwise hang the interpreter's atexit thread-join forever.
    try:
        rc = main() or 0
    except SystemExit as e:
        rc = int(e.code or 0)
    except BaseException as e:  # noqa: BLE001 — fast, visible failure
        _log(f"bench: fatal: {type(e).__name__}: {str(e)[:400]}")
        rc = 1
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
