"""Driver benchmark: the SHIPPED backup data path on one TPU chip.

Measures ``DeviceChunkHasher.process_device`` — exactly what TreeBackup /
stream_chunks run per segment: aligned gear-CDC candidate compaction, the
host FastCDC boundary walk, strided Merkle leaf SHA-256 + gather-path
tail leaves, and host-side root assembly. This is the restic-engine
replacement (SURVEY.md §2.2 #25) on its real code path, not a kernel
microbenchmark.

Data is device-resident and salted per iteration (the serving tunnel
memoizes executions with identical args and its host->device link is not
representative of a TPU VM's DMA path, so upload is excluded — the same
basis as the CPU number, which also reads from RAM).

The CPU baseline is the identical computation on one core the way the
reference's mover pod would do it: gear-CDC scan + per-chunk blob ids via
hashlib (repo/blobid.py host path).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _make_data(total: int, redundancy: float = 0.5) -> np.ndarray:
    """BASELINE.json configs[4]-style synthetic volume: ``redundancy`` of
    the stream is a repeated region (dedup finds it; boundaries/digests
    are computed for every byte either way)."""
    rng = np.random.RandomState(7)
    uniq = rng.randint(0, 256, size=(int(total * (1 - redundancy)),),
                       dtype=np.uint8)
    rep = rng.randint(0, 256, size=(total - uniq.shape[0],), dtype=np.uint8)
    return np.concatenate([uniq, rep])


def device_throughput(total_mib: int = 64, iters: int = 4,
                      streams: int = 3) -> float:
    import jax
    import jax.numpy as jnp

    from volsync_tpu.engine.chunker import DeviceChunkHasher
    from volsync_tpu.ops.gearcdc import (
        DEFAULT_PARAMS,
        cdc_candidates_aligned_packed,
    )
    from volsync_tpu.ops.sha256 import sha256_leaves_device

    n = total_mib * 1024 * 1024
    p = DEFAULT_PARAMS
    data = jnp.asarray(_make_data(n))
    jax.block_until_ready(data)

    # Salting is fused INTO each device stage (data ^ s traces through
    # the very same library kernels the shipped path dispatches), so each
    # iteration hashes distinct content without a data-sized transfer —
    # the tunnel memoizes identical executions and would otherwise fake
    # the timing. Host walk, leaf assignment, and root assembly run the
    # unmodified DeviceChunkHasher code.
    # data is an explicit argument (NOT a closure capture: captured
    # arrays embed as HLO constants and blow the remote-compile payload).
    cand_jit = jax.jit(
        lambda d, s, cap: cdc_candidates_aligned_packed(
            d ^ s, seed=p.seed, mask_s=p.mask_s, mask_l=p.mask_l,
            align=p.align, max_candidates=cap, valid_len=n),
        static_argnames=("cap",))
    leaf_jit = jax.jit(
        lambda d, s, rows, ts, tl: sha256_leaves_device(d ^ s, rows, ts, tl),
    )

    def make_hasher(base_salt: int) -> DeviceChunkHasher:
        """The shipped hasher with the salt composed into its two device
        dispatches via the override hooks — retry loops, packed-array
        decoding, leaf planning, and root assembly are the unmodified
        library code."""
        h = DeviceChunkHasher(p)
        h.salt = jnp.uint8(base_salt)
        h.cand_device_fn = lambda dev, cap: cand_jit(data, h.salt, cap)
        h.leaf_device_fn = \
            lambda dev, rows, ts, tl, leaf_len=4096: leaf_jit(
                data, h.salt, rows, ts, tl)
        return h

    def run_stream(base_salt: int) -> int:
        """One CR's backup loop: double-buffered like stream_chunks —
        segment i's digest fetch happens only after segment i+1's device
        work is dispatched."""
        h = make_hasher(base_salt)
        emitted = 0
        token = h.begin_device(data, n)
        for i in range(1, iters):
            h.salt = jnp.uint8(base_salt + i)
            nxt = h.begin_device(data, n)
            emitted += len(token.finish())
            token = nxt
        emitted += len(token.finish())
        return emitted

    make_hasher(255).begin_device(data, n).finish()  # warm all shapes
    # ``streams`` concurrent relationships on one chip (BASELINE
    # configs[4]): the manager runs concurrent movers, whose result
    # round-trips overlap while the device serializes their kernels.
    from concurrent.futures import ThreadPoolExecutor

    t0 = time.perf_counter()
    with ThreadPoolExecutor(streams) as pool:
        emitted = sum(pool.map(run_stream,
                               [s * 100 for s in range(1, streams + 1)]))
    dt = time.perf_counter() - t0
    assert emitted > 0
    return streams * iters * n / dt  # bytes/s, full shipped path


def cpu_baseline(total_mib: int = 64) -> float:
    """The strongest plausible single-core implementation of the same
    work (the reference's unit of compute is one mover pod ~ one core):
    a numpy-vectorized gear candidate scan at aligned positions plus
    C-speed SHA-256 (hashlib, one call per ~avg-size chunk — no Python
    per-leaf loop, deliberately generous to the baseline)."""
    import hashlib

    from volsync_tpu.ops.gearcdc import DEFAULT_PARAMS

    p = DEFAULT_PARAMS
    n = total_mib * 1024 * 1024
    host = _make_data(n)
    table = p.table
    t0 = time.perf_counter()
    rows = host[: n // p.align * p.align].reshape(-1, p.align)[:, -32:]
    g = table[rows].astype(np.uint64)
    shifts = np.arange(31, -1, -1, dtype=np.uint64)
    h = (g << shifts[None, :]).sum(axis=1).astype(np.uint32)
    cand = np.nonzero((h & np.uint32(p.mask_l)) == 0)[0]
    view = host.tobytes()
    pos = 0
    while pos < n:
        end = min(pos + p.avg_size, n)
        hashlib.sha256(view[pos:end]).digest()
        pos = end
    _ = cand
    dt = time.perf_counter() - t0
    return n / dt


def main():
    dev = device_throughput()
    cpu = cpu_baseline()
    gib = dev / (1 << 30)
    print(json.dumps({
        "metric": "backup_path_throughput_single_chip",
        "value": round(gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev / cpu, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
