"""``volsync session`` — supervised accelerator session verbs.

Replaces scripts/chip_recovery_playbook.sh and the probe/recovery half
of scripts/tunnel_watch.sh with the cluster/sessions.py supervisor:

- ``volsync session run [opts] -- CMD...`` — run CMD as the next
  serialized verify-then-measure job: probe first, kill at the hard
  deadline, recycle on wedge, stamp VOLSYNC_SESSION_* into CMD's
  environment so every bench JSON it emits carries session provenance.
  Exit code is CMD's, or 75 (EX_TEMPFAIL) when the backend never
  verifies healthy / the job is fenced or killed.
- ``volsync session status [--probe]`` — show the last supervisor
  status mirror (VOLSYNC_SESSION_STATUS); ``--probe`` additionally
  runs one live subprocess probe (exit 75 when wedged).
- ``volsync session recycle`` — force-release now: SIGKILL stale
  marked measurement children (the round-4 recovery action), exit 0.

Dispatched pre-boot from cli/main.py (like ``lint`` and ``trace``) so
``session status`` on a wedged host never imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from volsync_tpu import envflags
from volsync_tpu.cluster import sessions
from volsync_tpu.objstore.faultstore import FaultSchedule, parse_spec

DEFAULT_STATUS = "/tmp/volsync_session_status.json"

#: EX_TEMPFAIL — the backend is unhealthy / the result was refused;
#: retry after recovery (tunnel_watch.sh keys off this)
EXIT_UNHEALTHY = 75


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="volsync session",
        description="Supervised accelerator sessions: serialized "
                    "verify-then-measure jobs, status, forced recycle.")
    sub = p.add_subparsers(dest="verb", required=True)

    run = sub.add_parser(
        "run", help="run CMD as the next serialized bench job")
    run.add_argument("--backend", choices=("jax", "fake"), default="jax",
                     help="session backend (fake = deterministic "
                          "seeded chaos, no chip)")
    run.add_argument("--label", default="job",
                     help="job label for spans and logs")
    run.add_argument("--deadline", type=float, default=None,
                     help="per-job hard deadline in seconds "
                          "(default VOLSYNC_SESSION_JOB_DEADLINE_S)")
    run.add_argument("--ttl", type=float, default=None,
                     help="lease TTL seconds "
                          "(default VOLSYNC_SESSION_TTL_S)")
    run.add_argument("--probe-timeout", type=float, default=None,
                     help="verify-probe budget in seconds "
                          "(default VOLSYNC_SESSION_PROBE_TIMEOUT_S)")
    run.add_argument("--status-file", default=None,
                     help="mirror supervisor state to this JSON file "
                          "(default VOLSYNC_SESSION_STATUS)")
    run.add_argument("--fake-seed", type=int, default=0,
                     help="fault-schedule seed for --backend fake")
    run.add_argument("--fake-spec", action="append", default=[],
                     metavar="SPEC",
                     help="faultstore spec for --backend fake, e.g. "
                          "'hang:op=probe,at=2,ms=400000' or "
                          "'zombie:op=keepalive,at=4' (repeatable)")
    run.add_argument("cmd", nargs=argparse.REMAINDER,
                     help="command to run (prefix with --)")

    st = sub.add_parser("status",
                        help="show last supervisor status mirror")
    st.add_argument("--file", default=None,
                    help=f"status mirror path (default "
                         f"VOLSYNC_SESSION_STATUS or {DEFAULT_STATUS})")
    st.add_argument("--probe", action="store_true",
                    help="also run one live backend probe")
    st.add_argument("--probe-timeout", type=float, default=None)

    rec = sub.add_parser("recycle",
                         help="force-release: kill stale marked "
                              "measurement children now")
    rec.add_argument("--marker", default=sessions.BENCH_CHILD_MARKER,
                     help="environment marker identifying stale "
                          "measurement children")
    return p


def _parse_session_specs(texts: list) -> list:
    """faultstore ``parse_spec`` plus the session-only ``zombie`` kind
    (not in the store registry: a store op can't hold a device)."""
    import dataclasses

    out = []
    for text in texts:
        for entry in filter(None, (e.strip() for e in text.split(";"))):
            kind, _, rest = entry.partition(":")
            if kind.strip() == "zombie":
                out.extend(dataclasses.replace(s, kind="zombie")
                           for s in parse_spec(f"transient:{rest}"))
            else:
                out.extend(parse_spec(entry))
    return out


def _make_backend(args) -> object:
    if args.backend == "fake":
        return sessions.FakeSessionBackend(
            FaultSchedule(seed=args.fake_seed,
                          specs=_parse_session_specs(args.fake_spec)))
    return sessions.JaxSessionBackend(probe_timeout=args.probe_timeout)


def _status_path(explicit: Optional[str]) -> str:
    return (explicit or envflags.session_status_path()
            or DEFAULT_STATUS)


def _run(args, out) -> int:
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        out("session run: no command given (append -- CMD...)")
        return 2
    backend = _make_backend(args)
    sup = sessions.SessionSupervisor(
        backend, ttl=args.ttl, probe_timeout=args.probe_timeout,
        status_path=_status_path(args.status_file))
    queue = sessions.BenchQueue(sup, job_deadline=args.deadline)
    with sup:  # keepalive thread runs between (not during) jobs
        try:
            res = queue.run_command(cmd, label=args.label)
        except sessions.SessionError as exc:
            out(f"session run: {exc}")
            return EXIT_UNHEALTHY
    inner = res["result"]
    if inner["stdout"]:
        out(inner["stdout"].rstrip("\n"))
    if inner["stderr"]:
        print(inner["stderr"].rstrip("\n"), file=sys.stderr)
    out(json.dumps({"session": res["session"],
                    "label": res["label"], "rc": inner["rc"]}))
    return inner["rc"]


def _status(args, out) -> int:
    path = _status_path(args.file)
    try:
        with open(path, encoding="utf-8") as f:
            out(json.dumps(json.loads(f.read()), indent=2,
                           sort_keys=True))
    except (OSError, ValueError):
        out(f"no session status at {path}")
        if not args.probe:
            return 1
    if args.probe:
        backend = sessions.JaxSessionBackend(
            probe_timeout=args.probe_timeout)
        try:
            platform = backend.probe("status-probe",
                                     timeout=args.probe_timeout or 0.0)
        except Exception as exc:  # noqa: BLE001 — any probe failure
            # means "wedged" to the operator reading this
            out(f"probe: WEDGED ({exc})")
            return EXIT_UNHEALTHY
        out(f"probe: live ({platform})")
    return 0


def _recycle(args, out) -> int:
    killed = sessions.kill_marked_children(args.marker, log_fn=out)
    out(f"recycle: killed {killed} stale measurement "
        f"child{'' if killed == 1 else 'ren'} "
        f"(marker {args.marker!r}, pid {os.getpid()} spared)")
    return 0


def main(argv=None, out=print) -> int:
    args = build_parser().parse_args(argv)
    if args.verb == "run":
        return _run(args, out)
    if args.verb == "status":
        return _status(args, out)
    return _recycle(args, out)


if __name__ == "__main__":
    raise SystemExit(main())
