"""Job/Deployment runner: the kubelet analogue.

Resolves ``spec.entrypoint`` from a registered catalog (the
container-image analogue: the reference wires mover images via
``--<mover>-container-image`` flags — SURVEY.md §5 config) and executes
payloads in worker threads. Jobs retry up to ``backoff_limit`` (the
reference's Jobs use backoffLimit 2 or 8 — rsync/mover.go:363,
restic/mover.go:286); Deployments run until stopped.

Tests that want envtest semantics simply don't start a runner and flip
``job.status.succeeded`` themselves (SURVEY.md §4 tier 2).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import traceback
from datetime import datetime, timezone
from pathlib import Path
from typing import Callable, Optional

from volsync_tpu.cluster.objects import HOSTNAME_LABEL

log = logging.getLogger("volsync_tpu.runner")


@dataclasses.dataclass
class JobContext:
    """What a data-plane entrypoint sees: its config and its mounts.

    ``cluster`` is provided for substrate interactions that a pod would do
    through its environment (e.g. a daemon publishing its bound port on its
    Service); data-plane logic must otherwise stick to env/mounts/secrets —
    that discipline preserves the reference's process boundary.
    """

    name: str
    namespace: str
    env: dict
    mounts: dict            # mount name -> Path
    secrets: dict           # mount name -> {key: bytes}
    stop_event: threading.Event
    cluster: object = None
    attempt: int = 0
    kind: str = "Job"       # Job | Deployment — which object hosts us

    def report_transfer(self, nbytes: int, seconds: float):
        """Data-plane self-report (the termination-message analogue): the
        entrypoint records how many bytes its transfer moved and how long
        the data path took; the control plane reads this off the completed
        Job and drives the throughput gauge + TransferCompleted event."""
        if self.cluster is None:
            return
        obj = self.cluster.try_get(self.kind, self.namespace, self.name)
        if obj is None:
            return
        obj.status.transfer_bytes = int(nbytes)
        obj.status.transfer_seconds = float(seconds)
        self.cluster.update_status(obj)


class EntrypointCatalog:
    """Global registry of data-plane entrypoints, name -> callable(ctx)->int."""

    def __init__(self):
        # entrypoints register at import/setup time, before any
        # JobRunner worker thread starts; threads only read
        self._entries: dict[str, Callable] = {}  # lint: ignore[VL404]

    def register(self, name: str, fn: Optional[Callable] = None):
        if fn is None:
            def deco(f):
                self._entries[name] = f
                return f
            return deco
        self._entries[name] = fn
        return fn

    def get(self, name: str) -> Callable:
        if name not in self._entries:
            raise KeyError(f"no entrypoint registered for {name!r}")
        return self._entries[name]

    def __contains__(self, name):
        return name in self._entries


CATALOG = EntrypointCatalog()


class JobRunner:
    """Watches the cluster and executes runnable Jobs and Deployments."""

    def __init__(self, cluster, catalog: EntrypointCatalog = CATALOG,
                 max_workers: int = 8, node_name: str = "node-0",
                 node_labels: Optional[dict] = None):
        self.cluster = cluster
        self.catalog = catalog
        self.max_workers = max_workers
        # The runner is the kubelet analogue: one runner = one node. A
        # payload with a node_selector only runs on a runner whose labels
        # satisfy it (the affinity pinning of utils/affinity.go:35-83 —
        # two runners with different hostnames model a two-node cluster).
        self.node_name = node_name
        self.node_labels = dict(node_labels or {})
        self.node_labels.setdefault(HOSTNAME_LABEL, node_name)
        self._stop = threading.Event()
        self._running: dict[tuple, threading.Thread] = {}
        self._daemon_stops: dict[tuple, threading.Event] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # Lifecycle -------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-runner")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            for ev in self._daemon_stops.values():
                ev.set()
            threads = list(self._running.values())
        for t in threads:
            t.join(timeout=10)
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # Main loop -------------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._schedule_once()
            except Exception:
                log.exception("runner scheduling error")
            self.cluster.wait_for(lambda: self._stop.is_set(), timeout=0.2)

    def _schedule_once(self):
        with self._lock:
            for job in self.cluster.list("Job"):
                if len(self._running) >= self.max_workers:
                    return
                key = ("Job",) + job.metadata.key
                if key in self._running:
                    continue
                if self._job_runnable(job):
                    t = threading.Thread(
                        target=self._run_job, args=(job,), daemon=True,
                        name=f"job-{job.metadata.name}",
                    )
                    self._running[key] = t
                    t.start()
            for dep in self.cluster.list("Deployment"):
                if len(self._running) >= self.max_workers:
                    return
                key = ("Deployment",) + dep.metadata.key
                alive = key in self._running and self._running[key].is_alive()
                if alive and not self._selector_matches(dep.spec):
                    # Selector moved away from this node mid-flight: stop
                    # our instance so the right node can take over (the
                    # selector only *gates* starts; stop/pause handling
                    # below must still run for daemons we already host).
                    self._daemon_stops[key].set()
                elif (dep.spec.replicas >= 1 and not alive
                        and self._selector_matches(dep.spec)
                        and not (dep.status.ready_replicas > 0
                                 and dep.status.node not in (None, self.node_name))):
                    stop = threading.Event()
                    self._daemon_stops[key] = stop
                    t = threading.Thread(
                        target=self._run_deployment, args=(dep, stop),
                        daemon=True, name=f"dep-{dep.metadata.name}",
                    )
                    self._running[key] = t
                    t.start()
                elif dep.spec.replicas == 0 and key in self._daemon_stops:
                    self._daemon_stops[key].set()
            # Reap daemons whose object is gone
            for key, stop in list(self._daemon_stops.items()):
                kind, ns, name = key
                if self.cluster.try_get(kind, ns, name) is None:
                    stop.set()

    def _selector_matches(self, spec) -> bool:
        sel = getattr(spec, "node_selector", None) or {}
        return all(self.node_labels.get(k) == v for k, v in sel.items())

    def _job_runnable(self, job) -> bool:
        s = job.status
        if job.spec.parallelism == 0:   # paused (rsync/mover.go:366-370)
            return False
        if s.succeeded > 0 or s.active > 0:
            return False
        if s.failed > job.spec.backoff_limit:
            return False
        if job.spec.entrypoint not in self.catalog:
            return False
        if not self._selector_matches(job.spec):
            return False
        return self._mounts_ready(job.spec, job.metadata.namespace)

    def _mounts_ready(self, spec, namespace: str) -> bool:
        for volname in spec.volumes.values():
            vol = self.cluster.try_get("Volume", namespace, volname)
            if vol is None or vol.status.phase != "Bound":
                return False
        for secname in spec.secrets.values():
            if self.cluster.try_get("Secret", namespace, secname) is None:
                return False
        return True

    def _resolve(self, meta, spec):
        mounts = {}
        for mount, volname in spec.volumes.items():
            vol = self.cluster.get("Volume", meta.namespace, volname)
            mounts[mount] = Path(vol.status.path)
        secrets = {}
        for mount, secname in spec.secrets.items():
            sec = self.cluster.get("Secret", meta.namespace, secname)
            secrets[mount] = dict(sec.data)
        return mounts, secrets

    # Execution -------------------------------------------------------------

    def _run_job(self, job):
        key = ("Job",) + job.metadata.key
        try:
            if not self._mounts_ready(job.spec, job.metadata.namespace):
                return
            # Atomic claim (CAS on resourceVersion): with several runners
            # (nodes) watching one cluster, exactly one may flip the Job
            # active — a lost race means another node took it.
            job = self.cluster.try_get("Job", *job.metadata.key)
            if job is None or job.status.active > 0 or job.status.succeeded > 0:
                return
            claim_version = job.metadata.resource_version
            mounts, secrets = self._resolve(job.metadata, job.spec)
            job.status.active = 1
            job.status.node = self.node_name
            job.status.start_time = job.status.start_time or datetime.now(
                timezone.utc
            )
            from volsync_tpu.cluster.cluster import Conflict

            try:
                self.cluster.update_status(job, expect_version=claim_version)
            except Conflict:
                return  # another runner claimed it first
            ctx = JobContext(
                name=job.metadata.name, namespace=job.metadata.namespace,
                env=dict(job.spec.env), mounts=mounts, secrets=secrets,
                stop_event=self._stop, cluster=self.cluster,
                attempt=job.status.failed,
            )
            fn = self.catalog.get(job.spec.entrypoint)
            try:
                rc = fn(ctx)
                rc = 0 if rc is None else int(rc)
            except Exception as e:  # noqa: BLE001 — mover failure, not ours
                log.warning("job %s attempt %d failed: %s",
                            job.metadata.name, ctx.attempt, e)
                job.status.message = "".join(
                    traceback.format_exception_only(type(e), e)
                ).strip()
                rc = 1
            fresh = self.cluster.try_get("Job", *job.metadata.key)
            if fresh is None or fresh.metadata.uid != job.metadata.uid:
                return  # deleted/recreated while we ran
            fresh.status.active = 0
            fresh.status.exit_code = rc
            fresh.status.message = job.status.message
            if rc == 0:
                fresh.status.succeeded = 1
                fresh.status.completion_time = datetime.now(timezone.utc)
            else:
                fresh.status.failed += 1
            self.cluster.update_status(fresh)
        finally:
            with self._lock:
                self._running.pop(key, None)

    def _run_deployment(self, dep, stop):
        key = ("Deployment",) + dep.metadata.key
        claimed = False
        try:
            while not (stop.is_set() or self._stop.is_set()):
                if self._mounts_ready(dep.spec, dep.metadata.namespace):
                    break
                self.cluster.wait_for(lambda: stop.is_set(), timeout=0.2)
            if stop.is_set() or self._stop.is_set():
                return
            # Atomic claim, as for Jobs: replicas=1 means ONE live daemon
            # across all runners.
            dep = self.cluster.try_get("Deployment", *dep.metadata.key)
            if dep is None or (dep.status.ready_replicas > 0
                               and dep.status.node != self.node_name):
                return
            claim_version = dep.metadata.resource_version
            mounts, secrets = self._resolve(dep.metadata, dep.spec)
            dep.status.ready_replicas = 1
            dep.status.node = self.node_name
            from volsync_tpu.cluster.cluster import Conflict

            try:
                self.cluster.update_status(dep, expect_version=claim_version)
            except Conflict:
                return
            claimed = True
            ctx = JobContext(
                name=dep.metadata.name, namespace=dep.metadata.namespace,
                env=dict(dep.spec.env), mounts=mounts, secrets=secrets,
                stop_event=stop, cluster=self.cluster, kind="Deployment",
            )
            fn = self.catalog.get(dep.spec.entrypoint)
            try:
                fn(ctx)
            except Exception as e:  # noqa: BLE001
                log.warning("deployment %s crashed: %s", dep.metadata.name, e)
                fresh = self.cluster.try_get("Deployment", *dep.metadata.key)
                if fresh is not None:
                    fresh.status.message = str(e)
                    self.cluster.update_status(fresh)
        finally:
            fresh = self.cluster.try_get("Deployment", *dep.metadata.key)
            if (claimed and fresh is not None
                    and fresh.metadata.uid == dep.metadata.uid):
                fresh.status.ready_replicas = 0
                fresh.status.node = None
                self.cluster.update_status(fresh)
            with self._lock:
                self._running.pop(key, None)
                self._daemon_stops.pop(key, None)
