"""Supervised accelerator sessions: leases, keepalive TTLs, auto-recycle,
and a serialized verify-then-measure bench queue.

Rounds 4/5 lost every accelerator measurement to ONE leaked
single-tenant tunnel session that wedged the backend for 8+ hours
(docs/performance.md). The fix is lifecycle, not shell scripts — the
lesson "Reexamining Paradigms of End-to-End Data Movement" (PAPERS.md)
draws for long-lived transfer channels: sessions need supervised leases,
bounded renewal, and fencing, exactly like the recovery-coordination
discipline of the repository store locks (repo/repository.py).

Four pieces:

- **Lease** — a hard-TTL hold on the backend's single-tenant device
  slot. Acquire goes through ``resilience.RetryPolicy`` with the
  per-backend circuit breaker; every successful keepalive beat extends
  the expiry to ``now + ttl``; a lease whose beats stop is EXPIRED at
  the TTL no matter what the holder believes (the 8-hour wedge becomes
  a bounded outage).
- **SessionSupervisor** — the state machine ACQUIRING -> HEALTHY ->
  DEGRADED -> RECYCLING. Keepalive failures degrade; the consecutive-
  failure threshold, a probe timeout, or TTL expiry force a
  single-flight recycle (``force_release`` on the backend + a fresh
  acquire under a NEW fencing epoch). Every forced recycle drops a
  ``record_trigger`` annotation into the flight recorder, so the trace
  around the wedge is preserved. ``guard(epoch)`` refuses results from
  a session that was fenced out while it ran — a zombie's late write
  can never land.
- **BenchQueue** — the serialized verify-then-measure queue: jobs run
  strictly one-at-a-time behind a verify probe, are killed at a
  per-job hard deadline, and every result carries the session
  provenance (backend, session id, fencing epoch) that
  ``bench.bench_provenance`` stamps into BENCH_*.json.
- **FakeSessionBackend** — deterministic seeded fault schedules in the
  ``objstore/faultstore.py`` style (probe hang, keepalive drop,
  zombie-holds-device, crash mid-job) so the whole supervisor is
  chaos-tested in tier-1 with no chip. ``JaxSessionBackend`` is the
  real thing: subprocess probes with hard timeouts and a
  stale-measurement-child sweep as ``force_release``.

``scripts/tunnel_watch.sh`` and ``scripts/bench_self.py`` are thin
wrappers over this module via the ``volsync session run/status/recycle``
CLI verbs (cluster/sessioncli.py).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from volsync_tpu import envflags
from volsync_tpu.analysis import lockcheck
from volsync_tpu.metrics import GLOBAL as GLOBAL_METRICS
from volsync_tpu.objstore.faultstore import FaultSchedule
from volsync_tpu.obs import record_trigger, span
from volsync_tpu.resilience import RetryPolicy, TransientError, breaker_for

log = logging.getLogger("volsync_tpu.sessions")

# -- states ------------------------------------------------------------------

ACQUIRING = "acquiring"
HEALTHY = "healthy"
DEGRADED = "degraded"
RECYCLING = "recycling"

_STATE_CODE = {ACQUIRING: 0, HEALTHY: 1, DEGRADED: 2, RECYCLING: 3}


# -- errors ------------------------------------------------------------------

class SessionError(RuntimeError):
    """Supervised-session failure (fatal to the caller's attempt; the
    supervisor has already scheduled whatever recovery applies)."""


class SessionBusy(TransientError):
    """The backend's single-tenant device slot is held by another
    session (typically a zombie awaiting force_release) — retryable
    once the holder is recycled."""


class FencedError(SessionError):
    """The producing session's fencing epoch is stale: it was recycled
    while the work ran, so its result is refused. NOT retryable — the
    zombie must die, not retry."""


class JobDeadlineExceeded(SessionError):
    """A queued job hit its per-job hard deadline and was killed."""


# -- deterministic clock (tests, chaos schedules) ----------------------------

class FakeClock:
    """Deterministic clock: calling it reads the time, ``sleep``
    advances it. Injected as ``clock``/``sleep_fn`` so supervisor tests
    drive TTL and probe-timeout arithmetic without wall-clock waits."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.now += max(0.0, float(seconds))


# -- lease -------------------------------------------------------------------

class Lease:
    """Hard-TTL hold on a backend's single-tenant device slot.

    ``acquire`` runs under the shared retry policy with the per-backend
    circuit breaker (a dead backend fails fast instead of being
    hammered); each successful ``beat`` extends the expiry to
    ``now + ttl``. Expiry is judged by the injected ``clock`` so the
    deterministic chaos tests need no wall time.
    """

    def __init__(self, backend, *, ttl: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 policy: Optional[RetryPolicy] = None):
        self.backend = backend
        self.ttl = envflags.session_ttl_seconds() if ttl is None else ttl
        self._clock = clock
        self._lock = lockcheck.make_lock(f"session.lease.{backend.name}")
        self._policy = policy if policy is not None else RetryPolicy.from_env(
            f"session.{backend.name}", sleep_fn=sleep_fn,
            breaker=breaker_for(f"session.{backend.name}"))
        self.session_id: Optional[str] = None
        self._expires = 0.0

    def acquire(self) -> str:
        sid = self._policy.call(self.backend.acquire)
        with self._lock:
            self.session_id = sid
            self._expires = self._clock() + self.ttl
        return sid

    def beat(self) -> None:
        """One keepalive beat — no internal retry (the supervisor
        counts consecutive failures; retrying here would hide them)."""
        with self._lock:
            sid = self.session_id
        if sid is None:
            raise SessionError("no session to keep alive")
        self.backend.keepalive(sid)
        with self._lock:
            self._expires = self._clock() + self.ttl

    def expired(self) -> bool:
        with self._lock:
            return self.session_id is None or self._clock() >= self._expires

    def remaining(self) -> float:
        with self._lock:
            if self.session_id is None:
                return 0.0
            return max(0.0, self._expires - self._clock())

    def release(self, *, force: bool = False) -> None:
        with self._lock:
            sid, self.session_id = self.session_id, None
            self._expires = 0.0
        if force:
            self.backend.force_release()
        elif sid is not None:
            try:
                self.backend.release(sid)
            except Exception as exc:  # noqa: BLE001 — best-effort; the
                # TTL reaps whatever a failed release leaves behind
                log.warning("session release failed (TTL reaps it): %s",
                            exc)


# -- supervisor --------------------------------------------------------------

class SessionSupervisor:
    """ACQUIRING -> HEALTHY -> DEGRADED -> RECYCLING over one backend.

    All state mutates under one re-entrant lock; ``tick()`` is one
    supervision beat (the keepalive thread calls it on an interval;
    deterministic tests call it directly). ``transitions`` records the
    full ``(clock, state, cause)`` trace — the chaos tests assert the
    same seed reproduces the same trace byte-for-byte.

    Fencing: ``epoch`` bumps on every recycle AND every fresh acquire,
    so a token captured by a job admitted under epoch N goes stale the
    instant the session is fenced out — ``guard(N)`` then refuses the
    job's result (the zombie's late write never lands).
    """

    def __init__(self, backend, *, ttl: Optional[float] = None,
                 keepalive_interval: Optional[float] = None,
                 probe_timeout: Optional[float] = None,
                 max_keepalive_failures: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 status_path: Optional[str] = None):
        self.backend = backend
        self.lease = Lease(backend, ttl=ttl, clock=clock,
                           sleep_fn=sleep_fn)
        self.keepalive_interval = (envflags.session_keepalive_seconds()
                                   if keepalive_interval is None
                                   else keepalive_interval)
        self.probe_timeout = (envflags.session_probe_timeout()
                              if probe_timeout is None else probe_timeout)
        self.max_keepalive_failures = (
            envflags.session_keepalive_failures()
            if max_keepalive_failures is None else max_keepalive_failures)
        self._clock = clock
        self._lock = lockcheck.make_rlock(
            f"session.supervisor.{backend.name}")
        self.state = ACQUIRING
        self.epoch = 0
        self.session_id: Optional[str] = None
        self.transitions: list[tuple[float, str, str]] = []
        self.keepalive_failures = 0
        self._recycling = False
        self._paused = 0
        self._status_path = (status_path if status_path is not None
                             else envflags.session_status_path())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauge = GLOBAL_METRICS.session_state.labels(
            backend=backend.name)
        self._gauge.set(_STATE_CODE[self.state])

    # -- state bookkeeping --------------------------------------------------

    def _transition(self, to: str, cause: str) -> None:
        lockcheck.assert_held(self._lock, "session state transition")
        if to == self.state:
            return
        self.state = to
        self.transitions.append((round(self._clock(), 3), to, cause))
        self._gauge.set(_STATE_CODE[to])
        GLOBAL_METRICS.session_transitions.labels(
            backend=self.backend.name, to=to).inc()
        log.info("session %s -> %s (%s)", self.backend.name, to, cause)
        self._write_status()

    def _write_status(self) -> None:
        if not self._status_path:
            return
        try:
            payload = json.dumps(dict(self.provenance(),
                                      wall_time=time.time()))
            tmp = f"{self._status_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload + "\n")
            os.replace(tmp, self._status_path)
        except OSError as exc:
            log.warning("session status mirror failed: %s", exc)

    def provenance(self) -> dict:
        """The identity block the bench queue stamps into every result
        (and into job environments as VOLSYNC_SESSION_*)."""
        with self._lock:
            return {"backend": self.backend.name,
                    "session_id": self.session_id,
                    "epoch": self.epoch,
                    "state": self.state}

    def job_env(self) -> dict:
        """VOLSYNC_SESSION_* variables for a queued job's environment —
        ``bench.bench_provenance`` reads them back into the provenance
        block of every BENCH_*.json."""
        with self._lock:
            return {"VOLSYNC_SESSION_ID": self.session_id or "",
                    "VOLSYNC_SESSION_EPOCH": str(self.epoch),
                    "VOLSYNC_SESSION_BACKEND": self.backend.name}

    # -- lifecycle ----------------------------------------------------------

    def ensure(self) -> str:
        """Return a healthy session id, acquiring one if needed."""
        with self._lock:
            if self.state == HEALTHY and not self.lease.expired():
                return self.session_id  # type: ignore[return-value]
            self._transition(ACQUIRING, "ensure")
            with span("session.acquire"):
                sid = self.lease.acquire()
            self.session_id = sid
            self.epoch += 1
            self.keepalive_failures = 0
            self._transition(HEALTHY, "acquired")
            return sid

    def pause_keepalive(self) -> None:
        """Suspend supervision beats while a queued job holds the
        single-tenant device — a keepalive probe would contend with the
        measurement for the chip. The lease is re-beaten at job end."""
        with self._lock:
            self._paused += 1

    def resume_keepalive(self) -> None:
        with self._lock:
            self._paused = max(0, self._paused - 1)

    def tick(self) -> None:
        """One supervision beat: TTL check + keepalive. Failures
        degrade; the consecutive-failure threshold or an expired lease
        force a recycle."""
        with self._lock:
            if self._paused or self.state in (ACQUIRING, RECYCLING):
                return
            if self.lease.expired():
                self.recycle("ttl_expired")
                return
            try:
                with span("session.keepalive"):
                    self.lease.beat()
            except Exception as exc:  # noqa: BLE001 — every failure
                # class counts toward the threshold; classification
                # nuance belongs to acquire's RetryPolicy, not the beat
                GLOBAL_METRICS.session_keepalives.labels(
                    backend=self.backend.name, outcome="failed").inc()
                self.keepalive_failures += 1
                log.warning("session keepalive failed (%d/%d): %s",
                            self.keepalive_failures,
                            self.max_keepalive_failures, exc)
                if self.keepalive_failures >= self.max_keepalive_failures:
                    self.recycle("keepalive_failures")
                elif self.state == HEALTHY:
                    self._transition(DEGRADED, "keepalive_failed")
                return
            GLOBAL_METRICS.session_keepalives.labels(
                backend=self.backend.name, outcome="ok").inc()
            self.keepalive_failures = 0
            if self.state == DEGRADED:
                self._transition(HEALTHY, "keepalive_recovered")

    def verify(self) -> str:
        """The verify probe in front of every queued job. A probe that
        fails — or blocks past ``probe_timeout`` (the faultstore
        ``hang`` kind in chaos schedules) — forces a recycle and raises
        SessionError; the queue retries admission against the fresh
        session."""
        sid = self.ensure()
        t0 = self._clock()
        try:
            with span("session.probe"):
                info = self.backend.probe(sid, timeout=self.probe_timeout)
        except Exception as exc:  # noqa: BLE001 — any probe failure
            # means the session cannot be trusted with the device
            elapsed = self._clock() - t0
            cause = ("probe_timeout" if elapsed >= self.probe_timeout
                     else "probe_failed")
            self.recycle(cause)
            raise SessionError(
                f"verify probe {cause} after {elapsed:.1f}s: {exc}"
            ) from exc
        elapsed = self._clock() - t0
        if elapsed >= self.probe_timeout:
            self.recycle("probe_timeout")
            raise SessionError(
                f"verify probe blocked {elapsed:.1f}s "
                f"(budget {self.probe_timeout:.1f}s)")
        return info

    def recycle(self, cause: str) -> bool:
        """Single-flight forced recycle: fence the epoch, dump the
        flight recorder, force-release the device, land in ACQUIRING.
        Returns False when another flight is already recycling."""
        with self._lock:
            if self._recycling:
                return False
            self._recycling = True
            try:
                old = self.session_id
                self._transition(RECYCLING, cause)
                # Fence FIRST: from this instant, results produced under
                # the old epoch are refused even while force_release is
                # still in flight.
                self.epoch += 1
                GLOBAL_METRICS.session_recycles.labels(
                    backend=self.backend.name, cause=cause).inc()
                record_trigger("session_recycle",
                               backend=self.backend.name, cause=cause,
                               epoch=self.epoch, session=old or "")
                with span("session.recycle"):
                    self.lease.release(force=True)
                self.session_id = None
                self.keepalive_failures = 0
                self._transition(ACQUIRING, "recycled")
            finally:
                self._recycling = False
        return True

    def guard(self, epoch: int) -> None:
        """Refuse work stamped with a stale fencing epoch — the zombie
        session's late write."""
        with self._lock:
            if epoch != self.epoch or self.state != HEALTHY:
                GLOBAL_METRICS.session_fenced_writes.labels(
                    backend=self.backend.name).inc()
                record_trigger("session_fenced_write",
                               backend=self.backend.name,
                               stale_epoch=epoch, epoch=self.epoch)
                raise FencedError(
                    f"fencing epoch {epoch} is stale "
                    f"(current {self.epoch}, state {self.state}); "
                    f"result refused")

    def wait_healthy(self, *, timeout: float,
                     sleep_fn: Callable[[float], None] = time.sleep) -> str:
        """Block (with jittered backoff) until a healthy session exists
        or ``timeout`` expires — the tunnel-watch entry point."""
        policy = RetryPolicy.from_env(
            "session.wait_healthy", max_attempts=10_000,
            deadline=timeout, sleep_fn=sleep_fn)
        return policy.call(self.verify)

    # -- keepalive thread ---------------------------------------------------

    def start(self) -> "SessionSupervisor":
        """Run ``tick()`` every ``keepalive_interval`` seconds on a
        named thread until ``stop()``."""
        if self._thread is not None:
            return self

        def beat_loop():
            while not self._stop.wait(self.keepalive_interval):
                try:
                    self.tick()
                except Exception as exc:  # noqa: BLE001 — the beat
                    # must survive anything; recycle paths report their
                    # own failures
                    log.warning("session tick failed: %s", exc)

        self._thread = threading.Thread(target=beat_loop,
                                        name="session-keepalive")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._write_status()

    def __enter__(self) -> "SessionSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- serialized verify-then-measure queue ------------------------------------

class BenchQueue:
    """Bench jobs, strictly one-at-a-time behind a verify probe.

    The queue lock serializes admission AND execution — two jobs can
    never hold the single-tenant device concurrently, whatever threads
    submit them. Each job is killed at a hard deadline (the 8-hour
    wedge of round 4 becomes a bounded, recycled failure), and its
    result is ``guard``-checked against the fencing epoch captured at
    admission: a job that rode across a recycle is refused.
    """

    #: verify attempts per admission — each failure already recycled
    #: the session, so the retry runs against a fresh one
    ADMIT_ATTEMPTS = 3

    def __init__(self, supervisor: SessionSupervisor, *,
                 job_deadline: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.supervisor = supervisor
        self.job_deadline = (envflags.session_job_deadline()
                             if job_deadline is None else job_deadline)
        self._clock = clock
        self._lock = lockcheck.make_lock(
            f"session.queue.{supervisor.backend.name}")
        self.completed: list[dict] = []

    def _admit(self) -> dict:
        last: Optional[Exception] = None
        for _ in range(self.ADMIT_ATTEMPTS):
            try:
                with span("session.verify"):
                    self.supervisor.verify()
                return self.supervisor.provenance()
            except SessionError as exc:
                last = exc  # verify already recycled; retry fresh
            except Exception as exc:  # noqa: BLE001 — acquire itself
                # failed (e.g. SessionBusy: a zombie holds the device);
                # force_release via recycle, then retry admission
                last = exc
                self.supervisor.recycle("acquire_failed")
        raise SessionError(
            f"verify failed {self.ADMIT_ATTEMPTS}x — backend stays "
            f"unhealthy: {last}")

    def _notify(self, method: str, sid: Optional[str]) -> None:
        hook = getattr(self.supervisor.backend, method, None)
        if hook is not None:
            hook(sid)

    def run(self, fn: Callable[[], object], *, label: str = "job",
            deadline: Optional[float] = None) -> dict:
        """Run ``fn`` as the next serialized job. Returns
        ``{"label", "result", "session"}``; raises JobDeadlineExceeded
        (after recycling) when the job outruns its deadline, and
        FencedError when the session was recycled out from under it."""
        deadline = self.job_deadline if deadline is None else deadline
        with self._lock:
            prov = self._admit()
            epoch = prov["epoch"]
            sid = prov["session_id"]
            from concurrent.futures import ThreadPoolExecutor
            from concurrent.futures import TimeoutError as FutTimeout

            t0 = self._clock()
            self.supervisor.pause_keepalive()
            pool = ThreadPoolExecutor(
                1, thread_name_prefix=f"session-job-{label}")
            try:
                self._notify("job_started", sid)
                with span("session.job"):
                    fut = pool.submit(fn)
                    try:
                        result = fut.result(timeout=deadline)
                    except (FutTimeout, TimeoutError):
                        self.supervisor.recycle("job_deadline")
                        raise JobDeadlineExceeded(
                            f"job {label!r} exceeded {deadline:.0f}s — "
                            f"killed and session recycled") from None
            except JobDeadlineExceeded:
                raise
            except FencedError:
                raise
            except Exception:
                # the job died inside the session: device state is
                # unknown, so the slot is recycled before the next job
                self.supervisor.recycle("job_failed")
                raise
            finally:
                self._notify("job_finished", sid)
                self.supervisor.resume_keepalive()
                # never join a possibly-wedged worker (bench.py rule)
                pool.shutdown(wait=False, cancel_futures=True)
            elapsed = self._clock() - t0
            if elapsed >= deadline:
                # deterministic-clock path: the job "ran long" even if
                # the wall-clock future returned promptly
                self.supervisor.recycle("job_deadline")
                raise JobDeadlineExceeded(
                    f"job {label!r} took {elapsed:.1f}s "
                    f"(deadline {deadline:.0f}s); result refused")
            self.supervisor.guard(epoch)
            out = {"label": label, "result": result, "session": prov}
            self.completed.append({"label": label, "epoch": epoch,
                                   "session_id": sid})
            return out

    def run_command(self, cmd: list[str], *, label: str = "job",
                    deadline: Optional[float] = None,
                    env_extra: Optional[dict] = None) -> dict:
        """Run a subprocess as the next serialized job, its environment
        stamped with VOLSYNC_SESSION_* so any bench JSON it emits
        carries session provenance. The subprocess is KILLED at the
        deadline — the only hang-proof boundary is a killable process."""
        deadline = self.job_deadline if deadline is None else deadline

        def job():
            env = dict(os.environ, **self.supervisor.job_env(),
                       **(env_extra or {}))
            try:
                r = subprocess.run(cmd, env=env, capture_output=True,
                                   text=True, timeout=deadline)
            except subprocess.TimeoutExpired as exc:
                out = exc.stdout or ""
                if isinstance(out, bytes):
                    out = out.decode(errors="replace")
                return {"rc": 124, "stdout": out, "stderr": "TIMEOUT"}
            return {"rc": r.returncode, "stdout": r.stdout,
                    "stderr": r.stderr}

        # generous outer margin: the subprocess timeout is the real
        # enforcement; the future timeout only guards a wedged spawn
        res = self.run(job, label=label, deadline=deadline + 60)
        if res["result"]["rc"] == 124:
            self.supervisor.recycle("job_deadline")
            raise JobDeadlineExceeded(
                f"command {label!r} exceeded {deadline:.0f}s — killed "
                f"and session recycled")
        return res


# -- fake backend (deterministic chaos) --------------------------------------

class FakeSessionBackend:
    """Deterministic seeded session backend, faultstore-style.

    Faults come from a ``FaultSchedule`` whose specs target session ops
    (``op=`` one of acquire/keepalive/probe/job) with these kinds:

    - ``transient`` — the op fails retryable (keepalive DROP when
      targeted at ``keepalive``);
    - ``hang``      — the op blocks ``ms=`` (default ``hang_s``) on the
      injected clock, then fails — the probe-timeout trigger;
    - ``zombie``    — the session stops answering keepalives but HOLDS
      the device: acquire raises SessionBusy until ``force_release``;
    - ``crash``     — the op (or the job started under it) dies
      non-retryably.

    Decisions reuse ``FaultSchedule.roll`` — a pure hash of
    (seed, spec, op, key, occurrence) — so the same seed over the same
    op sequence reproduces the same faults and therefore the same
    supervisor transition trace. Everything is logged in ``ops`` for
    replay assertions; ``max_concurrent_jobs`` pins the queue's
    one-at-a-time guarantee.
    """

    name = "fake"

    def __init__(self, schedule: Optional[FaultSchedule] = None, *,
                 seed: int = 0, clock: Optional[FakeClock] = None,
                 hang_s: float = 60.0):
        self.schedule = (schedule if schedule is not None
                         else FaultSchedule(seed=seed, specs=[]))
        self.clock = clock if clock is not None else FakeClock()
        self._sleep = self.clock.sleep
        self.hang_s = hang_s
        self._lock = lockcheck.make_lock("session.fake")
        self._spec_hits = [0] * len(self.schedule.specs)
        self._occurrence: dict[tuple[str, str], int] = {}
        self._count = 0
        self.device_holder: Optional[str] = None
        self.zombies: set[str] = set()
        self.ops: list[tuple[str, str, tuple]] = []
        self.writes: list[tuple[int, object]] = []
        self.active_jobs = 0
        self.max_concurrent_jobs = 0
        self.force_releases = 0

    def _decide(self, op: str, key: str) -> list:
        with self._lock:
            n = self._occurrence.get((op, key), 0) + 1
            self._occurrence[(op, key)] = n
            fired = []
            for i, spec in enumerate(self.schedule.specs):
                if not spec.matches(op, key):
                    continue
                self._spec_hits[i] += 1
                hit = (self._spec_hits[i] == spec.at
                       if spec.at is not None
                       else self.schedule.roll(i, op, key, n) < spec.p)
                if hit:
                    fired.append(spec)
            self.ops.append((op, key, tuple(s.kind for s in fired)))
        return fired

    def _apply(self, op: str, fired: list) -> None:
        for spec in fired:
            if spec.kind == "hang":
                self._sleep(spec.latency if spec.latency > 0
                            else self.hang_s)
                raise TransientError(f"injected hang at {op}")
            if spec.kind == "crash":
                raise RuntimeError(f"injected crash at {op}")
            if spec.kind == "transient":
                raise TransientError(f"injected drop at {op}")

    # -- session backend protocol -------------------------------------------

    def acquire(self) -> str:
        fired = self._decide("acquire", "")
        if self.device_holder is not None:
            raise SessionBusy(
                f"device held by {self.device_holder!r} "
                f"(zombie awaiting force_release)")
        self._apply("acquire", fired)
        with self._lock:
            self._count += 1
            sid = f"fake-{self._count}"
            self.device_holder = sid
        return sid

    def keepalive(self, session_id: str) -> None:
        fired = self._decide("keepalive", session_id)
        for spec in fired:
            if spec.kind == "zombie":
                with self._lock:
                    self.zombies.add(session_id)
                raise TransientError("session went zombie "
                                     "(holds the device)")
        if session_id in self.zombies:
            raise TransientError("zombie session ignores keepalive")
        self._apply("keepalive", fired)

    def probe(self, session_id: str, *, timeout: float = 0.0) -> str:
        fired = self._decide("probe", session_id)
        if session_id in self.zombies:
            self._sleep(max(timeout, self.hang_s))
            raise TransientError("zombie session: probe wedged")
        self._apply("probe", fired)
        if self.device_holder != session_id:
            raise SessionError(f"probe of released session "
                               f"{session_id!r}")
        return "fake-ok"

    def release(self, session_id: str) -> None:
        self._decide("release", session_id)
        with self._lock:
            if (self.device_holder == session_id
                    and session_id not in self.zombies):
                self.device_holder = None
        # a zombie ignores polite release — only force_release frees it

    def force_release(self) -> int:
        with self._lock:
            freed = int(self.device_holder is not None)
            self.device_holder = None
            self.force_releases += 1
            self.ops.append(("force_release", "", ()))
        return freed

    # -- queue hooks ---------------------------------------------------------

    def job_started(self, session_id: Optional[str]) -> None:
        with self._lock:
            self.active_jobs += 1
            self.max_concurrent_jobs = max(self.max_concurrent_jobs,
                                           self.active_jobs)
        fired = self._decide("job", session_id or "")
        self._apply("job", fired)

    def job_finished(self, session_id: Optional[str]) -> None:
        with self._lock:
            self.active_jobs -= 1

    def write(self, epoch: int, payload: object) -> None:
        """A landed result write (tests call this only after a
        successful ``supervisor.guard`` — the fence test asserts the
        zombie's write never reaches here)."""
        with self._lock:
            self.writes.append((epoch, payload))


# -- real backend ------------------------------------------------------------

_JAX_PROBE_SRC = """
import jax, jax.numpy as jnp
x = jnp.arange(64, dtype=jnp.float32)
y = jax.jit(lambda v: (v * 2 + 1).sum())(x)
y.block_until_ready()
print("probe-ok", jax.default_backend())
"""

#: environment marker carried ONLY by this harness's measurement
#: children — the targeted-kill filter (see kill_marked_children)
BENCH_CHILD_MARKER = "VOLSYNC_BENCH_INNER=1"


def kill_marked_children(marker: str = BENCH_CHILD_MARKER, *,
                         log_fn: Callable[[str], None] = log.info) -> int:
    """SIGKILL processes leaked by PRIOR measurement runs — the round-4
    wedge cause was a leaked single-tenant session still holding the
    serving tunnel. Targeted: only processes whose environment carries
    ``marker`` (set exclusively by the measurement harness's children)
    and that are not this process or its parent. Never touches other
    TPU clients. ``marker`` is parameterized so tests can sweep a
    sentinel value without ever matching a real run."""
    import glob

    killed = 0
    own = {os.getpid(), os.getppid()}
    want = marker.encode()
    for path in glob.glob("/proc/[0-9]*/environ"):
        try:
            pid = int(path.split("/")[2])
        except ValueError:
            continue
        if pid in own:
            continue
        try:
            with open(path, "rb") as f:
                env_blob = f.read()
        except OSError:
            continue
        if want in env_blob.split(b"\0"):
            try:
                os.kill(pid, signal.SIGKILL)
                killed += 1
                log_fn(f"sessions: killed stale measurement pid {pid}")
            except OSError:
                pass
    return killed


class JaxSessionBackend:
    """The real single-tenant serving tunnel, probed in SUBPROCESSES
    with hard timeouts (a wedged ``jax.devices()`` hangs in C++ where
    in-process deadlines cannot interrupt — bench.py's round-3 lesson).
    ``force_release`` sweeps stale marked measurement children, the one
    recovery action with known cause-and-effect from the round-4/5
    postmortems."""

    name = "jax"

    def __init__(self, *, probe_timeout: Optional[float] = None,
                 keepalive_timeout: float = 120.0,
                 marker: str = BENCH_CHILD_MARKER):
        self.probe_timeout = (envflags.session_probe_timeout()
                              if probe_timeout is None else probe_timeout)
        self.keepalive_timeout = keepalive_timeout
        self.marker = marker
        self._count = 0

    def _probe_subprocess(self, timeout: float) -> str:
        try:
            r = subprocess.run(
                [sys.executable, "-c", _JAX_PROBE_SRC],
                timeout=max(timeout, 1.0), capture_output=True,
                text=True, env=dict(os.environ))
        except subprocess.TimeoutExpired:
            raise TransientError(
                f"backend probe exceeded {timeout:.0f}s "
                f"(tunnel wedged)") from None
        if r.returncode == 0 and "probe-ok" in r.stdout:
            return r.stdout.strip().split()[-1]
        raise TransientError(
            f"backend probe rc={r.returncode}: "
            f"{(r.stderr or '').strip()[-200:]}")

    def acquire(self) -> str:
        self._probe_subprocess(self.probe_timeout)
        self._count += 1
        return f"jax-{os.getpid()}-{self._count}"

    def keepalive(self, session_id: str) -> None:
        self._probe_subprocess(self.keepalive_timeout)

    def probe(self, session_id: str, *, timeout: float = 0.0) -> str:
        return self._probe_subprocess(timeout or self.probe_timeout)

    def release(self, session_id: str) -> None:
        pass  # sessions are subprocess-scoped; nothing to hand back

    def force_release(self) -> int:
        return kill_marked_children(self.marker)
