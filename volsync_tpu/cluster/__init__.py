"""Cluster substrate: typed object store + storage provider + job runner.

The reference is a Kubernetes operator; its substrate (API server, CSI
driver, kubelet) is external. The TPU framework is standalone, so this
package provides the equivalent substrate natively:

- ``objects``   — the resource kinds the movers build (Volume, VolumeSnapshot,
                  Job, Service, Secret, ServiceAccount, Deployment, Event),
                  mirroring what the reference's movers create via
                  controller-runtime (SURVEY.md §2 #10-13).
- ``cluster``   — an in-process API server: CRUD with resource versions,
                  labels/owner refs, label-selector deletes, and watch
                  notification. Controller tests run against it exactly the
                  way the reference's envtest suites run against a real
                  kube-apiserver with no kubelet (SURVEY.md §4 tier 2).
- ``storage``   — directory-backed volume provisioner with snapshot/clone
                  (hardlink PiT images), the CSI analogue.
- ``runner``    — the kubelet analogue: executes Job/Deployment payloads
                  from a registered entrypoint catalog in worker threads.
                  Optional — envtest-style tests flip Job status manually.
- ``sessions``  — supervised accelerator sessions: TTL leases with
                  keepalive, the ACQUIRING/HEALTHY/DEGRADED/RECYCLING
                  supervisor with fencing epochs, and the serialized
                  verify-then-measure bench queue (docs/sessions.md).
"""

from volsync_tpu.cluster.objects import (
    Volume,
    VolumeSpec,
    VolumeStatus,
    VolumeSnapshot,
    VolumeSnapshotSpec,
    VolumeSnapshotStatus,
    Job,
    JobSpec,
    JobStatus,
    Service,
    ServicePort,
    ServiceSpec,
    ServiceStatus,
    Secret,
    ServiceAccount,
    Deployment,
    DeploymentSpec,
    DeploymentStatus,
    Event,
)
from volsync_tpu.cluster.cluster import Cluster, NotFound, Conflict
from volsync_tpu.cluster.storage import StorageProvider
from volsync_tpu.cluster.runner import JobRunner, EntrypointCatalog
from volsync_tpu.cluster.sessions import (
    BenchQueue,
    FakeSessionBackend,
    FencedError,
    JaxSessionBackend,
    Lease,
    SessionBusy,
    SessionError,
    SessionSupervisor,
)

__all__ = [
    "Volume",
    "VolumeSpec",
    "VolumeStatus",
    "VolumeSnapshot",
    "VolumeSnapshotSpec",
    "VolumeSnapshotStatus",
    "Job",
    "JobSpec",
    "JobStatus",
    "Service",
    "ServicePort",
    "ServiceSpec",
    "ServiceStatus",
    "Secret",
    "ServiceAccount",
    "Deployment",
    "DeploymentSpec",
    "DeploymentStatus",
    "Event",
    "Cluster",
    "NotFound",
    "Conflict",
    "StorageProvider",
    "JobRunner",
    "EntrypointCatalog",
    "BenchQueue",
    "FakeSessionBackend",
    "FencedError",
    "JaxSessionBackend",
    "Lease",
    "SessionBusy",
    "SessionError",
    "SessionSupervisor",
]
