"""Batched SHA-256 as vectorized uint32 JAX ops (TPU VPU friendly).

This replaces the per-blob SHA-256 performed inside the reference's vendored
restic binary (reference: mover-restic/Dockerfile:7-10 pins restic v0.13.1,
whose repository format keys every blob/pack/index by SHA-256) and
syncthing's per-block SHA-256 (mover-syncthing/Dockerfile:9-21). The
reference runs these hot loops on CPU inside wrapped Unix binaries; here the
compression function is expressed as uint32 lane arithmetic so XLA maps it
onto the TPU vector unit, with *chunks as the batch dimension* — one TPU
chip hashes thousands of content-defined chunks concurrently.

Design notes
------------
- The sequential dependency of SHA-256 is *within* a chunk (64-byte message
  blocks chain through the compression function). Across chunks there is no
  dependency, so we ``lax.scan`` over block index and vectorize over the
  chunk batch: total step count = max_blocks, each step a [B]-wide
  compression. Lanes whose chunk is already finished are masked out.
- All arithmetic is uint32 with wraparound (XLA integer ops wrap, matching
  the spec's mod-2^32 adds). Rotations are shift-or pairs.
- Bit-exactness is enforced by golden tests against hashlib.

Two packing paths:
- ``sha256_pack_host``: numpy padding of a list of byte strings (control
  path, small metadata).
- ``sha256_chunks_device``: given a device-resident byte buffer and chunk
  (start, length) vectors, builds padded message blocks *on device* with
  gathers + masks — no host round-trip. This is the bulk data path used by
  the chunk/hash engine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# First 32 bits of the fractional parts of the cube roots of the first 64
# primes (FIPS 180-4 §4.2.2).
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

# Initial hash state (square roots of first 8 primes).
_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress_unrolled(state: jax.Array, block: jax.Array) -> jax.Array:
    """Straight-line SHA-256 compression: 64 SSA rounds, schedule fully
    unrolled. The TPU path — carries stay in vector registers."""
    w = [block[..., t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)

    a, b, c, d, e, f, g, h = (state[..., i] for i in range(8))
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = g ^ (e & (f ^ g))  # == (e&f)^(~e&g), one op fewer
        t1 = h + s1 + ch + _K[t] + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & (b | c)) | (b & c)  # == (a&b)^(a&c)^(b&c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=-1)
    return state + out


def _compress_scan(state: jax.Array, block: jax.Array) -> jax.Array:
    """Rolled SHA-256 compression: scan over 64 rounds with a rolling
    16-word schedule window. The CPU path — XLA's CPU backend takes
    minutes to compile the unrolled form (CPU is tests/dry-runs only,
    where compile time matters and throughput doesn't)."""
    K = jnp.asarray(_K, dtype=jnp.uint32)
    w0 = jnp.moveaxis(block, -1, 0)  # [16, ...] rolling schedule window
    abcdefgh = tuple(state[..., i] for i in range(8))

    def round_step(carry, t):
        (a, b, c, d, e, f, g, h), w = carry
        wt = w[0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = g ^ (e & (f ^ g))
        t1 = h + s1 + ch + K[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & (b | c)) | (b & c)
        t2 = s0 + maj
        state_new = (t1 + t2, a, b, c, d + t1, e, f, g)
        # Extend the schedule: w[t+16] from the window (FIPS 180-4 §6.2.2).
        sw0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ (w[1] >> np.uint32(3))
        sw1 = _rotr(w[14], 17) ^ _rotr(w[14], 19) ^ (w[14] >> np.uint32(10))
        w_next = w[0] + sw0 + w[9] + sw1
        w = jnp.concatenate([w[1:], w_next[None]], axis=0)
        return (state_new, w), None

    (final, _), _ = jax.lax.scan(
        round_step, (abcdefgh, w0), jnp.arange(64, dtype=jnp.int32)
    )
    return state + jnp.stack(final, axis=-1)


def _compress(state: jax.Array, block: jax.Array) -> jax.Array:
    """One SHA-256 compression over a batch.

    state: [..., 8] uint32;  block: [..., 16] uint32 (big-endian words).
    Picks the implementation by backend at trace time (jit caches are
    per-backend, so this is safe under jit).
    """
    if jax.default_backend() == "cpu":
        return _compress_scan(state, block)
    return _compress_unrolled(state, block)


@jax.jit
def sha256_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Hash a batch of pre-padded messages.

    blocks:  [B, N, 16] uint32 big-endian message words (already padded per
             FIPS 180-4: 0x80, zeros, 64-bit bit length).
    nblocks: [B] int32, number of valid 64-byte blocks per message (<= N).
    returns: [B, 8] uint32 digests.
    """
    B, N, _ = blocks.shape
    state0 = jnp.broadcast_to(jnp.asarray(_H0, dtype=jnp.uint32), (B, 8))
    # XOR with a zero slice of the input so the carry inherits the input's
    # shard_map varying-axis metadata (scan requires carry-in == carry-out;
    # a constant init would be "unvarying" while the output varies).
    state0 = state0 ^ (blocks[:, 0, :8] & jnp.uint32(0))
    xs_blocks = jnp.transpose(blocks, (1, 0, 2))  # [N, B, 16]
    active = (jnp.arange(N, dtype=jnp.int32)[:, None]
              < nblocks[None, :].astype(jnp.int32))  # [N, B]

    def step(state, xs):
        block, act = xs
        new = _compress(state, block)
        return jnp.where(act[:, None], new, state), None

    state, _ = jax.lax.scan(step, state0, (xs_blocks, active))
    return state


def sha256_pack_host(chunks: list[bytes], pad_batch_to: int | None = None,
                     pad_blocks_to: int | None = None):
    """Pad a list of messages into [B, N, 16] uint32 blocks + [B] nblocks.

    Optional padding of the batch / block dims limits jit recompiles (extra
    lanes carry nblocks=0 and are masked inside the scan).
    """
    B = len(chunks)
    nb = np.array([(len(c) + 9 + 63) // 64 for c in chunks], dtype=np.int32)
    N = int(nb.max()) if B else 1
    if pad_blocks_to is not None:
        N = max(N, 1)
        target = 1
        while target < N:
            target *= 2
        N = max(target, pad_blocks_to) if N > pad_blocks_to else pad_blocks_to
    Bp = B
    if pad_batch_to is not None:
        Bp = ((B + pad_batch_to - 1) // pad_batch_to) * pad_batch_to
        Bp = max(Bp, pad_batch_to)
    buf = np.zeros((Bp, N * 64), dtype=np.uint8)
    for i, c in enumerate(chunks):
        L = len(c)
        buf[i, :L] = np.frombuffer(c, dtype=np.uint8)
        buf[i, L] = 0x80
        bitlen = L * 8
        buf[i, nb[i] * 64 - 8 : nb[i] * 64] = np.frombuffer(
            np.array([bitlen], dtype=">u8").tobytes(), dtype=np.uint8  # lint: ignore[VL106] 8 B length field
        )
    words = buf.reshape(Bp, N, 16, 4).astype(np.uint32)
    blocks = (
        (words[..., 0] << 24) | (words[..., 1] << 16)
        | (words[..., 2] << 8) | words[..., 3]
    )
    nblocks = np.zeros((Bp,), dtype=np.int32)
    nblocks[:B] = nb
    return blocks, nblocks


def digest_bytes(digests: np.ndarray) -> list[bytes]:
    """[B, 8] uint32 -> list of 32-byte big-endian digests."""
    d = np.asarray(digests).astype(">u4")
    return [d[i].tobytes() for i in range(d.shape[0])]  # lint: ignore[VL106] 32 B digests


def sha256_many(chunks: list[bytes]) -> list[bytes]:
    """Convenience: hash a list of byte strings, returns 32-byte digests."""
    if not chunks:
        return []
    blocks, nblocks = sha256_pack_host(chunks, pad_batch_to=8, pad_blocks_to=1)
    out = sha256_blocks(jnp.asarray(blocks), jnp.asarray(nblocks))
    return digest_bytes(np.asarray(out))[: len(chunks)]  # lint: ignore[VL501] host-digest convenience API: one batched fetch


def pack_words_rows(r: jax.Array, *, little_endian: bool = False
                    ) -> jax.Array:
    """[B, 4*W] uint8 rows -> [B, W] uint32 words via 2-D minor-dim byte
    strides — the one TPU-safe packing layout (see pack_words: [*, 4]-
    minor arrays tile-pad 32x; 1-D stride-4 slices lower ~100x slower).
    Big-endian for SHA-256, little-endian for MD5."""
    b0 = r[:, 0::4].astype(jnp.uint32)
    b1 = r[:, 1::4].astype(jnp.uint32)
    b2 = r[:, 2::4].astype(jnp.uint32)
    b3 = r[:, 3::4].astype(jnp.uint32)
    if little_endian:
        return (b0 | (b1 << np.uint32(8)) | (b2 << np.uint32(16))
                | (b3 << np.uint32(24)))
    return ((b0 << np.uint32(24)) | (b1 << np.uint32(16))
            | (b2 << np.uint32(8)) | b3)


def pack_words(data: jax.Array) -> jax.Array:
    """[L] uint8 (L % 64 == 0) -> [L/64, 16] uint32 big-endian message
    blocks of the whole buffer — the strided, gather-free layout the
    aligned leaf path hashes from. NOT independently jitted: callers fuse
    it into their own jit so the 1x-data-sized word array never
    materializes across a dispatch boundary.

    Stride-4 byte lanes on a 2-D minor dim combine into big-endian
    words. Any variant routing through an [..., 4]-minor array
    (reshape+combine OR the bitcast trick, whose *input* is u8[L/4, 4])
    tile-pads the minor dim to 128 on TPU — a 32x HBM blowup that OOMs
    at 256 MiB segments — and 1-D stride-4 slices lower ~100x slower
    than the same stride on a 2-D minor dim (measured on v5e)."""
    L = data.shape[0]
    return pack_words_rows(data.reshape(L // 64, 64))


@functools.partial(jax.jit, static_argnames=("leaf_len",))
def sha256_leaves_device(data: jax.Array, rows0: jax.Array,
                         tail_starts: jax.Array, tail_lengths: jax.Array,
                         *, leaf_len: int = 4096) -> jax.Array:
    """ONE dispatch for a whole segment's Merkle leaves (aligned cuts).

    data: [L] uint8 resident buffer (L % 64 == 0);
    rows0: [F] int32 — block row of each FULL leaf (64B-aligned starts);
    tail_starts/tail_lengths: [T] int32 — the short tail leaves
    (< leaf_len), hashed via the generic gather path.
    Returns ONE [F + T, 8] uint32 array (full digests then tail digests)
    so the host needs exactly one result fetch.

    Packing, the strided full-leaf scan, and the tail gather fuse into a
    single program so no data-sized intermediate ever crosses a dispatch
    boundary (which costs ~1 GiB/s-scale stalls on remote-attached
    devices and wastes HBM on local ones).
    """
    wb = pack_words(data)
    if (leaf_len == 4096 and rows0.shape[0] % _LANE_TILE == 0
            and use_pallas_leaves()):
        full = _sha256_rows_pallas(wb, rows0)
    else:
        full = _sha256_rows(wb, rows0, leaf_len)
    tail = sha256_chunks_device(data, tail_starts, tail_lengths,
                                max_len=leaf_len)
    return jnp.concatenate([full, tail], axis=0)


def _sha256_rows(wb: jax.Array, rows0: jax.Array,
                 leaf_len: int) -> jax.Array:
    """SHA-256 of full, 64-byte-row-aligned slices of a packed buffer.

    wb:    [NB, 16] uint32 — pack_words(buffer).
    rows0: [B] int32 — first block row of each slice (all slices exactly
           ``leaf_len`` bytes, leaf_len % 64 == 0).
    returns [B, 8] uint32 digests.

    This is the aligned-cuts fast path (GearParams.align >= 64): every
    Merkle leaf's message blocks are whole rows of ``wb``, so each scan
    step is one row-gather [B, 16] — no byte gathers, no padding masks
    (the FIPS pad for a fixed full length is one constant extra block).
    Measured ~24x faster than the generic sha256_chunks_device gather
    path on v5e for 4 KiB leaves.
    """
    B = rows0.shape[0]
    nsteps = leaf_len // 64
    state0 = jnp.broadcast_to(jnp.asarray(_H0, dtype=jnp.uint32), (B, 8))
    state0 = state0 ^ (wb[rows0, :8] & jnp.uint32(0))  # varying-axis align

    def step(state, t):
        return _compress(state, wb[rows0 + t]), None

    state, _ = jax.lax.scan(step, state0,
                            jnp.arange(nsteps, dtype=jnp.int32))
    pad = np.zeros((16,), dtype=np.uint32)
    pad[0] = 0x80000000
    pad[14] = (leaf_len * 8) >> 32
    pad[15] = (leaf_len * 8) & 0xFFFFFFFF
    pad_block = (state[:, :1] & jnp.uint32(0)) ^ jnp.asarray(pad)[None, :]
    return _compress(state, pad_block)


# ---------------------------------------------------------------------------
# Pallas TPU kernel for the full-leaf bulk path
# ---------------------------------------------------------------------------
#
# XLA's scan-of-compressions is limited by per-step HBM round-trips of the
# carry and conservative scheduling. The Pallas kernel keeps the running
# digest state in a VMEM scratch across a (lane-tile, message-block) grid
# and unrolls the 64 rounds, so per grid step the only HBM traffic is one
# 16-word message tile read; the final pad-block compression and the
# 32-byte digest write happen on the last block step. Measured ~20% faster
# than the XLA scan on v5e (net of dispatch), bit-exact vs hashlib.

_LANE_SUB = 32                  # sublanes per lane tile (4 u32 vregs/op)
_LANE_TILE = _LANE_SUB * 128    # leaves per grid row


def _rotr_p(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _round64_p(state, w):
    """One full SHA-256 compression (64 unrolled rounds) on [S, 128]
    uint32 vector tiles; ``w`` is the 16-entry message-word list (extended
    in place to 64)."""
    a, b, c, d, e, f, g, h = state
    for r in range(64):
        if r < 16:
            wt = w[r]
        else:
            s0 = (_rotr_p(w[r - 15], 7) ^ _rotr_p(w[r - 15], 18)
                  ^ (w[r - 15] >> np.uint32(3)))
            s1 = (_rotr_p(w[r - 2], 17) ^ _rotr_p(w[r - 2], 19)
                  ^ (w[r - 2] >> np.uint32(10)))
            wt = w[r - 16] + s0 + w[r - 7] + s1
            w.append(wt)
        S1 = _rotr_p(e, 6) ^ _rotr_p(e, 11) ^ _rotr_p(e, 25)
        ch = g ^ (e & (f ^ g))  # == (e&f)^(~e&g), one op fewer
        t1 = h + S1 + ch + np.uint32(_K[r]) + wt
        S0 = _rotr_p(a, 2) ^ _rotr_p(a, 13) ^ _rotr_p(a, 22)
        maj = (a & (b | c)) | (b & c)  # == (a&b)^(a&c)^(b&c)
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + S0 + maj
    return tuple(x + y for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def _sha256_leaf_kernel(x_ref, o_ref, st_ref):
    """Grid (lane tiles, 64 message blocks), block t fastest. x_ref:
    [1, 16, S, 128] — this lane tile's words for block t; st_ref: [8, S,
    128] VMEM scratch carrying the digest state across block steps."""
    import jax.experimental.pallas as pl

    S = st_ref.shape[1]
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        for j in range(8):
            st_ref[j] = jnp.full((S, 128), np.uint32(_H0[j]), jnp.uint32)

    state = tuple(st_ref[j] for j in range(8))
    w = x_ref[0]  # [16, S, 128]
    state = _round64_p(state, [w[j] for j in range(16)])
    for j in range(8):
        st_ref[j] = state[j]

    @pl.when(t == 63)
    def _():
        # Constant FIPS pad block for a full 4096-byte message.
        zero = jnp.zeros((S, 128), jnp.uint32)
        pad = [zero + np.uint32(0x80000000)] + [zero] * 13 + [
            zero, zero + np.uint32(4096 * 8)]
        fin = _round64_p(state, pad)
        for j in range(8):
            o_ref[j] = fin[j]


def _sha256_rows_pallas(wb: jax.Array, rows0: jax.Array) -> jax.Array:
    """Full 4 KiB leaves via the Pallas kernel. rows0 length must be a
    multiple of _LANE_TILE (callers bucket lanes)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = rows0.shape[0]
    assert B % _LANE_TILE == 0
    # Gather each leaf's 64 message blocks, lanes minor for the VPU.
    gathered = wb[rows0[:, None] + jnp.arange(64, dtype=jnp.int32)[None, :]]
    x = jnp.transpose(gathered, (1, 2, 0))  # [64, 16, B]
    x = x.reshape(64, 16, B // 128, 128)

    out = pl.pallas_call(
        _sha256_leaf_kernel,
        grid=(B // _LANE_TILE, 64),
        in_specs=[pl.BlockSpec((1, 16, _LANE_SUB, 128),
                               lambda i, t: (t, 0, i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, _LANE_SUB, 128), lambda i, t: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, B // 128, 128), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((8, _LANE_SUB, 128), jnp.uint32)],
    )(x)
    return jnp.transpose(out, (1, 2, 0)).reshape(B, 8)


def use_pallas_leaves() -> bool:
    """The Pallas path runs on real TPU backends; tests/dry-runs on CPU
    use the XLA scan (identical digests, golden-tested on both).
    VOLSYNC_NO_PALLAS=1 forces the XLA scan everywhere (operational
    kill-switch for toolchains without Mosaic support)."""
    from volsync_tpu import envflags

    if envflags.no_pallas():
        return False
    return jax.default_backend() == "tpu"


@functools.partial(jax.jit, static_argnames=("max_len",))
def sha256_chunks_device(data: jax.Array, starts: jax.Array,
                         lengths: jax.Array, *, max_len: int) -> jax.Array:
    """Hash variable-length chunks of a device-resident byte buffer.

    data:    [L] uint8 — the flat volume/block buffer already on device.
    starts:  [B] int32 chunk start offsets into ``data``.
    lengths: [B] int32 chunk lengths (<= max_len; max_len < 2**28).
    returns: [B, 8] uint32 digests. Bit-exact vs hashlib on each chunk.

    The padded message (0x80 terminator + 64-bit bit length) is materialized
    on device with gathers and index masks, so the bulk path never leaves
    HBM. Lanes may have length 0 (digest of empty string — masked out by
    callers as needed).
    """
    assert max_len < (1 << 28), "bit length packed in uint32 lanes"
    B = starts.shape[0]
    L = data.shape[0]
    # Total padded bytes per lane: fixed at the max so shapes are static.
    padded = ((max_len + 9) + 63) // 64 * 64
    N = padded // 64

    starts = starts.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    j = jnp.arange(padded, dtype=jnp.int32)  # [P]
    idx = starts[:, None] + j[None, :]  # [B, P]
    idx = jnp.clip(idx, 0, L - 1)
    raw = data[idx]  # [B, P] uint8 gather

    lens = lengths[:, None]
    in_msg = j[None, :] < lens
    is_term = j[None, :] == lens
    msg = jnp.where(in_msg, raw, jnp.where(is_term, jnp.uint8(0x80), jnp.uint8(0)))

    # 64-bit big-endian bit length occupies the final 8 bytes of block
    # nb-1 where nb = ceil((len+9)/64). bitlen < 2^31 so the top 4 bytes
    # stay zero.
    nb = (lengths + 9 + 63) // 64  # [B]
    len_pos = nb[:, None] * 64 - 8  # [B, 1] position of first length byte
    k = j[None, :] - len_pos  # [B, P]; 0..7 inside the length field
    bitlen = (lengths.astype(jnp.uint32) << np.uint32(3))[:, None]  # [B,1]
    # Only bytes k in [4, 8) of the 8-byte field are nonzero (bitlen < 2^31);
    # clamp the shift to stay < 32 (XLA shift-by->=width is undefined).
    kc = jnp.clip(k, 4, 7).astype(jnp.uint32)
    shift = (jnp.uint32(7) - kc) * np.uint32(8)
    len_byte = ((bitlen >> shift) & np.uint32(0xFF)).astype(jnp.uint8)
    in_len_field = (k >= 4) & (k < 8)
    msg = jnp.where(in_len_field, len_byte, msg)

    words = msg.reshape(B, N, 16, 4).astype(jnp.uint32)
    blocks = (
        (words[..., 0] << np.uint32(24)) | (words[..., 1] << np.uint32(16))
        | (words[..., 2] << np.uint32(8)) | words[..., 3]
    )
    return sha256_blocks(blocks, nb)
