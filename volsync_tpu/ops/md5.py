"""Batched MD5 as vectorized uint32 JAX ops.

The reference's rsync mover uses MD5 as the strong per-block checksum in its
delta-transfer algorithm (reference: mover-rsync/source.sh:54 invokes
``rsync -aAhHSxz``; rsync's wire protocol pairs a rolling Adler-32-style
weak checksum with an MD5 strong checksum). Our delta engine
(volsync_tpu.engine.deltasync) verifies weak-checksum match candidates with
this batched MD5, vectorized across candidate offsets.

Same architecture as volsync_tpu.ops.sha256: ``lax.scan`` over 64-byte
message blocks, batch dimension across messages, uint32 wraparound lanes.
MD5 is little-endian (words and the trailing 64-bit length), unlike SHA-256.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

# T[i] = floor(2^32 * |sin(i+1)|) (RFC 1321 §3.4). Computed in double
# precision, which reproduces the canonical table; golden tests vs hashlib
# enforce bit-exactness.
_T = np.array(
    [int(math.floor(abs(math.sin(i + 1)) * 2**32)) & 0xFFFFFFFF for i in range(64)],
    dtype=np.uint32,
)

_S = np.array(
    [7, 12, 17, 22] * 4 + [5, 9, 14, 20] * 4 + [4, 11, 16, 23] * 4 + [6, 10, 15, 21] * 4,
    dtype=np.int32,
)

# Message word index per operation.
_G = np.array(
    [i for i in range(16)]
    + [(5 * i + 1) % 16 for i in range(16)]
    + [(3 * i + 5) % 16 for i in range(16)]
    + [(7 * i) % 16 for i in range(16)],
    dtype=np.int32,
)

_A0 = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476], dtype=np.uint32)


def _rotl(x: jax.Array, n) -> jax.Array:
    n = n if isinstance(n, jax.Array) else np.uint32(n)
    return (x << n) | (x >> (np.uint32(32) - n))


def _compress_unrolled(state: jax.Array, block: jax.Array) -> jax.Array:
    """Straight-line MD5 rounds (TPU path; see sha256._compress)."""
    m = [block[..., t] for t in range(16)]
    a, b, c, d = (state[..., i] for i in range(4))
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
        elif i < 32:
            f = (d & b) | (~d & c)
        elif i < 48:
            f = b ^ c ^ d
        else:
            f = c ^ (b | ~d)
        tmp = a + f + _T[i] + m[int(_G[i])]
        a, d, c, b = d, c, b, b + _rotl(tmp, int(_S[i]))
    out = jnp.stack([a, b, c, d], axis=-1)
    return state + out


def _compress_scan(state: jax.Array, block: jax.Array) -> jax.Array:
    """Rolled MD5 rounds (CPU path — fast compile): scan over the
    (T, S, G) tables; per-phase boolean function is a 4-way select on
    ``i // 16``."""
    m = jnp.moveaxis(block, -1, 0)  # [16, ...]
    quad = tuple(state[..., i] for i in range(4))
    xs = (
        jnp.arange(64, dtype=jnp.int32),
        jnp.asarray(_T),
        jnp.asarray(_S).astype(jnp.uint32),
        jnp.asarray(_G),
    )

    def round_step(carry, x):
        a, b, c, d = carry
        i, t_i, s_i, g_i = x
        phase = i >> 2 >> 2  # i // 16
        f = jnp.where(
            phase == 0, (b & c) | (~b & d),
            jnp.where(
                phase == 1, (d & b) | (~d & c),
                jnp.where(phase == 2, b ^ c ^ d, c ^ (b | ~d)),
            ),
        )
        tmp = a + f + t_i + m[g_i]
        return (d, b + _rotl(tmp, s_i), b, c), None

    (a, b, c, d), _ = jax.lax.scan(round_step, quad, xs)
    return state + jnp.stack([a, b, c, d], axis=-1)


def _compress(state: jax.Array, block: jax.Array) -> jax.Array:
    """state: [..., 4] uint32; block: [..., 16] uint32 little-endian words.
    Backend-selected at trace time (jit caches are per-backend)."""
    if jax.default_backend() == "cpu":
        return _compress_scan(state, block)
    return _compress_unrolled(state, block)


@jax.jit
def md5_blocks(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """blocks: [B, N, 16] uint32 LE words (padded); nblocks: [B] int32.

    Returns [B, 4] uint32 state words (little-endian serialization gives the
    standard digest).
    """
    B, N, _ = blocks.shape
    state0 = jnp.broadcast_to(jnp.asarray(_A0), (B, 4))
    # Align shard_map varying-axis metadata with the input (see sha256.py).
    state0 = state0 ^ (blocks[:, 0, :4] & jnp.uint32(0))
    xs_blocks = jnp.transpose(blocks, (1, 0, 2))
    active = (jnp.arange(N, dtype=jnp.int32)[:, None]
              < nblocks[None, :].astype(jnp.int32))

    def step(state, xs):
        block, act = xs
        new = _compress(state, block)
        return jnp.where(act[:, None], new, state), None

    state, _ = jax.lax.scan(step, state0, (xs_blocks, active))
    return state


def md5_pack_host(chunks: list[bytes]):
    """Pad messages into [B, N, 16] uint32 little-endian blocks + nblocks."""
    B = len(chunks)
    nb = np.array([(len(c) + 9 + 63) // 64 for c in chunks], dtype=np.int32)
    N = int(nb.max()) if B else 1
    buf = np.zeros((B, N * 64), dtype=np.uint8)
    for i, c in enumerate(chunks):
        L = len(c)
        buf[i, :L] = np.frombuffer(c, dtype=np.uint8)
        buf[i, L] = 0x80
        buf[i, nb[i] * 64 - 8 : nb[i] * 64] = np.frombuffer(
            np.array([L * 8], dtype="<u8").tobytes(), dtype=np.uint8  # lint: ignore[VL106] 8 B length field
        )
    words = buf.reshape(B, N, 16, 4).astype(np.uint32)
    blocks = (
        words[..., 0] | (words[..., 1] << 8)
        | (words[..., 2] << 16) | (words[..., 3] << 24)
    )
    return blocks, nb


def md5_many(chunks: list[bytes]) -> list[bytes]:
    """Hash byte strings; returns standard 16-byte MD5 digests."""
    if not chunks:
        return []
    blocks, nblocks = md5_pack_host(chunks)
    out = np.asarray(md5_blocks(jnp.asarray(blocks), jnp.asarray(nblocks)))  # lint: ignore[VL501] host-digest convenience API: one batched fetch
    le = out.astype("<u4")
    return [le[i].tobytes() for i in range(le.shape[0])]  # lint: ignore[VL106] 16 B digests


@functools.partial(jax.jit, static_argnames=("block_len",))
def md5_fixed_blocks_device(data: jax.Array, starts: jax.Array,
                            *, block_len: int) -> jax.Array:
    """MD5 of fixed-length windows of a device buffer (delta strong check).

    data: [L] uint8; starts: [B] int32 window starts; every window has
    length ``block_len`` (callers pad the tail window host-side or exclude
    it). Returns [B, 4] uint32 states.
    """
    B = starts.shape[0]
    L = data.shape[0]
    padded = (block_len + 9 + 63) // 64 * 64
    N = padded // 64
    j = jnp.arange(padded, dtype=jnp.int32)
    idx = jnp.clip(starts.astype(jnp.int32)[:, None] + j[None, :], 0, L - 1)
    raw = data[idx]
    msg = jnp.where(j[None, :] < block_len, raw,
                    jnp.where(j[None, :] == block_len, jnp.uint8(0x80), jnp.uint8(0)))
    # Little-endian 64-bit bit length in the final 8 bytes; block_len is
    # static so the length bytes are a host-computed constant row.
    len_bytes = np.zeros((padded,), dtype=np.uint8)
    len_bytes[-8:] = np.frombuffer(np.array([block_len * 8], dtype="<u8").tobytes(),  # lint: ignore[VL106] 8 B length field
                                   dtype=np.uint8)
    is_len = np.zeros((padded,), dtype=bool)
    is_len[-8:] = True
    msg = jnp.where(jnp.asarray(is_len)[None, :], jnp.asarray(len_bytes)[None, :], msg)
    words = msg.reshape(B, N, 16, 4).astype(jnp.uint32)
    blocks = (
        words[..., 0] | (words[..., 1] << np.uint32(8))
        | (words[..., 2] << np.uint32(16)) | (words[..., 3] << np.uint32(24))
    )
    nb = jnp.full((B,), N, dtype=jnp.int32)
    return md5_blocks(blocks, nb)


@functools.partial(jax.jit, static_argnames=("block_len",))
def md5_contiguous_blocks_device(data: jax.Array, *,
                                 block_len: int) -> jax.Array:
    """MD5 of every contiguous ``block_len`` window of ``data``
    ([L] uint8, L % block_len == 0) -> [L/block_len, 4] uint32 states.

    The delta signature's bulk path (engine/deltasync.build_signature:
    the destination's blocks tile its file, so its strong checksums
    never need the windowed gather of md5_fixed_blocks_device, which is
    reserved for sparse match verification). TPU-fast by construction
    (docs/performance.md op classes): little-endian words pack via 2-D
    minor-dim strides, a Pallas tile-transpose puts blocks on the lane
    axis, and the per-64-byte-block scan takes row slices of the
    transposed table — no data-sized XLA gather or transpose anywhere.
    block_len must be a multiple of 1024 (the Pallas transpose tiles
    256 word columns; pick_block_len yields pow2 >= 4 KiB) — the
    build_signature wrapper falls back to the windowed kernel for other
    sizes.
    """
    assert block_len % 1024 == 0, "fast path needs 256-word columns"
    from volsync_tpu.ops.sha256 import pack_words_rows

    L = data.shape[0]
    B = L // block_len
    r = data.reshape(B, block_len)
    w = pack_words_rows(r, little_endian=True)  # [B, W] LE words

    from volsync_tpu.ops.sha256 import use_pallas_leaves

    if not use_pallas_leaves():
        # Shares sha256's predicate (CPU backend OR the
        # VOLSYNC_NO_PALLAS kill-switch): the operational escape hatch
        # for a broken Mosaic toolchain must cover the MD5 delta path
        # too, not just the leaf hashers.
        xt = jnp.transpose(w, (1, 0))  # XLA transpose is fine here
        Bp = B
    else:
        from volsync_tpu.ops.segment import _pallas_transpose

        Bp = (B + 255) // 256 * 256
        if Bp != B:
            w = jnp.pad(w, ((0, Bp - B), (0, 0)))
        xt = _pallas_transpose(w)  # [W, Bp]

    state0 = jnp.broadcast_to(jnp.asarray(_A0), (Bp, 4))

    def step(state, t):
        m = jnp.stack(
            [jax.lax.dynamic_index_in_dim(xt, t * 16 + j, 0, False)
             for j in range(16)], axis=-1)  # [Bp, 16]
        return _compress(state, m), None

    state, _ = jax.lax.scan(step, state0,
                            jnp.arange(block_len // 64, dtype=jnp.int32))
    # FIPS pad for a fixed full-length message: one constant extra block
    # (0x80 terminator then the 64-bit LE bit length).
    pad = np.zeros((16,), dtype=np.uint32)
    pad[0] = 0x80
    bitlen = block_len * 8
    pad[14] = bitlen & 0xFFFFFFFF
    pad[15] = (bitlen >> 32) & 0xFFFFFFFF
    pad_block = jnp.broadcast_to(jnp.asarray(pad), (Bp, 16))
    return _compress(state, pad_block)[:B]
