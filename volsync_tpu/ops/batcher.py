"""Cross-stream segment microbatching (shared by service + local engine).

Concurrent producers — gRPC ChunkHash handlers (service/server.py) or
TreeBackup's per-file workers (engine/backup.py) — submit segments
that coalesce into ONE batched device dispatch
(ops/segment.chunk_hash_segments): the service/engine-side form of
BASELINE configs[5]'s cross-PVC batching. A lone producer pays at most
``window_ms``; a busy pipeline pays it never (the queue is already
non-empty when the worker looks).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future

from volsync_tpu import envflags
from volsync_tpu.analysis import lockcheck
from volsync_tpu.obs import span
from volsync_tpu.ops.gearcdc import GearParams


class BatcherStopped(RuntimeError):
    """submit() after stop(), or work stranded by shutdown. Typed so
    the service layer can map it to a clean UNAVAILABLE instead of
    pattern-matching a RuntimeError message."""


class SegmentMicroBatcher:
    """Queue + worker thread: the first item waits up to ``window_ms``
    for companions (bounded by ``max_batch``), the batch dispatches via
    BatchedSegmentHasher, and each caller's future resolves with its
    lane. ``stop()`` drains the queue — a future enqueued before stop
    is always resolved, never stranded."""

    def __init__(self, params: GearParams, *, max_batch: int = 16,
                 window_ms: float = 2.0, pipeline_depth: int = 2):
        from volsync_tpu.ops.segment import BatchedSegmentHasher

        self._hasher = BatchedSegmentHasher(params)
        self._q: queue.Queue = queue.Queue()
        self._max_batch = max_batch
        self._window = window_ms / 1000.0
        # Up to ``pipeline_depth`` batches in flight: while one dispatch
        # waits out the device round trip (~80 ms through a serving
        # tunnel; ~100 us local), the collector assembles and launches
        # the next — the result-latency/compute overlap measured as the
        # r4 bench's pipelined win. The semaphore bounds in-flight
        # batches so producer backpressure (blocking submit) still
        # holds. Depth 1 restores strict one-at-a-time dispatch.
        #
        # Dispatchers are hand-rolled DAEMON threads, not a
        # ThreadPoolExecutor: the executor's non-daemon workers register
        # an interpreter-exit join, so a shared_batcher (never stopped)
        # with a dispatch wedged on a dead tunnel would hang process
        # exit. Daemon threads preserve "the process can always exit".
        self._depth = max(1, pipeline_depth)
        self._inflight = threading.BoundedSemaphore(self._depth)
        self._dq: queue.Queue = queue.Queue()
        self._dispatchers = [
            threading.Thread(target=self._dispatch_loop, daemon=True,
                             name=f"segment-batch-{i}")
            for i in range(self._depth)]
        for t in self._dispatchers:
            t.start()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="segment-microbatcher")
        self._thread.start()

    def submit(self, data: bytes, length: int, eof: bool):
        """Blocking: returns (chunks, consumed) for this segment."""
        # The worker resolves every queued future (including at
        # shutdown); the timeout is a last-ditch liveness bound so a
        # producer thread can never hang the interpreter.
        return self.submit_async(data, length, eof).result(timeout=600)

    def submit_async(self, data: bytes, length: int, eof: bool) -> Future:
        """Non-blocking enqueue: the future resolves with
        (chunks, consumed) for this segment. The service scheduler
        (service/scheduler.py) feeds the batcher through this so its
        deficit-round-robin thread never blocks on a device round
        trip."""
        if self._stop.is_set():
            raise BatcherStopped("microbatcher stopped")
        f: Future = Future()
        self._q.put((data, length, eof, f))
        return f

    def _run(self):
        import time as time_mod

        while True:
            try:
                first = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [first]
            deadline = time_mod.monotonic() + self._window
            while len(batch) < self._max_batch:
                remaining = deadline - time_mod.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            # Interruptible slot wait: if every dispatch slot stays
            # occupied for 30 s AFTER stop() fires (the same bound
            # stop() grants in-flight dispatches — a healthy-but-slow
            # pipeline frees a slot well within it), the pipeline is
            # wedged: fail the in-hand batch instead of blocking
            # forever with popped futures that stop()'s queue drain
            # can no longer reach.
            acquired = stop_deadline = None
            while True:
                if self._inflight.acquire(timeout=0.2):
                    acquired = True
                    break
                if not self._stop.is_set():
                    continue
                now = time_mod.monotonic()
                if stop_deadline is None:
                    stop_deadline = now + 30.0
                elif now >= stop_deadline:
                    break
            if not acquired:
                exc = BatcherStopped("microbatcher stopped")
                for _, _, _, f in batch:
                    if not f.done():
                        f.set_exception(exc)
                return
            self._dq.put(batch)

    def _dispatch_loop(self):
        while True:
            batch = self._dq.get()
            try:
                # One span per coalesced device dispatch. A batch mixes
                # segments from many streams/traces, so this span is
                # context-free; per-stream attribution happens in the
                # scheduler's svc.batch span around each future.
                with span("ops.batch_dispatch", lanes=len(batch)):
                    results = self._hasher.hash_segments(
                        [(d, n, e) for d, n, e, _ in batch])
                for (_, _, _, f), r in zip(batch, results):
                    f.set_result(r)
            except Exception as exc:  # noqa: BLE001 — per-caller delivery
                for _, _, _, f in batch:
                    if not f.done():
                        f.set_exception(exc)
            finally:
                self._inflight.release()

    def stop(self):
        """Stop accepting work, then let the collector DRAIN the queue:
        it exits only via the empty-queue check, so a future enqueued
        before stop() is always resolved, never stranded. In-flight
        dispatches run on daemon threads — wait (bounded) for them to
        resolve their futures; a dispatch wedged past the bound can
        never block process exit."""
        self._stop.set()
        self._thread.join(timeout=30.0)
        # Drain the in-flight window by taking every slot (bounded wait).
        got = 0
        deadline = 30.0
        import time as time_mod
        t_end = time_mod.monotonic() + deadline
        for _ in range(self._depth):
            if self._inflight.acquire(
                    timeout=max(0.0, t_end - time_mod.monotonic())):
                got += 1
        for _ in range(got):
            self._inflight.release()
        # Belt-and-braces: if the collector died abnormally, fail
        # leftovers still queued.
        while True:
            try:
                _, _, _, f = self._q.get_nowait()
            except queue.Empty:
                break
            if not f.done():
                f.set_exception(BatcherStopped("microbatcher stopped"))


_SHARED: dict = {}
_SHARED_LOCK = lockcheck.make_lock("batcher.shared")


def _batching_enabled() -> bool:
    """VOLSYNC_BATCH_SEGMENTS: "1" forces on, "0"/"false"/"no" forces
    off. Unset -> backend-aware default: ON on real TPU backends (the
    measured ~7 ms/dispatch execution overhead and ~80 ms result round
    trip make coalescing a clear win there), OFF on the CPU backend
    (compute-bound; batching measurably loses)."""
    forced = envflags.batch_segments_override()
    if forced is not None:
        return forced
    import jax

    return jax.default_backend() == "tpu"


def shared_batcher(params: GearParams):
    """Process-wide microbatcher per chunker-params (the local engine's
    batching path): TreeBackup workers hashing different files — and
    different CRs' movers in one operator process — coalesce through
    one instance. Returns None when batching is disabled (see
    _batching_enabled: default follows the backend) or the params
    aren't page-aligned."""
    if not _batching_enabled():
        return None
    if params.align != 4096:
        return None
    with _SHARED_LOCK:
        b = _SHARED.get(params)
        if b is None:
            b = _SHARED[params] = SegmentMicroBatcher(
                params,
                max_batch=envflags.batch_max(),
                window_ms=envflags.batch_window_ms(),
                pipeline_depth=envflags.batch_pipeline_depth())
        return b
