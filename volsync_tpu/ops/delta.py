"""Device-side primitives for the rsync-style delta scan.

The reference's delta transfer happens inside the rsync binary (reference:
mover-rsync/source.sh:54): the destination sends per-block (weak, strong)
checksums; the source slides the weak checksum over every offset, and on a
weak match verifies with the strong checksum, emitting copy ops for matched
blocks and literal bytes for the rest.

TPU mapping: the full rolling-weak scan is one parallel pass
(volsync_tpu.ops.rolling); membership against the destination's weak set is
a vectorized binary search (jnp.searchsorted) over the sorted signature;
candidate offsets are compacted on device; strong verification batches MD5
over the candidate windows (volsync_tpu.ops.md5.md5_fixed_blocks_device).
The final greedy left-to-right op selection (sequential, but only over the
sparse verified matches) runs on host in the engine layer
(volsync_tpu.engine.deltasync).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from volsync_tpu.ops.md5 import (
    md5_contiguous_blocks_device,
    md5_fixed_blocks_device,
)
from volsync_tpu.ops.rolling import block_weak_checksums, rolling_weak_checksums


def build_signature(data: jax.Array, *, block_len: int):
    """Destination side: per-block (weak uint32, strong md5 [nb,4] uint32).

    The tail block's strong checksum is computed over its true length by the
    host wrapper in the engine; here all full blocks are batched on device.
    """
    weak = block_weak_checksums(data, block_len=block_len)
    L = int(data.shape[0])
    n_full = L // block_len
    if block_len % 1024 == 0:
        # The destination's blocks tile the file contiguously: the
        # strong checksums take the gather-free transposed-lane path
        # (pick_block_len sizes are always eligible; the windowed
        # gather kernel stays for sparse match verification and for
        # caller-chosen odd block sizes).
        strong = md5_contiguous_blocks_device(
            jax.lax.slice_in_dim(data, 0, n_full * block_len),
            block_len=block_len)
    else:
        starts = jnp.arange(n_full, dtype=jnp.int32) * block_len
        strong = md5_fixed_blocks_device(data, starts,
                                         block_len=block_len)
    return weak, strong


@functools.partial(jax.jit, static_argnames=("window", "max_candidates"))
def match_offsets(data: jax.Array, sorted_weak: jax.Array, *,
                  window: int, max_candidates: int):
    """Source side: offsets whose rolling weak checksum hits the signature.

    data:        [L] uint8 source buffer.
    sorted_weak: [nb] uint32, destination block weak checksums, sorted.
    Returns (cand_idx [max_candidates] int32 ascending with L as fill,
    true_count) — host re-runs with a larger bound on truncation.
    """
    L = data.shape[0]
    if sorted_weak.shape[0] == 0 or L < window:  # static: no possible match
        return (jnp.full((max_candidates,), L, dtype=jnp.int32),
                jnp.zeros((), dtype=jnp.int32))
    weak = rolling_weak_checksums(data, window=window)  # [L-window+1]
    pos = jnp.searchsorted(sorted_weak, weak)
    pos = jnp.clip(pos, 0, sorted_weak.shape[0] - 1)
    hit = sorted_weak[pos] == weak
    cand = jnp.nonzero(hit, size=max_candidates, fill_value=L)[0]
    return cand.astype(jnp.int32), jnp.sum(hit)


def verify_candidates(data: jax.Array, cand: np.ndarray, *,
                      block_len: int) -> np.ndarray:
    """Batch MD5 over candidate windows -> [n, 4] uint32 states (host array)."""
    if len(cand) == 0:
        return np.zeros((0, 4), dtype=np.uint32)
    starts = jnp.asarray(np.asarray(cand, dtype=np.int32))
    return np.asarray(md5_fixed_blocks_device(data, starts, block_len=block_len))  # lint: ignore[VL501] host-result contract: one batched strong-check fetch


_M16 = np.uint32(0xFFFF)


@functools.partial(jax.jit, static_argnames=("window", "max_candidates"))
def match_offsets_batch(data: jax.Array, sorted_weak: jax.Array,
                        nb: jax.Array, nscan: jax.Array, *,
                        window: int, max_candidates: int):
    """Multi-file ``match_offsets``: one rolling scan + membership pass
    over a whole padded file batch (engine/deltasync.delta_scan_batch).

    data:        [n, L] uint8, one zero-padded file per row.
    sorted_weak: [n, nb_cap] uint32 per-row sorted signature weak sets,
                 0xFFFFFFFF-padded past each row's true count.
    nb:          [n] int32 true signature lengths (masks the padding —
                 a real weak equal to the sentinel still matches inside
                 its row's first ``nb`` entries, exactly like the serial
                 clip-then-compare).
    nscan:       [n] int32 valid scan offsets per row (len - window + 1);
                 offsets whose window would read padding are masked out,
                 which is what makes the batch candidate set per row
                 identical to the serial per-file scan.

    Returns (cand [max_candidates] int32 ascending row-major flattened
    indices into [n, L-window+1] with n*(L-window+1) as fill,
    true_count) — the host re-runs with a doubled bound on truncation,
    same ladder as the serial path.
    """
    n, L = data.shape
    width = L - window + 1
    # Rolling weak checksum of every row at every offset, batched: the
    # same prefix-sum identity as ops/rolling.py with cumsums along the
    # row axis (uint32 wraparound keeps the mod-2^16 residues exact).
    x = data.astype(jnp.uint32)
    j = jnp.arange(L, dtype=jnp.uint32)[None, :]
    S = jnp.pad(jnp.cumsum(x, axis=1, dtype=jnp.uint32), ((0, 0), (1, 0)))
    T = jnp.pad(jnp.cumsum(j * x, axis=1, dtype=jnp.uint32), ((0, 0), (1, 0)))
    k = jnp.arange(width, dtype=jnp.uint32)[None, :]
    dS = S[:, window:] - S[:, :width]
    dT = T[:, window:] - T[:, :width]
    a = dS & _M16
    b = ((k + np.uint32(window)) * dS - dT) & _M16
    weak = a | (b << np.uint32(16))                      # [n, width]
    # Per-row membership against that row's sorted signature.
    pos = jax.vmap(jnp.searchsorted)(sorted_weak, weak)  # [n, width]
    clipped = jnp.minimum(pos, sorted_weak.shape[1] - 1)
    found = jnp.take_along_axis(sorted_weak, clipped, axis=1)
    hit = (found == weak) & (pos < nb[:, None])
    hit = hit & (jnp.arange(width, dtype=jnp.int32)[None, :]
                 < nscan[:, None])
    flat = hit.reshape(-1)
    cand = jnp.nonzero(flat, size=max_candidates, fill_value=n * width)[0]
    return cand.astype(jnp.int32), jnp.sum(hit)


def verify_candidates_batch(data: jax.Array, rows: np.ndarray,
                            offs: np.ndarray, *,
                            block_len: int) -> np.ndarray:
    """Batch MD5 over candidate windows across a padded [n, L] file
    batch -> [k, 4] uint32 states. One dispatch for the whole batch:
    rows flatten to offsets into the [n*L] buffer, and a candidate
    window never crosses a row boundary (offs <= row_len - block_len)."""
    if len(rows) == 0:
        return np.zeros((0, 4), dtype=np.uint32)
    L = data.shape[1]
    starts = (np.asarray(rows, dtype=np.int64) * L
              + np.asarray(offs, dtype=np.int64)).astype(np.int32)
    return np.asarray(md5_fixed_blocks_device(  # lint: ignore[VL501] host-result contract: one batched strong-check fetch
        data.reshape(-1), jnp.asarray(starts), block_len=block_len))
