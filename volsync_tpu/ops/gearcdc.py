"""Content-defined chunking with a gear rolling hash, TPU-parallel.

Replaces the Rabin-fingerprint content-defined chunking inside the
reference's vendored restic engine (reference: mover-restic/Dockerfile:7-10;
restic cuts blobs with a 64-byte Rabin window, min 512KiB / avg 1MiB / max
8MiB). This is a clean-room design with equivalent *semantics* (content-
defined cut points, min/avg/max bounds, deterministic for identical content)
built around a gear hash, which is the TPU-friendly choice:

    h_i = (h_{i-1} << 1) + G[b_i]  (mod 2^32)
        = sum_{k=0}^{31} 2^k * G[b_{i-k}]          -- exactly 32-byte window

Because the shift drops bits after 32 steps, the hash at position ``i`` is a
pure function of the trailing 32 bytes — no sequential carry survives, so
the whole buffer can be hashed *in parallel*. We compute it in log2(32)=5
doubling passes of shift-scale-add over uint32 lanes:

    h^(2m)_i = h^(m)_i + 2^m * h^(m)_{i-m}

(a parallel prefix specialized to the mod-2^32 linear recurrence). Boundary
candidates are positions where the top bits of ``h`` vanish under a mask
(high bits carry the most mixing for gear). FastCDC-style normalization
uses a harder mask before the average size and an easier one after, which
tightens the chunk-size distribution. Final boundary *selection* (min/max
enforcement, which is sequential but touches only the sparse candidate
list) runs on host over compacted candidate indices.

Chunk determinism: boundaries depend only on content in the trailing 32
bytes plus the previous boundary, so identical content yields identical
chunks regardless of how the buffer was segmented for streaming (the engine
carries a 31-byte halo between segments).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

_WINDOW = 32  # bytes of context in a 32-bit gear hash


def _mix_u32(x):
    """Murmur3-style finalizer: full-avalanche u32 mixing with 6 vector
    ops — the gear table as a *function*. A 256-entry gather would
    serialize on the TPU VPU (gathers are scalar-ish; measured ~100x
    slower than arithmetic), so the device evaluates this directly on the
    byte lanes and the host materializes the identical 256-entry table for
    the scalar/streaming paths. numpy and jax.numpy both wrap mod 2^32."""
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    return x ^ (x >> np.uint32(16))


def _make_gear_table(seed: int) -> np.ndarray:
    b = np.arange(256, dtype=np.uint32)
    with np.errstate(over="ignore"):
        return _mix_u32(b + np.uint32(seed & 0xFFFFFFFF))


def _pow2ceil_int(n: int, lo: int) -> int:
    """Pow2 bucketing for retry capacities — arbitrary sizes would mint a
    fresh XLA compile per distinct value."""
    v = lo
    while v < n:
        v *= 2
    return v


def _top_mask(bits: int) -> int:
    """Mask selecting the top ``bits`` bits of a uint32."""
    bits = max(1, min(bits, 31))
    return (((1 << bits) - 1) << (32 - bits)) & 0xFFFFFFFF


@dataclasses.dataclass(frozen=True)
class GearParams:
    """CDC parameters. Defaults mirror restic's chunker envelope.

    ``align`` constrains cut positions so every chunk start is a multiple
    of ``align`` (the mask is evaluated only at eligible positions, with
    its bit count reduced by log2(align) to keep the same average chunk
    size). align=64 is the TPU-native default: the gear window at an
    eligible position sits entirely inside one 64-byte row (no halo), the
    candidate compaction shrinks 64x, and — the big one — every Merkle
    leaf becomes 64-byte-row-aligned so leaf hashing runs the strided
    (gather-free) SHA-256 layout. The trade: chunk boundaries are content
    -defined only modulo the 64-byte phase, so an insertion of k bytes
    (k % 64 != 0) inside one large file re-chunks that file's tail
    (cross-snapshot dedup of unshifted/whole-file/appended data — the
    dominant backup pattern — is unaffected). ``align=1`` restores the
    reference engine's fully shift-invariant behavior and the gather
    hashing path.
    """

    min_size: int = 512 * 1024
    avg_size: int = 1024 * 1024
    max_size: int = 8 * 1024 * 1024
    seed: int = 0x5EED_CDC1
    norm_level: int = 2  # FastCDC normalization: mask_s=bits+n, mask_l=bits-n
    align: int = 64

    def __post_init__(self):
        assert self.min_size >= _WINDOW
        assert self.min_size <= self.avg_size <= self.max_size
        assert self.avg_size & (self.avg_size - 1) == 0, "avg_size must be 2^k"
        assert self.align >= 1 and self.align & (self.align - 1) == 0
        if self.align > 1:
            # The aligned kernel reads the gear window from one row.
            assert self.align >= _WINDOW, "align must be >= the gear window"
            assert self.min_size % self.align == 0
            assert self.max_size % self.align == 0
            assert self.eff_bits - self.norm_level >= 1, \
                "avg_size too small for this align/norm combination"

    @property
    def bits(self) -> int:
        return int(self.avg_size).bit_length() - 1

    @property
    def eff_bits(self) -> int:
        """Mask bits after discounting the 1/align eligible positions:
        candidate density stays 2^-bits overall."""
        return self.bits - (int(self.align).bit_length() - 1)

    @property
    def mask_s(self) -> int:
        """Strict mask for ALIGNED evaluation (applied at 1/align
        positions — the align discount keeps overall candidate density
        at 2^-(bits+norm))."""
        return _top_mask(self.eff_bits + self.norm_level)

    @property
    def mask_l(self) -> int:
        return _top_mask(self.eff_bits - self.norm_level)

    @property
    def dense_mask_s(self) -> int:
        """Strict mask for PER-POSITION evaluation (no align discount) —
        what consumers applying the mask at every byte must use, e.g. the
        (wave, seq) batch step in parallel/engine.py."""
        return _top_mask(self.bits + self.norm_level)

    @property
    def dense_mask_l(self) -> int:
        return _top_mask(self.bits - self.norm_level)

    @functools.cached_property
    def table(self) -> np.ndarray:
        return _make_gear_table(self.seed)


#: Repo-format default: page-aligned cuts (align == the 4 KiB Merkle
#: leaf). Every full leaf of every chunk is then a PAGE of the stream,
#: so the fused engine (ops/segment.py) hashes leaves contiguously — no
#: data-sized gather/transpose outside Pallas, which on TPU is the
#: difference between ~1% and ~100% of HBM bandwidth. The trade (cuts
#: are content-defined modulo the 4 KiB phase) only affects dedup of
#: data that moved by a non-page-multiple offset within a file;
#: whole-file, unshifted, and appended dedup — the dominant backup
#: pattern — is unaffected. align=64 keeps the finer-grained split-phase
#: engine; align=1 the fully shift-invariant legacy behavior.
DEFAULT_PARAMS = GearParams(align=4096)


def gear_hash_positions(data: jax.Array, seed: int) -> jax.Array:
    """Gear hash at every byte position of ``data`` ([L] uint8 -> [L] uint32).

    Positions < 31 hash a shorter prefix window (consistent with the
    recurrence started from h=0); boundary selection never uses them because
    min_size >= 32. The per-byte table value is computed arithmetically
    (``_mix_u32``) — no gather.
    """
    g = _mix_u32(data.astype(jnp.uint32) + np.uint32(seed & 0xFFFFFFFF))
    h = g
    for m in (1, 2, 4, 8, 16):
        shifted = jnp.pad(h[:-m], (m, 0))
        h = h + (shifted << np.uint32(m))
    return h


def gear_at_aligned(data: jax.Array, seed: int, align: int) -> jax.Array:
    """Gear hash evaluated only at positions p = r*align + align-1
    ([L] uint8, L % align == 0 -> [L/align] uint32).

    For align >= 32 the 32-byte window ending at p lies inside row r
    (columns align-32..align-1), so this is a pure reshape + weighted
    row-sum: h_p = sum_m G[s_m] << (31-m) over the window bytes s_0..s_31
    — ~32x less arithmetic than hashing every position, no halo, no
    shift-doubling passes.
    """
    L = data.shape[0]
    rows = data.reshape(L // align, align)[:, align - _WINDOW:]
    g = _mix_u32(rows.astype(jnp.uint32) + np.uint32(seed & 0xFFFFFFFF))
    shifts = np.arange(_WINDOW - 1, -1, -1, dtype=np.uint32)  # 31..0
    return jnp.sum(g << shifts[None, :], axis=1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("seed", "max_candidates",
                                             "mask_s", "mask_l", "align"))
def cdc_candidates_aligned(data: jax.Array, *, seed: int,
                           mask_s: int, mask_l: int, align: int,
                           max_candidates: int, valid_len=None):
    """Aligned-cut candidate compaction: one nonzero over L/align lanes.

    Because the strict mask's zero-bits are a superset of the lax mask's
    (top_mask(eff+n) ⊃ top_mask(eff-n)), is_s ⊆ is_l — so only the lax
    candidates are compacted, each carrying its strict flag; the host
    splits them. Returns (positions [cap] int32 cut positions, strict
    flags [cap] bool, true count).
    """
    h = gear_at_aligned(data, seed, align)
    R = h.shape[0]
    is_s = (h & np.uint32(mask_s)) == 0
    is_l = (h & np.uint32(mask_l)) == 0
    if valid_len is not None:
        pos_ok = (jnp.arange(R, dtype=jnp.int32) * align + (align - 1)) \
            < valid_len
        is_s = is_s & pos_ok
        is_l = is_l & pos_ok
    ridx = jnp.nonzero(is_l, size=max_candidates, fill_value=R)[0]
    flags = jnp.where(ridx < R, is_s[jnp.clip(ridx, 0, R - 1)], False)
    pos = ridx.astype(jnp.int32) * align + (align - 1)
    return pos, flags, jnp.sum(is_l)


@functools.partial(jax.jit, static_argnames=("seed", "mask_s", "mask_l",
                                             "align", "max_candidates"))
def cdc_candidates_aligned_packed(data: jax.Array, *, seed: int,
                                  mask_s: int, mask_l: int, align: int,
                                  max_candidates: int, valid_len=None):
    """cdc_candidates_aligned with all three outputs packed into ONE
    int32 array [2*cap + 1] = (positions, strict flags, count) — a single
    result fetch per segment (result round-trips dominate on
    remote-attached devices)."""
    pos, flags, count = cdc_candidates_aligned(
        data, seed=seed, mask_s=mask_s, mask_l=mask_l, align=align,
        max_candidates=max_candidates, valid_len=valid_len)
    return jnp.concatenate([pos.astype(jnp.int32), flags.astype(jnp.int32),
                            count[None].astype(jnp.int32)])


@functools.partial(jax.jit, static_argnames=("seed", "max_candidates",
                                             "mask_s", "mask_l"))
def cdc_candidates(data: jax.Array, *, seed: int,
                   mask_s: int, mask_l: int, max_candidates: int,
                   valid_len=None):
    """Compute compacted candidate cut positions on device.

    Returns (idx_s, count_s, idx_l, count_l): positions where
    ``h & mask == 0`` for the strict / lax masks, as the first
    ``max_candidates`` indices in order plus the *true* total counts (host
    re-runs with a larger bound if truncated, keeping chunking
    deterministic).

    ``valid_len`` (traced scalar) restricts candidates and counts to
    positions < valid_len, so zero-padding a bucketed buffer can neither
    add candidates nor inflate the counts the overflow retry keys on.
    """
    h = gear_hash_positions(data, seed)
    is_s = (h & np.uint32(mask_s)) == 0
    is_l = (h & np.uint32(mask_l)) == 0
    L = data.shape[0]
    if valid_len is not None:
        pos_ok = jnp.arange(L, dtype=jnp.int32) < valid_len
        is_s = is_s & pos_ok
        is_l = is_l & pos_ok
    idx_s = jnp.nonzero(is_s, size=max_candidates, fill_value=L)[0]
    idx_l = jnp.nonzero(is_l, size=max_candidates, fill_value=L)[0]
    return idx_s, jnp.sum(is_s), idx_l, jnp.sum(is_l)


def select_boundaries(idx_s: np.ndarray, idx_l: np.ndarray, length: int,
                      params: GearParams, *, eof: bool = True,
                      base: int = 0) -> list[tuple[int, int]]:
    """FastCDC walk over sparse candidates -> [(start, length), ...].

    ``idx_*`` are sorted candidate cut positions *relative to this buffer*
    (cut after position i => chunk ends at i+1). ``base`` is added only to
    the emitted chunk start offsets, so streaming callers get absolute
    (start, length) pairs while passing buffer-relative candidates.

    If ``eof`` is False the tail (which might extend into the next segment)
    is not emitted; the caller resumes from the returned position.

    Dispatches to the native C walk (native/volio.cpp) when the library
    is available; ``_select_boundaries_py`` is the reference
    implementation, and the golden tests pin their equality.
    """
    try:
        from volsync_tpu.io.native import select_boundaries_native

        out = select_boundaries_native(idx_s, idx_l, length, params,
                                       eof, base)
        if out is not None:
            return out
    except Exception:  # lint: ignore[VL003] — native is an accelerator,
        pass           # not a dep: ANY native failure falls through to
        #              # the pure-Python reference on this per-segment
        #              # hot path (logging here would spam every call)
    return _select_boundaries_py(idx_s, idx_l, length, params, eof=eof,
                                 base=base)


def _select_boundaries_py(idx_s: np.ndarray, idx_l: np.ndarray, length: int,
                          params: GearParams, *, eof: bool = True,
                          base: int = 0) -> list[tuple[int, int]]:
    """Pure-Python reference walk (see select_boundaries)."""
    chunks: list[tuple[int, int]] = []
    pos = 0
    while pos < length:
        lo = pos + params.min_size - 1  # earliest cut position (chunk len >= min)
        mid = pos + params.avg_size - 1
        hi = pos + params.max_size - 1  # latest cut position (chunk len <= max)
        cut = None
        i = np.searchsorted(idx_s, lo, side="left")
        if i < len(idx_s) and idx_s[i] <= min(mid - 1, length - 1, hi):
            cut = int(idx_s[i])
        if cut is None:
            j = np.searchsorted(idx_l, max(lo, mid), side="left")
            if j < len(idx_l) and idx_l[j] <= min(hi, length - 1):
                cut = int(idx_l[j])
        if cut is None:
            if hi <= length - 1:
                cut = hi
            elif eof:
                cut = length - 1  # final short chunk
            else:
                break  # tail continues into the next segment
        chunks.append((base + pos, cut - pos + 1))
        pos = cut + 1
    return chunks


def chunk_buffer(data, params: GearParams = DEFAULT_PARAMS,
                 *, eof: bool = True) -> list[tuple[int, int]]:
    """Chunk a byte buffer (numpy uint8 / bytes / jax array) on device.

    Returns [(start, length)] covering the buffer (the last chunk may be
    shorter than min_size iff ``eof``).
    """
    if isinstance(data, (bytes, bytearray, memoryview)):
        data = np.frombuffer(data, dtype=np.uint8)
    length = int(data.shape[0])
    if length == 0:
        return []
    if length <= params.min_size:
        return [(0, length)] if eof else []
    if params.align > 1:
        padded = (length + params.align - 1) // params.align * params.align
        buf = np.pad(np.asarray(data), (0, padded - length)) \
            if padded != length else np.asarray(data)
        dev = jnp.asarray(buf, dtype=jnp.uint8)
        cap = 4096
        while True:
            pos, flags, count = cdc_candidates_aligned(
                dev, seed=params.seed, mask_s=params.mask_s,
                mask_l=params.mask_l, align=params.align,
                max_candidates=cap, valid_len=length)
            c = int(count)
            if c <= cap:
                break
            cap = _pow2ceil_int(c, cap * 2)
        pos = np.asarray(pos)[:c]
        flags = np.asarray(flags)[:c]
        return select_boundaries(pos[flags], pos, length, params, eof=eof)
    dev = jnp.asarray(data, dtype=jnp.uint8)
    # Expected candidate density is 2^-(bits-norm) for the lax mask; leave
    # generous headroom, and retry exactly if real data is denser.
    guess = max(1024, 8 * length // max(1, params.avg_size >> (params.norm_level + 1)))
    while True:
        idx_s, count_s, idx_l, count_l = cdc_candidates(
            dev, seed=params.seed, mask_s=params.mask_s, mask_l=params.mask_l,
            max_candidates=min(guess, length),
        )
        cs, cl = int(count_s), int(count_l)
        if max(cs, cl) <= guess or guess >= length:
            break
        guess = min(length, max(cs, cl) + 1024)
    idx_s = np.asarray(idx_s)[:cs]
    idx_l = np.asarray(idx_l)[:cl]
    return select_boundaries(idx_s, idx_l, length, params, eof=eof)
