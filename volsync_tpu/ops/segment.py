"""Fused single-dispatch segment pipeline: chunk + hash + Merkle roots.

The per-segment protocol of the original engine (engine/chunker.py) was
two device dispatches with two result fetches: (1) compacted CDC
candidates -> host FastCDC walk, (2) leaf digests -> host root assembly.
Every result fetch costs a fixed round trip (~70 ms through a serving
tunnel; ~100 us on a local TPU VM), and the digest fetch moves 32 bytes
per 4 KiB leaf — ~8 MiB per GiB of input. This module collapses the
whole segment into ONE device program with ONE small result fetch
(~20 KiB: the chunk table + one 32-byte blob id per chunk).

The enabling format choice is ``GearParams.align == 4096``: cut
positions land on the 4 KiB Merkle-leaf grid, so every full leaf of
every chunk IS a page of the segment — leaf hashing becomes *contiguous*
page hashing with no gather at all, and at most ONE leaf per segment
(the final eof tail) is partial. That matters because on TPU the only
fast bulk primitives are elementwise/reduction ops and Pallas kernels:
XLA-level gathers and transposes of data-sized arrays run at ~1% of HBM
bandwidth on the serving-tunnel AOT path (measured), so the pipeline is
built exclusively from:

- elementwise candidate masks + small ``nonzero`` compactions;
- a ``lax.while_loop`` FastCDC walk over compacted candidates,
  bit-identical to ``gearcdc._select_boundaries_py`` (golden-tested);
- a Pallas tile-transpose (VMEM shuffles, ~HBM speed) feeding the
  Pallas SHA-256 lane kernel, digests kept in kernel layout;
- a root stage that hashes "VMRK1" || le64(len) || leaf-digests
  (repo/blobid.py) with a while_loop over message blocks — a 17-word
  gather per block per chunk lane, nothing data-sized.

Replaces the hot loop of the reference's vendored restic engine
(reference: mover-restic/entry.sh:63, Dockerfile:7-10) on its real
streaming path; engine/chunker.DeviceChunkHasher dispatches this program
when the page-aligned format is active.

Capacity model: all shapes are static under jit. ``segment_caps`` sizes
the candidate/chunk tables from the segment length with generous
headroom; the packed result carries the TRUE counts and the host
retries with doubled capacities iff real data overflowed (adversarial
inputs only). eof is a static arg (two compiled variants per shape).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from volsync_tpu.obs import record_copy
from volsync_tpu.ops.gearcdc import GearParams, gear_at_aligned
from volsync_tpu.ops.sha256 import (
    _H0,
    _LANE_SUB,
    _LANE_TILE,
    _compress,
    _sha256_leaf_kernel,
    _sha256_rows,
    pack_words,
    sha256_chunks_device,
    use_pallas_leaves,
)

LEAF_SIZE = 4096  # == repo.blobid.LEAF_SIZE (static repo format constant)

#: Largest flat [S*P] byte view one batched dispatch may address: the
#: view is gathered with int32 indices (x64 off; TPUs index in int32).
#: chunk_hash_segments refuses bigger batches; BatchedSegmentHasher
#: splits them. Module constant so tests can exercise the split with
#: small shapes.
_MAX_FLAT_BYTES = (1 << 31) - 1
_DOMAIN_WORD0 = int.from_bytes(b"VMRK", "big")  # "VMRK1" header, word 0
_DOMAIN_BYTE4 = b"VMRK1"[4]


from volsync_tpu.ops.gearcdc import _pow2ceil_int as _pow2ceil


def segment_caps(padded_len: int, params: GearParams) -> tuple[int, int]:
    """(cand_cap, chunk_cap) for a padded segment length.

    Expected lax-candidate density is 2^-(eff_bits-norm) per aligned
    position — the default gives ~8-16x headroom. chunk_cap covers the
    min_size packing bound exactly (+ slack for the eof tail)."""
    chunk_cap = _pow2ceil(padded_len // params.min_size + 2, 16)
    cand_cap = max(4096, _pow2ceil(4 * padded_len // params.avg_size, 4096))
    return cand_cap, chunk_cap


def _compact_candidates(mask: jax.Array, cand_cap: int, R: int,
                        align: int) -> jax.Array:
    """[R] bool candidate mask -> [cand_cap] sorted aligned cut
    positions, sentinel-padded (sentinel > any valid position). The one
    compaction used by BOTH the single-segment and batched programs —
    the sentinel/fill protocol must never drift between them."""
    sentinel = jnp.int32(2**31 - 2)
    ridx = jnp.nonzero(mask, size=cand_cap, fill_value=R)[0]
    return jnp.where(ridx < R,
                     ridx.astype(jnp.int32) * align + (align - 1),
                     sentinel)


def _use_pagemajor() -> bool:
    """Opt-in page-major digest-table layout (word j of page p at
    p*8 + j instead of j*n_pages_pad + p): each root-loop lane then
    gathers CONTIGUOUS 16U+1-word runs instead of 8-plane strides.
    Off by default until the on-chip A/B (scripts/profile_root.py
    measures both via the word_index override) proves it; the mesh
    path always stays word-major (its cross-shard word_index assumes
    the per-shard kernel layout)."""
    from volsync_tpu.envflags import env_bool

    return env_bool("VOLSYNC_PAGEMAJOR")


def _word_index_fn(n_pages_pad: int, pagemajor: bool):
    """THE home of the digest-table index formula — every producer,
    tail override, root gather, and host decode must route through
    this one mapping or the layouts silently desynchronize."""
    if pagemajor:
        return lambda j, p: p * 8 + j
    return lambda j, p: j * n_pages_pad + p


def _apply_tail_overrides(flat: jax.Array, n_pages_pad: int,
                          tail_pages: jax.Array, tail_digs: jax.Array,
                          has_tail: jax.Array,
                          pagemajor: bool | None = None) -> jax.Array:
    """Overwrite the page-digest table with per-lane partial tail-leaf
    digests (lanes with has_tail False write out of bounds -> dropped).
    tail_pages/has_tail: [N]; tail_digs: [N, 8]. Shared by the single,
    batched, and span programs so the layout indexing (word-major:
    digest word j of page p at j*n_pages_pad + p; page-major: at
    p*8 + j) has ONE home."""
    if pagemajor is None:
        pagemajor = _use_pagemajor()
    wi = _word_index_fn(n_pages_pad, pagemajor)
    j8 = jnp.arange(8, dtype=jnp.int32)[None, :]
    ovr = jnp.where(has_tail[:, None], wi(j8, tail_pages[:, None]),
                    8 * n_pages_pad)  # OOB -> dropped
    return flat.at[ovr.reshape(-1)].set(tail_digs.reshape(-1), mode="drop")


def _select_boundaries_device(pos_s, ns, pos_l, nl, valid_len, *,
                              min_size: int, avg_size: int, max_size: int,
                              chunk_cap: int, eof: bool,
                              align: int = 0, n_rows: int = 0):
    """FastCDC walk == gearcdc._select_boundaries_py, successor-table
    form.

    pos_s/pos_l: sorted compacted candidate cut positions (padded with a
    sentinel greater than any valid position); ns/nl their true counts.
    Returns (starts[chunk_cap], lens[chunk_cap], count, consumed).

    With the page-aligned format every reachable chunk start is a
    multiple of ``align`` (cuts are ≡ align-1 mod align; the max_size
    fallback advances by a page multiple), so the cut decision is a pure
    function of the start ROW. The cut/emit tables for ALL ``n_rows``
    possible starts are precomputed with two BATCHED searchsorted calls
    (one vector op each), and the sequential walk degrades to a
    per-step table gather. Measured on v5e (64 MiB): ~6 ms of
    per-iteration searchsorted pairs -> <1 ms. ``align``/``n_rows`` == 0
    keeps the generic per-iteration form (callers without row
    structure).
    """
    i32 = jnp.int32
    L = valid_len.astype(i32)
    cap_s = pos_s.shape[0]
    cap_l = pos_l.shape[0]

    def cut_emit(pos):
        """(cut, emit) of a chunk starting at ``pos`` — scalar in the
        per-iteration form, [n_rows] in the table precompute. ONE home
        for the FastCDC decision so the two forms cannot drift."""
        lo = pos + (min_size - 1)
        mid = pos + (avg_size - 1)
        hi = pos + (max_size - 1)
        i = jnp.searchsorted(pos_s, lo, side="left").astype(i32)
        cs = pos_s[jnp.clip(i, 0, cap_s - 1)]
        lim_s = jnp.minimum(jnp.minimum(mid - 1, L - 1), hi)
        found_s = (i < ns) & (cs <= lim_s)
        j = jnp.searchsorted(pos_l, jnp.maximum(lo, mid),
                             side="left").astype(i32)
        cl = pos_l[jnp.clip(j, 0, cap_l - 1)]
        found_l = (j < nl) & (cl <= jnp.minimum(hi, L - 1))
        hi_ok = hi <= L - 1
        cut = jnp.where(found_s, cs,
                        jnp.where(found_l, cl,
                                  jnp.where(hi_ok, hi, L - 1)))
        # eof may be a static Python bool (single-segment path, part of
        # the jit cache key) OR a traced per-lane scalar (batched path).
        emit = found_s | found_l | hi_ok | jnp.asarray(eof, jnp.bool_)
        return cut, emit

    use_table = (align > 0 and (align & (align - 1)) == 0 and n_rows > 0
                 and min_size % align == 0 and max_size % align == 0
                 and avg_size % align == 0)
    if use_table:
        # Successor tables over every possible start row: two BATCHED
        # searchsorted calls replace a searchsorted pair per iteration.
        cut_tab, emit_tab = cut_emit(jnp.arange(n_rows, dtype=i32) * align)
        shift = int(align).bit_length() - 1

    def cond(c):
        pos, cnt, done, _, _ = c
        return (~done) & (pos < L) & (cnt < chunk_cap)

    def body(c):
        pos, cnt, done, starts, lens = c
        if use_table:
            r = jnp.clip(pos >> shift, 0, n_rows - 1)
            cut = cut_tab[r]
            emit = emit_tab[r]
        else:
            cut, emit = cut_emit(pos)
        # Predicated append: drop the write when not emitting.
        wr = jnp.where(emit, cnt, chunk_cap)
        starts = starts.at[wr].set(pos, mode="drop")
        lens = lens.at[wr].set(cut - pos + 1, mode="drop")
        return (jnp.where(emit, cut + 1, pos), cnt + emit.astype(i32),
                ~emit, starts, lens)

    init = (jnp.int32(0), jnp.int32(0), jnp.bool_(False),
            jnp.zeros((chunk_cap,), i32), jnp.zeros((chunk_cap,), i32))
    pos, cnt, _, starts, lens = jax.lax.while_loop(cond, body, init)
    return starts, lens, cnt, pos


# ---------------------------------------------------------------------------
# Page-digest stage: contiguous leaf hashing, no gathers
# ---------------------------------------------------------------------------

def _n_pages_pad(F: int) -> int:
    """Page count padded for the Pallas lane grid (identity on CPU).
    The single source of truth — chunk_hash_segment, page_digests, and
    span_roots_device must agree or their word-major indexing into
    _page_digests_flat desynchronizes."""
    if not use_pallas_leaves():
        return F
    return max(_LANE_TILE, (F + _LANE_TILE - 1) // _LANE_TILE * _LANE_TILE)


def _transpose_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...].T


def _pallas_transpose(x: jax.Array) -> jax.Array:
    """[R, C] u32 -> [C, R] via VMEM tile shuffles. XLA's own transpose
    lowering runs at ~0.1 GiB/s on the tunnel AOT path; this runs at
    ~HBM speed. R % 256 == 0, C % 256 == 0."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = x.shape
    return pl.pallas_call(
        _transpose_kernel,
        grid=(R // 256, C // 256),
        in_specs=[pl.BlockSpec((256, 256), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((256, 256), lambda i, j: (j, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, R), jnp.uint32),
    )(x)


def _relayout_kernel(x_ref, o_ref):
    # [8, 512] (word j x page p) -> [32, 128] page-major flat rows:
    # x.T element order is p-major, j-minor == the page-major stream.
    o_ref[...] = x_ref[...].T.reshape(32, 128)


def _pallas_pagemajor(out: jax.Array, n_pages_pad: int) -> jax.Array:
    """Kernel-layout digests [8, npp/128, 128] -> [npp*8] page-major
    via VMEM shuffles (an XLA transpose of the data-sized table runs at
    ~1% of HBM speed on the tunnel AOT path; this is the same trick as
    _pallas_transpose at the digest table's shape)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = out.reshape(8, n_pages_pad)
    y = pl.pallas_call(
        _relayout_kernel,
        grid=(n_pages_pad // 512,),
        in_specs=[pl.BlockSpec((8, 512), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((32, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pages_pad * 8 // 128, 128),
                                       jnp.uint32),
    )(x)
    return y.reshape(-1)


def _page_digests_flat(data: jax.Array, n_pages_pad: int,
                       pagemajor: bool | None = None) -> jax.Array:
    """SHA-256 of every 4 KiB page of ``data``, flat layout: by default
    WORD-MAJOR (result[j * n_pages_pad + p] = word j of page p's
    digest); ``pagemajor`` (default: the VOLSYNC_PAGEMAJOR gate) packs
    page p's 8 words contiguously at p*8 instead.

    data: [P] uint8, P % LEAF_SIZE == 0; hashes are computed for
    ``n_pages_pad`` >= P/LEAF_SIZE pages (the pad region hashes zeros
    and is never referenced by the root stage).

    TPU: pack_words (elementwise) -> Pallas tile-transpose -> the
    Pallas SHA lane kernel; the digest output stays in the kernel's
    [8, B/128, 128] layout, whose row-major flattening IS word-major
    (page-major adds one small Pallas relayout pass over the
    1/128-data-sized table). CPU (tests/dry-runs): the XLA scan path +
    a small transpose.
    """
    P = data.shape[0]
    F = P // LEAF_SIZE
    if pagemajor is None:
        pagemajor = _use_pagemajor()

    if not use_pallas_leaves():
        wb = pack_words(data)  # [P/64, 16]
        rows0 = jnp.arange(n_pages_pad, dtype=jnp.int32) * (LEAF_SIZE // 64)
        rows0 = jnp.minimum(rows0, P // 64 - LEAF_SIZE // 64)
        dig = _sha256_rows(wb, rows0, LEAF_SIZE)  # [n_pages_pad, 8]
        if pagemajor:
            return dig.reshape(-1)
        return dig.T.reshape(-1)

    # Words packed straight into [F, 1024]: any [*, 16]-minor layout
    # tile-pads 8x on TPU, and 1-D stride-4 slices lower ~100x slower
    # than the same stride on a 2-D minor dim (measured) — so: page
    # rows first, then minor-dim byte strides.
    r = data.reshape(F, LEAF_SIZE)
    b0 = r[:, 0::4].astype(jnp.uint32)
    b1 = r[:, 1::4].astype(jnp.uint32)
    b2 = r[:, 2::4].astype(jnp.uint32)
    b3 = r[:, 3::4].astype(jnp.uint32)
    x2 = ((b0 << np.uint32(24)) | (b1 << np.uint32(16))
          | (b2 << np.uint32(8)) | b3)  # [F, 1024]
    if n_pages_pad != F:
        x2 = jnp.pad(x2, ((0, n_pages_pad - F), (0, 0)))
    xt = _pallas_transpose(x2)  # [1024, n_pages_pad]
    x = xt.reshape(64, 16, n_pages_pad // 128, 128)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        _sha256_leaf_kernel,
        grid=(n_pages_pad // _LANE_TILE, 64),
        in_specs=[pl.BlockSpec((1, 16, _LANE_SUB, 128),
                               lambda i, t: (t, 0, i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, _LANE_SUB, 128), lambda i, t: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, n_pages_pad // 128, 128),
                                       jnp.uint32),
        scratch_shapes=[pltpu.VMEM((8, _LANE_SUB, 128), jnp.uint32)],
    )(x)
    if pagemajor:
        return _pallas_pagemajor(out, n_pages_pad)
    return out.reshape(-1)  # [8 * n_pages_pad], word-major


# ---------------------------------------------------------------------------
# Root stage: while_loop over message blocks, small per-block gathers
# ---------------------------------------------------------------------------

def _root_digests_loop(flat, n_pages_pad: int, page0, nleaves, lens, live,
                       word_index=None):
    """Blob ids (repo/blobid.py: SHA-256 of "VMRK1" || le64(len) ||
    leaf digests) from word-major page digests.

    flat: flattened u32 page digests; by default word j of page p lives
    at j*n_pages_pad + p (word-major kernel layout), or at p*8 + j when
    the VOLSYNC_PAGEMAJOR gate is on (tail-leaf override already
    applied either way). ``word_index(j, p)`` overrides the mapping —
    the mesh-sharded path passes the all-gathered per-shard layout's
    index function. page0: [C_cap] first page of each chunk;
    nleaves/lens/live: the chunk table.

    The digest stream of chunk c is D(t) = flat[word_index(t%8,
    page0[c] + t//8)]. The 13-byte header shifts it to byte offset
    13 = 4*3+1, so message word q >= 4 is the byte-splice
    (D(q-4) << 24) | (D(q-3) >> 8); words 0..3 are header constants and
    the FIPS terminator/bit-length overlay at computed word indices.
    A while_loop runs only to the LARGEST live chunk's block count —
    per iteration one [C_cap, 17]-word gather + one compression, so
    low-entropy segments (few, max_size chunks) don't pay a
    max-possible-length scan.
    """
    C_cap = page0.shape[0]
    nl8 = 8 * nleaves  # digest stream length in words
    nb = (32 * nleaves + 13 + 9 + 63) // 64  # true block counts [C_cap]
    max_nb = jnp.max(jnp.where(live, nb, 0))
    qterm = 3 + nl8  # word holding the 0x80 terminator (byte 1)
    qlen = nb * 16 - 1  # word holding the bit length
    bitlen = (13 + 32 * nleaves.astype(jnp.uint32)) * jnp.uint32(8)

    lens_u = lens.astype(jnp.uint32)
    w1 = ((jnp.uint32(_DOMAIN_BYTE4) << jnp.uint32(24))
          | ((lens_u & jnp.uint32(0xFF)) << jnp.uint32(16))
          | (((lens_u >> jnp.uint32(8)) & jnp.uint32(0xFF)) << jnp.uint32(8))
          | ((lens_u >> jnp.uint32(16)) & jnp.uint32(0xFF)))
    w2 = ((lens_u >> jnp.uint32(24)) & jnp.uint32(0xFF)) << jnp.uint32(24)

    Fp = n_pages_pad
    if word_index is None:
        word_index = _word_index_fn(Fp, _use_pagemajor())
    # U message blocks per while iteration: ONE [C_cap, 16U+1] gather
    # covers all U sub-blocks (each needs D words m*16-4+j, j<=16 — the
    # sub-slices overlap by one word), so the loop pays the gather and
    # loop-carry overhead once per U compressions. The compressions
    # themselves chain (SHA is sequential per lane) — U trades overhead,
    # not parallelism.
    # Tuning knob for profiling runs only: read at TRACE time and not
    # part of any jit cache key, so it must be set before the first
    # compile of a shape in a fresh process. envflags clamps U >= 1
    # (U = 0 would make the loop body a no-op that never advances n —
    # device hang).
    from volsync_tpu import envflags
    U = envflags.root_unroll()
    jj = jnp.arange(16 * U + 1, dtype=jnp.int32)[None, :]
    q16 = jnp.arange(16, dtype=jnp.int32)[None, :]

    def cond(c):
        return c[0] < max_nb

    def body(c):
        n, state = c
        t = n * 16 - 4 + jj  # [1, 16U+1] broadcast over lanes
        tc = jnp.clip(t, 0, Fp * 8 - 1)
        idx = word_index(tc % 8, page0[:, None] + tc // 8)
        d = flat[jnp.clip(idx, 0, flat.shape[0] - 1)]  # [C_cap, 16U+1]
        d = jnp.where((t >= 0) & (t < nl8[:, None]), d, jnp.uint32(0))
        for u in range(U):
            m = n + u
            du = d[:, 16 * u: 16 * u + 17]  # this sub-block's 17 words
            blk = (du[:, :16] << jnp.uint32(24)) \
                | (du[:, 1:] >> jnp.uint32(8))
            q = m * 16 + q16  # [1,16]
            blk = jnp.where(q == 0, jnp.uint32(_DOMAIN_WORD0), blk)
            blk = jnp.where(q == 1, w1[:, None], blk)
            blk = jnp.where(q == 2, w2[:, None], blk)
            blk = jnp.where(q == 3, du[:, 4:5] >> jnp.uint32(8), blk)
            blk = jnp.where(q == qterm[:, None],
                            blk | jnp.uint32(0x00800000), blk)
            blk = jnp.where(q == qlen[:, None], bitlen[:, None], blk)
            new = _compress(state, blk)
            keep = (m < nb)[:, None]
            state = jnp.where(keep, new, state)
        return n + U, state

    state0 = jnp.broadcast_to(jnp.asarray(_H0), (C_cap, 8))
    _, state = jax.lax.while_loop(cond, body, (jnp.int32(0), state0))
    return state


@functools.partial(
    jax.jit,
    static_argnames=("min_size", "avg_size", "max_size", "seed", "mask_s",
                     "mask_l", "align", "eof", "cand_cap", "chunk_cap"))
def chunk_hash_segment(data: jax.Array, valid_len, *, min_size: int,
                       avg_size: int, max_size: int, seed: int, mask_s: int,
                       mask_l: int, align: int, eof: bool, cand_cap: int,
                       chunk_cap: int) -> jax.Array:
    """The whole segment in one device program, one small result.

    data: [P] uint8, P % LEAF_SIZE == 0 (zero-padded; candidates beyond
    ``valid_len`` are masked); requires align == LEAF_SIZE (the
    page-aligned cut format). Returns ONE uint32 array
    ``[4 + chunk_cap*10]``: header (count, consumed, true lax-candidate
    count, page count) then starts[chunk_cap], lens[chunk_cap],
    roots[chunk_cap*8]. Decode with ``decode_segment``.
    """
    assert align == LEAF_SIZE, "fused path requires page-aligned cuts"
    P = data.shape[0]
    R = P // align
    F = P // LEAF_SIZE
    n_pages_pad = _n_pages_pad(F)
    valid_len = jnp.asarray(valid_len, jnp.int32)

    # --- candidates (aligned gear evaluation, as cdc_candidates_aligned)
    h = gear_at_aligned(data, seed, align)
    pos_all = (jnp.arange(R, dtype=jnp.int32) * align + (align - 1))
    ok = pos_all < valid_len
    is_s = ((h & np.uint32(mask_s)) == 0) & ok
    is_l = ((h & np.uint32(mask_l)) == 0) & ok
    pos_s = _compact_candidates(is_s, cand_cap, R, align)
    pos_l = _compact_candidates(is_l, cand_cap, R, align)
    ns = jnp.sum(is_s).astype(jnp.int32)
    nl = jnp.sum(is_l).astype(jnp.int32)

    # --- FastCDC boundary walk (on device)
    starts, lens, count, consumed = _select_boundaries_device(
        pos_s, jnp.minimum(ns, cand_cap), pos_l, jnp.minimum(nl, cand_cap),
        valid_len, min_size=min_size, avg_size=avg_size, max_size=max_size,
        chunk_cap=chunk_cap, eof=eof, align=align, n_rows=R)

    # --- page digests (all full leaves are pages; no gather)
    flat = _page_digests_flat(data, n_pages_pad)

    # --- the ONE possibly-partial leaf: the final chunk's tail page.
    # Interior cuts land on the page grid (align == LEAF_SIZE and
    # min/avg/max are page multiples), so only the last chunk (eof, or
    # a chunk_cap-overflow remainder) can end off-grid.
    live = jnp.arange(chunk_cap, dtype=jnp.int32) < count
    end = jnp.where(count > 0,
                    starts[jnp.maximum(count - 1, 0)]
                    + lens[jnp.maximum(count - 1, 0)], 0)
    has_tail = (count > 0) & (end % LEAF_SIZE != 0)
    tail_page = jnp.maximum(end - 1, 0) // LEAF_SIZE
    tail_len = end - tail_page * LEAF_SIZE
    tail_dig = sha256_chunks_device(
        data, (tail_page * LEAF_SIZE)[None],
        jnp.where(has_tail, tail_len, 0)[None], max_len=LEAF_SIZE)
    flat = _apply_tail_overrides(flat, n_pages_pad, tail_page[None],
                                 tail_dig, has_tail[None])

    # --- roots
    nleaves = jnp.where(live, (lens + (LEAF_SIZE - 1)) // LEAF_SIZE, 0)
    page0 = starts // LEAF_SIZE
    roots = _root_digests_loop(flat, n_pages_pad, page0, nleaves, lens, live)

    header = jnp.stack([count.astype(jnp.uint32),
                        consumed.astype(jnp.uint32),
                        nl.astype(jnp.uint32),
                        jnp.sum(nleaves).astype(jnp.uint32)])
    return jnp.concatenate([
        header, starts.astype(jnp.uint32), lens.astype(jnp.uint32),
        roots.reshape(-1)])


def _chunk_hash_segments_impl(data: jax.Array, valid_len: jax.Array,
                              eof: jax.Array, *, min_size: int,
                              avg_size: int, max_size: int, seed: int,
                              mask_s: int, mask_l: int,
                              align: int, cand_cap: int,
                              chunk_cap: int) -> jax.Array:
    """MANY independent segments in ONE device program — the cross-PVC
    batched form of ``chunk_hash_segment`` (BASELINE configs[5]: many
    concurrent relationships share one chip; batching their segments
    into one dispatch replaces S dispatch/fetch round-trips with one).

    data: [S, P] uint8 (each row a zero-padded segment, P % 4096 == 0);
    valid_len: [S] int32; eof: [S] bool — both TRACED, so one compiled
    program serves every batch composition. Padding lanes use
    valid_len == 0. Returns [S, 4 + chunk_cap*10] packed rows, each
    decodable with ``decode_segment``.

    Stage economics vs S separate dispatches: page hashing runs as ONE
    Pallas lane batch over all S*P/4096 pages (better MXU/VPU occupancy
    for small segments), the FastCDC walk vmaps (one masked while_loop
    to the slowest lane), and root assembly runs as a single
    S*chunk_cap-lane loop. One fetch returns every stream's chunk
    table.
    """
    assert align == LEAF_SIZE, "fused path requires page-aligned cuts"
    S, P = data.shape
    if S * P > _MAX_FLAT_BYTES:
        # The flat [S*P] view is gathered with int32 indices (x64 is
        # off; TPUs index in int32) — a >=2 GiB batch silently can't.
        # BatchedSegmentHasher splits batches to stay under the bound;
        # the bench ladder respects it too.
        raise ValueError(
            f"batched dispatch of {S}x{P} bytes exceeds the int32 "
            f"index space (2 GiB); split the batch")
    R = P // align
    F = P // LEAF_SIZE
    npp = _n_pages_pad(S * F)
    valid_len = jnp.asarray(valid_len, jnp.int32)
    eof = jnp.asarray(eof, jnp.bool_)

    flat = data.reshape(S * P)
    # --- candidates: gear is page-local, so the flat evaluation equals
    # the per-segment one; masks reshape back to [S, R].
    h = gear_at_aligned(flat, seed, align).reshape(S, R)
    pos_all = jnp.arange(R, dtype=jnp.int32) * align + (align - 1)
    ok = pos_all[None, :] < valid_len[:, None]
    is_s = ((h & np.uint32(mask_s)) == 0) & ok
    is_l = ((h & np.uint32(mask_l)) == 0) & ok

    def compact(row):
        return _compact_candidates(row, cand_cap, R, align)

    pos_s = jax.vmap(compact)(is_s)
    pos_l = jax.vmap(compact)(is_l)
    ns = jnp.sum(is_s, axis=1).astype(jnp.int32)
    nl = jnp.sum(is_l, axis=1).astype(jnp.int32)

    # --- FastCDC walk per lane (vmapped masked while_loop)
    def walk(ps, n_s, plx, n_l, vl, e):
        return _select_boundaries_device(
            ps, jnp.minimum(n_s, cand_cap), plx, jnp.minimum(n_l, cand_cap),
            vl, min_size=min_size, avg_size=avg_size, max_size=max_size,
            chunk_cap=chunk_cap, eof=e, align=align, n_rows=R)

    starts, lens, count, consumed = jax.vmap(walk)(pos_s, ns, pos_l, nl,
                                                   valid_len, eof)

    # --- page digests: ONE kernel batch over every page of every lane
    digests = _page_digests_flat(flat, npp)

    # --- per-lane tail override (each lane has at most one partial leaf)
    live = (jnp.arange(chunk_cap, dtype=jnp.int32)[None, :]
            < count[:, None])
    last = jnp.maximum(count - 1, 0)
    end = jnp.where(count > 0,
                    jnp.take_along_axis(starts, last[:, None], axis=1)[:, 0]
                    + jnp.take_along_axis(lens, last[:, None], axis=1)[:, 0],
                    0)
    has_tail = (count > 0) & (end % LEAF_SIZE != 0)
    tail_page_local = jnp.maximum(end - 1, 0) // LEAF_SIZE
    tail_page = jnp.arange(S, dtype=jnp.int32) * F + tail_page_local
    tail_len = end - tail_page_local * LEAF_SIZE
    tail_dig = sha256_chunks_device(
        flat, jnp.clip(tail_page * LEAF_SIZE, 0, S * P - 1),
        jnp.where(has_tail, tail_len, 0), max_len=LEAF_SIZE)  # [S, 8]
    digests = _apply_tail_overrides(digests, npp, tail_page, tail_dig[:S],
                                    has_tail)

    # --- roots: one flat S*chunk_cap-lane loop over the shared digest
    # table (page0 offset per lane's segment)
    nleaves = jnp.where(live, (lens + (LEAF_SIZE - 1)) // LEAF_SIZE, 0)
    page0 = (starts // LEAF_SIZE
             + (jnp.arange(S, dtype=jnp.int32) * F)[:, None])
    roots = _root_digests_loop(
        digests, npp, page0.reshape(-1), nleaves.reshape(-1),
        lens.reshape(-1), live.reshape(-1))  # [S*chunk_cap, 8]

    header = jnp.stack([count.astype(jnp.uint32),
                        consumed.astype(jnp.uint32),
                        jnp.broadcast_to(nl, count.shape).astype(jnp.uint32),
                        jnp.sum(nleaves, axis=1).astype(jnp.uint32)],
                       axis=1)  # [S, 4]
    return jnp.concatenate([
        header, starts.astype(jnp.uint32), lens.astype(jnp.uint32),
        roots.reshape(S, chunk_cap * 8)], axis=1)


_SEGMENTS_STATIC = ("min_size", "avg_size", "max_size", "seed", "mask_s",
                    "mask_l", "align", "cand_cap", "chunk_cap")

#: normal variant — the staged [S, P] device rows stay alive after the
#: dispatch (callers that re-read them must use this)
chunk_hash_segments = functools.partial(
    jax.jit, static_argnames=_SEGMENTS_STATIC)(_chunk_hash_segments_impl)

#: buffer-donating variant: XLA reuses the [S, P] input rows' HBM for
#: program outputs/scratch — the batched hasher's staged segments are
#: write-once, so on TPU donation saves an [S, P]-sized live allocation
#: per in-flight dispatch. The donated device array is dead afterwards;
#: the overflow-retry path rebuilds lanes from the HOST rows, never the
#: donated array. On CPU jax ignores donation (with a warning), which
#: is why _use_donation defaults by backend.
chunk_hash_segments_donated = functools.partial(
    jax.jit, static_argnames=_SEGMENTS_STATIC,
    donate_argnums=(0,))(_chunk_hash_segments_impl)


@functools.lru_cache(maxsize=None)
def _donation_default() -> bool:
    return jax.default_backend() == "tpu"


def _use_donation() -> bool:
    """VOLSYNC_DONATE forced value, else donate exactly on TPU."""
    from volsync_tpu import envflags

    forced = envflags.donate_device_inputs()
    if forced is not None:
        return forced
    return _donation_default()


@functools.partial(jax.jit, static_argnames=("n_pages_pad", "pagemajor"))
def _page_digests_jit(data, n_pages_pad: int, pagemajor: bool):
    return _page_digests_flat(data, n_pages_pad, pagemajor=pagemajor)


def page_digests(dev) -> np.ndarray:
    """SHA-256 of every full 4 KiB page of a resident buffer ->
    [P/4096, 8] big-endian-word ndarray (one dispatch, one fetch of
    32 bytes per page). The streaming whole-file hasher's primitive.

    The layout gate is read ONCE here and passed as a static jit arg —
    the trace and the host-side decode can never disagree (a cached
    pre-flip trace reinterpreted in the other layout would produce
    garbage digests silently)."""
    P = int(dev.shape[0])
    F = P // LEAF_SIZE
    npps = _n_pages_pad(F)
    pm = _use_pagemajor()
    # The protocol's one sync point: a single bounded 32 B/page digest
    # download for the whole buffer (metadata, never payload bytes).
    flat = np.asarray(_page_digests_jit(dev, npps, pm))  # lint: ignore[VL501] bounded batched digest staging
    wi = _word_index_fn(npps, pm)
    j, p = np.meshgrid(np.arange(8), np.arange(F), indexing="xy")
    return flat[wi(j, p)]  # [F, 8]: j/p broadcast to (F, 8)


@jax.jit
def span_roots_device(data: jax.Array, starts: jax.Array,
                      lens: jax.Array) -> jax.Array:
    """Blob ids for page-aligned spans of a resident buffer, ONE fetch.

    data: [P] uint8, P % LEAF_SIZE == 0; starts/lens: [N] int32 with
    every start % LEAF_SIZE == 0 (padding lanes: lens < 0). Used by the
    rclone-style checksum mover (reference: mover-rclone/active.sh:19
    ``rclone sync --checksum``): many whole files pack into one buffer
    at page-aligned offsets, so all full Merkle leaves are pages of the
    buffer (hashed contiguously, no gather) and only each span's final
    partial leaf — at most one per span — pays the gather path. Returns
    [N, 8] uint32 roots (garbage on padding lanes).

    Unlike chunk_hash_segment there is no boundary walk: the spans ARE
    the blobs. CONTRACT: spans must be page-DISJOINT (no two spans may
    touch the same 4 KiB page) — the tail override mutates the shared
    page-digest table, so a page shared between spans would corrupt the
    other span's root. That also rules out zero-length spans (they'd
    override a page they don't own): callers mark them as padding lanes
    (lens < 0) and emit blob_id(b"") host-side, as
    engine/chunker.hash_spans does; its _spans_page_disjoint is the
    matching gate.
    """
    P = data.shape[0]
    F = P // LEAF_SIZE
    n_pages_pad = _n_pages_pad(F)
    starts = starts.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    # lens <= 0 lanes are inert: no tail override (they own no page —
    # writing one would corrupt its real owner) and a garbage root.
    live = lens > 0
    lens_c = jnp.maximum(lens, 0)

    flat = _page_digests_flat(data, n_pages_pad)

    # Per-span tail leaf: the partial last page (len % LEAF != 0).
    end = starts + lens_c
    has_tail = live & (lens_c % LEAF_SIZE != 0)
    tail_page = jnp.maximum(end - 1, 0) // LEAF_SIZE
    tail_len = end - tail_page * LEAF_SIZE
    tail_dig = sha256_chunks_device(
        data, jnp.clip(tail_page * LEAF_SIZE, 0, P - 1),
        jnp.where(has_tail, tail_len, 0), max_len=LEAF_SIZE)  # [n_cap, 8]
    flat = _apply_tail_overrides(flat, n_pages_pad, tail_page, tail_dig,
                                 has_tail)

    nleaves = jnp.where(live,
                        jnp.maximum((lens_c + LEAF_SIZE - 1) // LEAF_SIZE, 1),
                        0)
    page0 = starts // LEAF_SIZE
    return _root_digests_loop(flat, n_pages_pad, page0, nleaves, lens_c,
                              live)


def decode_segment(packed: np.ndarray, chunk_cap: int
                   ) -> tuple[list[tuple[int, int, str]], int, int, int]:
    """packed u32 array -> ([(start, len, root-hex)], consumed,
    true_candidates, total_leaves)."""
    packed = np.asarray(packed, dtype=np.uint32)
    count = int(packed[0])
    consumed = int(packed[1])
    n_cand = int(packed[2])
    n_leaves = int(packed[3])
    starts = packed[4: 4 + chunk_cap].astype(np.int64)
    lens = packed[4 + chunk_cap: 4 + 2 * chunk_cap].astype(np.int64)
    roots = packed[4 + 2 * chunk_cap:].reshape(chunk_cap, 8).astype(">u4")
    out = [(int(starts[c]), int(lens[c]), roots[c].tobytes().hex())  # lint: ignore[VL106] 32 B digests
           for c in range(count)]
    return out, consumed, n_cand, n_leaves


class FusedSegmentHasher:
    """Host driver for ``chunk_hash_segment``: capacity bucketing +
    overflow retry. Stateless apart from the params; safe to share
    across threads (jit cache is global)."""

    def __init__(self, params: GearParams):
        assert params.align == LEAF_SIZE, \
            "fused path requires the page-aligned cut format (align=4096)"
        self.params = params

    #: Override point (benchmarks compose a content salt into the same
    #: program); None = chunk_hash_segment on the library kernels.
    segment_device_fn = None

    def dispatch(self, dev, length: int, *, eof: bool,
                 cand_cap: int | None = None, chunk_cap: int | None = None):
        p = self.params
        P = int(dev.shape[0])
        cc, kc = segment_caps(P, p)
        cand_cap = cand_cap or cc
        chunk_cap = chunk_cap or kc
        fn = self.segment_device_fn or chunk_hash_segment
        return fn(dev, length, min_size=p.min_size, avg_size=p.avg_size,
                  max_size=p.max_size, seed=p.seed, mask_s=p.mask_s,
                  mask_l=p.mask_l, align=p.align, eof=eof,
                  cand_cap=cand_cap, chunk_cap=chunk_cap), \
            (cand_cap, chunk_cap)

    def finish(self, dev, length: int, inflight, *, eof: bool):
        """Fetch + decode; re-dispatch with doubled capacities iff the
        true counts overflowed the compiled tables (adversarial data)."""
        handle, (cand_cap, chunk_cap) = inflight
        while True:
            chunks, consumed, grown = decode_with_overflow_check(
                np.asarray(handle), length, cand_cap, chunk_cap)
            if grown is None:
                return chunks, consumed
            cand_cap, chunk_cap = grown
            handle, (cand_cap, chunk_cap) = self.dispatch(
                dev, length, eof=eof, cand_cap=cand_cap,
                chunk_cap=chunk_cap)


class BatchedSegmentHasher:
    """Host driver for ``chunk_hash_segments``: many independent
    streams' segments in one dispatch + one fetch (the cross-PVC batch
    of BASELINE configs[5]).

    ``hash_segments(items)`` takes ``[(bytes-like, valid_len, eof)]``,
    pads every lane to one shared bucketed length, and returns
    ``[(chunks, consumed)]`` per lane. Lanes whose true counts overflow
    the compiled capacities retry INDIVIDUALLY through the
    single-segment path (adversarial data only — the batch result for
    the other lanes is already in hand)."""

    def __init__(self, params: GearParams):
        assert params.align == LEAF_SIZE, \
            "batched path requires the page-aligned cut format"
        self.params = params
        self._single = FusedSegmentHasher(params)

    def hash_segments(self, items) -> list:
        from volsync_tpu.engine.chunker import _buffer_bucket

        if not items:
            return []
        # Lanes GROUP BY buffer bucket: padding every lane to the
        # largest one would multiply host/HBM bytes by the batch size
        # when one 32 MiB flush coalesces with tiny eof tails — grouped,
        # per-lane padded waste is bounded by the bucket rounding (<2x).
        groups: dict[int, list[int]] = {}
        for i, (buf, _, _) in enumerate(items):
            groups.setdefault(_buffer_bucket(max(len(buf), 1)),
                              []).append(i)
        out: list = [None] * len(items)
        for P, idxs in groups.items():
            for i, res in zip(idxs,
                              self._hash_bucket(P,
                                                [items[i] for i in idxs])):
                out[i] = res
        return out

    def _hash_bucket(self, P: int, items) -> list:
        """One dispatch for same-bucket lanes (lane count padded to a
        pow2 so the jit cache sees a bounded set of (S, P) shapes;
        padding lanes carry valid_len == 0). Batches whose PADDED shape
        would cross the int32 index-space bound (2 GiB — see
        chunk_hash_segments) split into compliant sub-batches."""
        import jax.numpy as jnp

        max_lanes = max(1, _MAX_FLAT_BYTES // P)
        if _pow2ceil(len(items), 1) > max_lanes:
            half = max(1, len(items) // 2)
            return (self._hash_bucket(P, items[:half])
                    + self._hash_bucket(P, items[half:]))

        p = self.params
        cand_cap, chunk_cap = segment_caps(P, p)
        S = _pow2ceil(len(items), 1)
        rows = np.zeros((S, P), dtype=np.uint8)
        lens = np.zeros((S,), dtype=np.int32)
        eofs = np.zeros((S,), dtype=bool)
        staged = 0
        for i, (buf, n, eof) in enumerate(items):
            arr = np.frombuffer(buf, dtype=np.uint8, count=len(buf))
            rows[i, : arr.shape[0]] = arr
            staged += arr.shape[0]
            lens[i] = n
            eofs[i] = eof
        record_copy("device.stage", staged)
        fn = (chunk_hash_segments_donated if _use_donation()
              else chunk_hash_segments)
        packed = np.asarray(fn(
            jnp.asarray(rows), jnp.asarray(lens), jnp.asarray(eofs),
            min_size=p.min_size, avg_size=p.avg_size, max_size=p.max_size,
            seed=p.seed, mask_s=p.mask_s, mask_l=p.mask_l, align=p.align,
            cand_cap=cand_cap, chunk_cap=chunk_cap))
        out = []
        for i, (buf, n, eof) in enumerate(items):
            chunks, consumed, grown = decode_with_overflow_check(
                packed[i], int(lens[i]), cand_cap, chunk_cap)
            if grown is not None:
                # adversarial lane: retry alone with doubled capacities
                dev = jnp.asarray(rows[i])  # lint: ignore[VL502] rare overflow retry: one adversarial lane re-dispatched alone
                inflight = self._single.dispatch(
                    dev, int(lens[i]), eof=bool(eofs[i]),
                    cand_cap=grown[0], chunk_cap=grown[1])
                chunks, consumed = self._single.finish(
                    dev, int(lens[i]), inflight, eof=bool(eofs[i]))
            out.append((chunks, consumed))
        return out


def decode_with_overflow_check(packed: np.ndarray, length: int,
                               cand_cap: int, chunk_cap: int):
    """Decode one packed result and apply the capacity-retry protocol.

    Returns (chunks, consumed, grown): ``grown`` is None when the
    result is trustworthy, else the (cand_cap, chunk_cap) to re-dispatch
    with. The in-band header makes truncation always detectable: slot 2
    carries the true (single-chip) / worst-shard (mesh) candidate count,
    and a full chunk table with bytes still unconsumed means the walk
    was cut short. Shared by FusedSegmentHasher and the mesh path so the
    protocol cannot drift between the single- and multi-chip engines.
    """
    chunks, consumed, n_cand, _ = decode_segment(packed, chunk_cap)
    grown_cand, grown_chunk = cand_cap, chunk_cap
    retry = False
    if n_cand > cand_cap:
        grown_cand = _pow2ceil(n_cand, cand_cap * 2)
        retry = True
    if len(chunks) >= chunk_cap and consumed < length:
        grown_chunk = chunk_cap * 2
        retry = True
    return chunks, consumed, (grown_cand, grown_chunk) if retry else None
