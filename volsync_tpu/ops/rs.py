"""Batched GF(2^8) Reed-Solomon erasure coding as JAX kernels.

Replaces the 2x full-pack mirrors (``VOLSYNC_PACK_COPIES=2``) with
systematic k+m striping: a sealed pack body is split into k equal data
shards and extended with m parity shards so ANY k of the k+m shards
reconstruct the body — m arbitrary losses survive at (k+m)/k storage
instead of failing on the second copy (ROADMAP item 4; arxiv
2508.05797's vector-lane chunking, arxiv 2602.22237's
lightweight-metadata DR layout).

Design notes
------------
- Field: GF(2^8) mod the primitive polynomial 0x11D, generator 2 — the
  classic RS-256 field. Multiplication is the log/exp-table form
  ``exp[log[a] + log[b]]`` with a doubled exp table so the index sum
  never needs a mod-255; zeros are masked (log[0] is undefined).
- Generator matrix: systematic ``[I_k ; C]`` where C is the m x k
  Cauchy matrix ``C[i][j] = 1/(x_i ^ y_j)`` with ``x_i = k + i`` and
  ``y_j = j``. Every k x k submatrix of ``[I_k ; C]`` is invertible, so
  the code is MDS: any k surviving rows decode.
- Dispatch shape mirrors the fused SHA-256 (ops/sha256.py): shards are
  packed host-side into a ``[k, P, _PAGE]`` uint8 page grid (pages as
  the vector lanes, ``pad_pages_to`` bounds jit recompiles the way
  ``pad_blocks_to`` does for sha256_pack_host), and the kernel is one
  log-gather per input shard plus one exp-gather per (row, shard)
  coefficient term — all table lookups, no field loops on device.
- Zero padding is harmless: RS is linear, zero bytes encode to zero
  parity, and the caller trims to the true shard length.
- Decoding inverts the tiny k x k surviving submatrix on the host
  (Gauss-Jordan over GF(2^8) on a matrix of at most 32x32 bytes) and
  applies the SAME device matmul kernel with the inverse rows — encode
  and decode share one jitted primitive per coefficient matrix.
- Bit-exactness is enforced by golden tests against the pure-NumPy
  oracle (``rs_encode_np`` / ``rs_reconstruct_np``), which is also the
  CPU baseline bench.py's ``ec`` mode reports against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from volsync_tpu.obs import record_copy

_GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, primitive over GF(2)
_PAGE = 4096      # page-grid minor dim (matches the pack seal alignment)
_MAX_SHARDS = 256  # field size bounds k + m

# exp/log tables for generator 2. The exp table is doubled (510 live
# entries) so exp[log[a] + log[b]] never needs an explicit mod 255.
_GF_EXP = np.zeros(512, dtype=np.uint8)
_GF_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _GF_EXP[_i] = _x
    _GF_LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _GF_POLY
_GF_EXP[255:510] = _GF_EXP[:255]
del _x, _i


def gf_mul_np(a, b) -> np.ndarray:
    """Elementwise GF(2^8) multiply (NumPy oracle path)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    prod = _GF_EXP[_GF_LOG[a] + _GF_LOG[b]]
    return np.where((a == 0) | (b == 0), 0, prod).astype(np.uint8)


def gf_inv_np(a: int) -> int:
    """GF(2^8) multiplicative inverse of a nonzero scalar."""
    if a == 0:
        raise ZeroDivisionError("gf_inv_np(0)")
    return int(_GF_EXP[255 - _GF_LOG[a]])


def rs_generator_matrix(k: int, m: int) -> np.ndarray:
    """[m, k] uint8 Cauchy parity rows (x_i = k+i, y_j = j)."""
    if k < 1 or m < 1 or k + m > _MAX_SHARDS:
        raise ValueError(f"invalid RS scheme {k}+{m}")
    rows = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            rows[i, j] = gf_inv_np((k + i) ^ j)
    return rows


def rs_full_matrix(k: int, m: int) -> np.ndarray:
    """[k+m, k] systematic matrix: identity data rows over Cauchy parity."""
    return np.concatenate(
        [np.eye(k, dtype=np.uint8), rs_generator_matrix(k, m)], axis=0)


def gf_mat_inv_np(a: np.ndarray) -> np.ndarray:
    """Invert a [k, k] GF(2^8) matrix by Gauss-Jordan (host side; k is
    tiny). Raises ValueError if singular — cannot happen for submatrices
    of the Cauchy construction, but decode guards anyway."""
    k = a.shape[0]
    aug = np.concatenate(
        [a.astype(np.uint8), np.eye(k, dtype=np.uint8)], axis=1)
    for col in range(k):
        piv = col
        while piv < k and aug[piv, col] == 0:
            piv += 1
        if piv == k:
            raise ValueError("singular GF(2^8) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        aug[col] = gf_mul_np(gf_inv_np(int(aug[col, col])), aug[col])
        for row in range(k):
            if row != col and aug[row, col]:
                aug[row] ^= gf_mul_np(int(aug[row, col]), aug[col])
    return aug[:, k:].copy()


# -- NumPy golden oracle -----------------------------------------------------


def rs_encode_np(data: np.ndarray, m: int) -> np.ndarray:
    """[k, L] uint8 data shards -> [m, L] parity shards (pure NumPy)."""
    k = data.shape[0]
    gm = rs_generator_matrix(k, m)
    out = np.zeros((m, data.shape[1]), dtype=np.uint8)
    for i in range(m):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for j in range(k):
            acc ^= gf_mul_np(gm[i, j], data[j])
        out[i] = acc
    return out


def rs_decode_plan(k: int, m: int, have: list[int]) -> tuple[list[int],
                                                             np.ndarray]:
    """Pick k surviving shard indices and build the [k, k] inverse that
    maps their rows back to the data shards. ``have`` is the sorted set
    of healthy shard indices (0..k-1 data, k..k+m-1 parity); data shards
    are preferred so a fully-systematic survival decodes by identity."""
    if len(have) < k:
        raise ValueError(f"need {k} shards, have {len(have)}")
    use = sorted(have)[:k]
    sub = rs_full_matrix(k, m)[use]
    return use, gf_mat_inv_np(sub)


def rs_reconstruct_np(shards: dict[int, np.ndarray], k: int,
                      m: int) -> np.ndarray:
    """Recover the [k, L] data shards from any k healthy shards
    (pure-NumPy oracle; ``shards`` maps shard index -> [L] uint8)."""
    use, inv = rs_decode_plan(k, m, sorted(shards))
    L = shards[use[0]].shape[0]
    out = np.zeros((k, L), dtype=np.uint8)
    for j in range(k):
        acc = np.zeros(L, dtype=np.uint8)
        for i in range(k):
            acc ^= gf_mul_np(inv[j, i], shards[use[i]])
        out[j] = acc
    return out


# -- device kernels ----------------------------------------------------------


@functools.lru_cache(maxsize=128)
def _gf_matmul_fn(rows_key: tuple, r: int, k: int):
    """Jitted GF(2^8) matrix-times-shards kernel, cached per coefficient
    matrix (encode rows and decode inverses both land here). The matrix
    is static: zero coefficients drop their term at trace time, and each
    surviving term is one exp-table gather on pre-shared log lanes."""
    rows = np.array(rows_key, dtype=np.uint8).reshape(r, k)
    logc = _GF_LOG[rows]  # [r, k] static int32 coefficient logs
    exp_t = jnp.asarray(_GF_EXP)
    log_t = jnp.asarray(_GF_LOG)

    @jax.jit
    def matmul(data: jax.Array) -> jax.Array:
        # data: [k, P, _PAGE] uint8 page grid -> [r, P, _PAGE] uint8.
        dlog = jnp.take(log_t, data.astype(jnp.int32))  # shared log lanes
        zero = data == jnp.uint8(0)
        outs = []
        for i in range(r):
            acc = None
            for j in range(k):
                if rows[i, j] == 0:
                    continue
                term = jnp.take(exp_t, dlog[j] + np.int32(logc[i, j]))
                term = jnp.where(zero[j], jnp.uint8(0), term)
                acc = term if acc is None else acc ^ term
            if acc is None:
                acc = jnp.zeros(data.shape[1:], dtype=jnp.uint8)
            outs.append(acc)
        return jnp.stack(outs)

    return matmul


def gf_matmul_device(rows: np.ndarray, data: jax.Array) -> jax.Array:
    """Apply a static [r, k] GF(2^8) matrix to a [k, P, _PAGE] page grid."""
    r, k = rows.shape
    key = tuple(np.asarray(rows, dtype=np.uint8).reshape(-1).tolist())
    return _gf_matmul_fn(key, r, k)(data)


def rs_pack_host(shards: list, *, pad_pages_to: int | None = None):
    """Pack k equal-length shard buffers into the [k, P, _PAGE] page
    grid. Zero-pads the tail page (linear-code safe) and optionally
    rounds P up to a multiple of ``pad_pages_to`` to bound recompiles,
    mirroring sha256_pack_host's pad_blocks_to."""
    k = len(shards)
    if k == 0:
        raise ValueError("rs_pack_host: no shards")
    L = len(shards[0])
    pages = max((L + _PAGE - 1) // _PAGE, 1)
    if pad_pages_to is not None:
        pages = ((pages + pad_pages_to - 1) // pad_pages_to) * pad_pages_to
    buf = np.zeros((k, pages * _PAGE), dtype=np.uint8)
    for i, s in enumerate(shards):
        if len(s) != L:
            raise ValueError("rs_pack_host: unequal shard lengths")
        buf[i, :L] = np.frombuffer(s, dtype=np.uint8)
    return buf.reshape(k, pages, _PAGE), L


def rs_encode_device(data_grid: jax.Array, m: int) -> jax.Array:
    """[k, P, _PAGE] data page grid -> [m, P, _PAGE] parity page grid."""
    k = int(data_grid.shape[0])
    return gf_matmul_device(rs_generator_matrix(k, m), data_grid)


def rs_reconstruct_device(shards: dict, k: int, m: int,
                          shard_len: int) -> list[bytes]:
    """Recover all k data shards from any k healthy shards on device.

    ``shards`` maps shard index -> buffer; returns the k data shards as
    ``shard_len``-byte strings. Survived data shards pass through the
    identity rows of the inverse, so the all-systematic case is pure
    gathers with no field math surviving dead-code elimination."""
    use, inv = rs_decode_plan(k, m, sorted(shards))
    grid, L = rs_pack_host([shards[i] for i in use])
    if L != shard_len:
        raise ValueError("rs_reconstruct_device: shard length mismatch")
    out = np.asarray(gf_matmul_device(inv, grid))
    flat = out.reshape(k, -1)[:, :shard_len]
    record_copy("ec.decode", k * shard_len)
    return [flat[i].tobytes() for i in range(k)]
