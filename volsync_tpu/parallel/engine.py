"""Sharded chunk+hash pipeline step — the framework's flagship compute.

One step consumes a [W, L] batch of byte streams (W independent
relationship "waves" × L bytes of volume data) laid out over the
(wave, seq) mesh and produces, fully on device:

- the gear-hash CDC boundary-candidate mask for every byte position
  (the restic-chunker replacement — SURVEY.md §2.2 #25),
- SHA-256 digests of every fixed-size block (the dedup/content-address
  hash — restic blob ids / syncthing block hashes),
- global dedup statistics via collectives: a bloom sketch of digests
  unioned with ``psum`` over the whole mesh, plus candidate/byte counts.

Cross-shard correctness: a gear hash at position i depends on the 31
preceding bytes, so each seq shard sends its 31-byte tail to its right
neighbor with ``ppermute`` (the sequence-parallel halo exchange — the
same pattern ring attention uses for block boundaries). The reference has
no intra-volume parallelism at all (SURVEY.md §5 "long-context" note);
this step is where the TPU build beats it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


def _axis_size(name) -> int:
    """Static size of a mapped axis inside a shard_map body. Pre-0.6 jax
    has no lax.axis_size; psum of a Python int is folded statically."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)

from volsync_tpu.ops.gearcdc import DEFAULT_PARAMS, GearParams, _mix_u32
from volsync_tpu.ops.sha256 import sha256_blocks
from volsync_tpu.parallel.mesh import SEQ_AXIS, WAVE_AXIS

_HALO = 31  # gear window is 32 bytes -> 31 bytes of left context


def _gear_doubling(g: jax.Array) -> jax.Array:
    """The 5 shift-scale-add passes turning per-byte table values into the
    32-byte-window gear hash (see ops/gearcdc.py)."""
    h = g
    pad_cfg = [(0, 0)] * (h.ndim - 1)
    for m in (1, 2, 4, 8, 16):
        shifted = jnp.pad(h[..., :-m], pad_cfg + [(m, 0)])
        h = h + (shifted << np.uint32(m))
    return h


def _gear_lastaxis(data: jax.Array, seed: int) -> jax.Array:
    """Gear hash over the last axis ([..., L] uint8 -> [..., L] uint32),
    log-depth doubling form with an arithmetic (gather-free) byte table
    (see ops/gearcdc.py)."""
    g = _mix_u32(data.astype(jnp.uint32) + np.uint32(seed & 0xFFFFFFFF))
    return _gear_doubling(g)


def sha256_fixed_blocks(blocks_u8: jax.Array) -> jax.Array:
    """SHA-256 of equal-length messages ([B, L] uint8, L % 64 == 0 -> [B, 8]).

    Fixed length means the FIPS 180-4 padding is one constant extra block,
    applied as a final compression — no gathers, so this is the cheapest
    bulk-hash path (the fixed-block dedup table and the syncthing-style
    block index; variable-length CDC chunks go through
    sha256_chunks_device).

    Memory layout: every bulk intermediate keeps a large minor dimension.
    A [B, nblocks, 16]-words layout would be 8x-padded by the TPU's
    (8, 128) tiling (and [.., 4] byte groups 32x), so words are extracted
    with strided slices from a [B, L/4] array and fed to the scan as a
    16-tuple of [nblocks, B] arrays instead.
    """
    from volsync_tpu.ops.sha256 import _H0, _compress

    B, L = blocks_u8.shape
    assert L % 64 == 0, "fixed-block path requires 64-byte-aligned blocks"
    x = blocks_u8.astype(jnp.uint32)  # [B, L]
    w = (
        (x[:, 0::4] << np.uint32(24)) | (x[:, 1::4] << np.uint32(16))
        | (x[:, 2::4] << np.uint32(8)) | x[:, 3::4]
    )  # [B, L/4] big-endian message words
    xs = tuple(jnp.transpose(w[:, t::16]) for t in range(16))  # 16 x [nb, B]

    state0 = jnp.broadcast_to(jnp.asarray(_H0), (B, 8))
    state0 = state0 ^ (w[:, :8] & jnp.uint32(0))  # varying-axis alignment

    def step(state, wt):
        return _compress(state, jnp.stack(wt, axis=-1)), None

    state, _ = jax.lax.scan(step, state0, xs)

    pad = np.zeros((16,), dtype=np.uint32)
    pad[0] = 0x80000000
    bitlen = L * 8
    pad[14] = (bitlen >> 32) & 0xFFFFFFFF
    pad[15] = bitlen & 0xFFFFFFFF
    pad_block = (state[:, :1] & jnp.uint32(0)) ^ jnp.asarray(pad)[None, :]
    return _compress(state, pad_block)


def make_chunk_hash_step(mesh, *, block_len: int = 64 * 1024,
                         params: GearParams = DEFAULT_PARAMS,
                         bloom_log2: int = 20):
    """Build the jitted sharded step for ``mesh``.

    Returns ``step(data)`` where data is [W, L] uint8 with W divisible by
    the wave axis and L by (seq axis * block_len). Output dict:

    - ``digests``   [W, L // block_len, 8] uint32 — per-block SHA-256,
      sharded (wave, seq);
    - ``cand_mask`` [W, L] bool — CDC boundary candidates (strict mask),
      sharded (wave, seq);
    - ``bloom``     [2^bloom_log2] uint32 — global digest-occupancy counts
      (replicated; membership = >0);
    - ``stats``     dict of replicated scalars: total_bytes,
      total_candidates, distinct_block_estimate, duplicate_block_estimate.
    """
    seed = params.seed
    mask_s = np.uint32(params.dense_mask_s)  # per-position evaluation
    bloom_size = 1 << bloom_log2

    def local_step(data):  # data: [Wl, Sl] — this shard's slice
        n_seq = _axis_size(SEQ_AXIS)
        seq_i = jax.lax.axis_index(SEQ_AXIS)

        # Sequence-parallel halo: my left context is the previous shard's
        # 31-byte tail. ppermute shifts tails one step to the right along
        # the seq ring; shard 0 (true buffer start) zeroes its halo.
        tail = data[:, -_HALO:]
        halo = jax.lax.ppermute(
            tail, SEQ_AXIS, [(i, (i + 1) % n_seq) for i in range(n_seq)]
        )
        ext = jnp.concatenate([halo, data], axis=1)  # [Wl, HALO + Sl]
        g = _mix_u32(ext.astype(jnp.uint32) + np.uint32(seed & 0xFFFFFFFF))
        # Shard 0 starts the true buffer: its halo positions must
        # contribute *nothing* to the hash (the unsharded recurrence
        # starts from h=0), so zero the table values — zeroing the halo
        # bytes would still contribute _mix_u32(seed) per position.
        g = jnp.where(
            (seq_i == 0)
            & (jnp.arange(ext.shape[1], dtype=jnp.int32) < _HALO)[None, :],
            jnp.uint32(0), g,
        )
        h = _gear_doubling(g)[:, _HALO:]  # [Wl, Sl]
        cand = (h & mask_s) == 0

        Wl, Sl = data.shape
        nb = Sl // block_len
        digests = sha256_fixed_blocks(
            data.reshape(Wl * nb, block_len)
        ).reshape(Wl, nb, 8)

        # Dedup sketch: one bit per digest (keyed by word 0 — uniform for
        # SHA-256), psum-unioned across the whole mesh.
        slot = digests[..., 0].reshape(-1) & np.uint32(bloom_size - 1)
        local_bloom = jnp.zeros((bloom_size,), jnp.uint32).at[slot].max(
            jnp.uint32(1)
        )
        bloom = jax.lax.psum(local_bloom, (WAVE_AXIS, SEQ_AXIS))

        total_cand = jax.lax.psum(
            jnp.sum(cand, dtype=jnp.uint32), (WAVE_AXIS, SEQ_AXIS)
        )
        distinct = jnp.sum(bloom > 0, dtype=jnp.uint32)
        return digests, cand, bloom, total_cand, distinct

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=P(WAVE_AXIS, SEQ_AXIS),
        out_specs=(
            P(WAVE_AXIS, SEQ_AXIS, None),
            P(WAVE_AXIS, SEQ_AXIS),
            P(),
            P(),
            P(),
        ),
    )

    jitted = jax.jit(sharded)

    def step(data):
        # Byte/block totals are static shape facts — computed host-side in
        # Python ints (a device uint32 psum would wrap at 4 GiB batches).
        W, L = data.shape
        total_blocks = W * (L // block_len)
        digests, cand, bloom, total_cand, distinct = jitted(data)
        return {
            "digests": digests, "cand_mask": cand, "bloom": bloom,
            "stats": {
                "total_bytes": W * L,
                "total_candidates": total_cand,
                "distinct_block_estimate": distinct,
                "duplicate_block_estimate": total_blocks - distinct,
            },
        }

    return step


@functools.partial(jax.jit, static_argnames=("block_len", "mask_s", "seed"))
def _single_chip_step(data, *, block_len: int, mask_s: int, seed: int):
    h = _gear_lastaxis(data, seed)
    cand = (h & np.uint32(mask_s)) == 0
    nb = data.shape[0] // block_len
    digests = sha256_fixed_blocks(data[: nb * block_len].reshape(nb, block_len))
    return digests, jnp.sum(cand, dtype=jnp.uint32)


def chunk_hash_block(data, *, block_len: int = 64 * 1024,
                     params: GearParams = DEFAULT_PARAMS):
    """Single-chip pipeline on one flat buffer: ([L] uint8) ->
    (block digests [L//block_len, 8], CDC candidate count). The jittable
    core behind it (``_single_chip_step``) is what ``__graft_entry__.entry``
    exposes for the driver's compile check."""
    return _single_chip_step(
        jnp.asarray(data), block_len=block_len, mask_s=params.dense_mask_s,
        seed=params.seed,
    )
