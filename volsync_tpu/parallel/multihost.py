"""Multi-host initialization for the data-plane mesh.

The reference scales across hosts with NCCL/MPI-free point-to-point
transports (SSH / HTTPS-S3 / TLS BEP — SURVEY.md §2.3); control fans out
as one operator per cluster driving mover pods anywhere. The TPU build
keeps that shape for the *movers* (one volsync-manager per TPU VM,
network movers between them — movers/rsync/standalone.py, service/), and
adds what the reference never had: a single logical device mesh spanning
hosts, so ONE volume's scan can shard over an entire pod slice.

``init_distributed()`` wires ``jax.distributed`` from the standard TPU
pod environment (or explicit arguments), after which ``jax.devices()``
returns every chip in the slice and the existing mesh builders
(parallel/mesh.make_mesh, sharded_chunker.make_stream_mesh) span hosts
transparently. The fused sharded engine's only collectives are an
all-gather of the 32B-per-4KiB digest stream and the candidate tables
(sharded_chunker._build_fused_fn) — XLA routes them over ICI within a
host and DCN between hosts; no framework code changes.

Single-host processes (the common case, and all tests) never call this:
jax.devices() already returns the local chips.
"""

from __future__ import annotations

import os
from typing import Optional


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> dict:
    """Initialize jax.distributed for a multi-host mesh.

    With no arguments, defers to JAX's TPU-pod auto-detection (the
    metadata-provided coordinator), falling back to the standard
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` env triplet. Returns a summary dict
    (process_index, process_count, local/global device counts) for the
    operator's startup log. Idempotent: calling twice is a no-op.
    """
    import jax

    if getattr(init_distributed, "_done", False):
        return _summary(jax)
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    else:
        # TPU pod slices self-describe; initialize() with no args uses
        # the platform's cluster-detection (a no-op on single host).
        try:
            jax.distributed.initialize()
        except Exception:  # noqa: BLE001 — single-host/CPU: nothing to do
            pass
    init_distributed._done = True
    return _summary(jax)


def _summary(jax) -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
