"""Mesh-sharded CDC chunk+hash: the multi-chip product path.

``MeshChunkHasher`` is a drop-in for ``engine.chunker.DeviceChunkHasher``
(same ``process(buffer, eof)`` protocol), so ``stream_chunks`` /
``TreeBackup`` — the real backup path — run sharded over a device mesh
with no orchestration changes. The reference has *no* intra-volume
parallelism at all (SURVEY.md §5 long-context note: rsync/restic stream
single-threaded); sharding one volume's scan across chips is the TPU
framework's core win.

Per segment, two shard_map kernels over a 1-D ``seq`` ring of devices:

1. **Candidates** — each shard gear-hashes its slice with a 31-byte left
   halo from its neighbor (``ppermute``; the same seam pattern ring
   attention uses), masks strict/lax CDC candidates, and compacts them to
   per-shard index lists. Shard 0 zeroes its halo contribution so
   positions hash exactly as the unsharded recurrence started from h=0.
2. **Leaf digests** — after the host's sparse FastCDC boundary walk
   (identical to the single-chip walk, so boundaries are bit-identical),
   every 4 KiB Merkle leaf of every chunk is assigned to the shard its
   start falls in; each shard takes a 4095-byte *right* halo so leaves
   crossing the seam read their tail from the neighbor, and hashes its
   leaves as independent gather lanes (ops/sha256.sha256_chunks_device).

Blob ids then assemble host-side from the leaf digests (repo/blobid.py),
byte-identical to the single-device path — golden tests enforce equality
against both DeviceChunkHasher and hashlib.
"""

from __future__ import annotations

import numpy as np

from volsync_tpu.engine.chunker import _pow2ceil
from volsync_tpu.ops.gearcdc import GearParams, _mix_u32, select_boundaries
from volsync_tpu.repo import blobid

_HALO = 31              # gear window context (see parallel/engine.py)
_LEAF = blobid.LEAF_SIZE
SEQ = "seq"


def make_stream_mesh(devices=None):
    """All devices as one ``seq`` ring — a single volume's byte stream
    shards across every chip (the wave axis of parallel/mesh.py batches
    *independent* streams; one big backup wants the whole machine)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (SEQ,))


class MeshChunkHasher:
    """chunk+hash a byte buffer sharded over a device mesh.

    Compile-count discipline matches DeviceChunkHasher: shard lengths are
    drawn from pow2 buckets, candidate/leaf capacities from doubling
    buckets, so steady-state streaming reuses a handful of compiled
    programs regardless of workload shape.
    """

    #: NOT safe for concurrent process() calls: sharded dispatches issue
    #: mesh collectives whose per-device enqueue order must match across
    #: the ring, and the compiled-fn caches race. TreeBackup serializes
    #: file hashing when this hasher is injected.
    thread_safe = False

    def __init__(self, params: GearParams, mesh=None):
        import jax

        self.params = params
        self.mesh = mesh if mesh is not None else make_stream_mesh()
        self.n_shards = self.mesh.devices.size
        self._cand_cache: dict = {}
        self._leaf_cache: dict = {}
        self._fused_cache: dict = {}
        self._jax = jax

    # -- public protocol (mirrors DeviceChunkHasher.process) ----------------

    def process(self, buffer, *, eof: bool = True) -> list[tuple[int, int, str]]:
        if isinstance(buffer, (bytes, bytearray, memoryview)):
            buffer = np.frombuffer(buffer, dtype=np.uint8)
        length = int(buffer.shape[0])
        if length == 0:
            return []
        p = self.params
        if length <= p.min_size:
            if not eof:
                return []
            return [(0, length, blobid.blob_id(buffer.tobytes()))]

        data, shard_len = self._upload(buffer, length)
        if p.align == _LEAF:
            return self._process_fused(data, shard_len, length, eof)
        idx_s, idx_l = self._candidates(data, shard_len, length)
        chunks = select_boundaries(idx_s, idx_l, length, p, eof=eof)
        if not chunks:
            return []
        hexes = self._span_roots(data, shard_len, chunks)
        return [(int(s), int(l), h) for (s, l), h in zip(chunks, hexes)]

    # -- fused page-aligned path (one dispatch, one small fetch) ------------

    def _process_fused(self, data, shard_len: int, length: int,
                       eof: bool) -> list[tuple[int, int, str]]:
        """The ops/segment.py one-round-trip protocol, sharded: page
        digests and candidates compute per shard (pages never cross
        seams — shard_len % LEAF == 0 — so there is NO halo at all),
        the 32-bytes-per-4KiB digest stream all-gathers over the seq
        ring (1/128th of the data volume, riding ICI), and the FastCDC
        walk + root assembly run replicated on the gathered table. ONE
        replicated ~20 KiB result comes back; capacity overflows are
        reported in-band and retried with doubled tables, exactly like
        the single-chip FusedSegmentHasher."""
        from volsync_tpu.ops.segment import (
            decode_with_overflow_check,
            segment_caps,
        )

        padded = self.n_shards * shard_len
        cand_cap, chunk_cap = segment_caps(padded, self.params)
        # cand_cap is per shard in this path (compaction is local; the
        # header's candidate slot carries the WORST shard's true count).
        cand_cap = max(1024, cand_cap // self.n_shards)
        while True:
            fn = self._fused_fn(shard_len, cand_cap, chunk_cap, eof)
            packed = np.asarray(fn(data, np.int32(length)))
            chunks, consumed, grown = decode_with_overflow_check(
                packed, length, cand_cap, chunk_cap)
            if grown is None:
                assert not eof or consumed == length
                return chunks
            cand_cap, chunk_cap = grown

    def _fused_fn(self, shard_len: int, cand_cap: int, chunk_cap: int,
                  eof: bool):
        key = (shard_len, cand_cap, chunk_cap, eof)
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = _build_fused_fn(self.mesh, self.params, shard_len,
                                 cand_cap, chunk_cap, eof)
            self._fused_cache[key] = fn
        return fn

    # -- upload -------------------------------------------------------------

    def _upload(self, buffer: np.ndarray, length: int):
        """Pad to S * pow2-bucketed shard length, lay out [S, Ls] with
        shard i holding bytes [i*Ls, (i+1)*Ls)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        S = self.n_shards
        shard_len = _pow2ceil((length + S - 1) // S, max(_LEAF, 64 * 1024))
        padded = S * shard_len
        if padded != length:
            buffer = np.pad(buffer, (0, padded - length))
        host = buffer.reshape(S, shard_len)
        data = jax.device_put(
            host, NamedSharding(self.mesh, P(SEQ, None)))
        return data, shard_len

    # -- kernel 1: CDC candidates -------------------------------------------

    def _cand_fn(self, key):
        fn = self._cand_cache.get(key)
        if fn is None:
            if isinstance(key, tuple) and key[0] == "aligned":
                fn = _build_cand_aligned_fn(self.mesh, self.params,
                                            key[1], key[2])
            else:
                fn = _build_cand_fn(self.mesh, self.params, *key)
            self._cand_cache[key] = fn
        return fn

    def _candidates(self, data, shard_len: int, length: int):
        if self.params.align > 1:
            return self._candidates_aligned(data, shard_len, length)
        # Expected strict-candidate density is 2^-(bits+norm); 1/64 bytes
        # covers any mask down to 2^-6 (same bound as DeviceChunkHasher).
        cap = max(_pow2ceil(shard_len // 64, 1024), 1024)
        while True:
            idx_s, cnt_s, idx_l, cnt_l = self._cand_fn((shard_len, cap))(
                data, np.int32(length))
            cnt_s = np.asarray(cnt_s)
            cnt_l = np.asarray(cnt_l)
            worst = int(max(cnt_s.max(), cnt_l.max()))
            if worst <= cap:
                break
            cap = _pow2ceil(worst, cap * 2)  # dense data: retry, recompile
        idx_s = np.asarray(idx_s)
        idx_l = np.asarray(idx_l)
        # Per-shard compacted lists -> one globally sorted list (shards
        # are contiguous byte ranges in order, so concatenation sorts).
        out_s = np.concatenate([idx_s[i, : int(cnt_s[i])]
                                for i in range(self.n_shards)])
        out_l = np.concatenate([idx_l[i, : int(cnt_l[i])]
                                for i in range(self.n_shards)])
        return out_s, out_l

    def _candidates_aligned(self, data, shard_len: int, length: int):
        """Aligned cuts need NO halo: the gear window at an eligible
        position sits inside one align-byte row, which never crosses a
        shard seam (shard_len % align == 0) — the collective disappears
        and each shard compacts its own row lanes."""
        cap = 1024
        while True:
            pos, flags, cnt = self._cand_fn(("aligned", shard_len, cap))(
                data, np.int32(length))
            cnt = np.asarray(cnt)
            worst = int(cnt.max())
            if worst <= cap:
                break
            cap = _pow2ceil(worst, cap * 2)
        pos = np.asarray(pos)
        flags = np.asarray(flags)
        out_l = []
        out_s = []
        for i in range(self.n_shards):
            n = int(cnt[i])
            p = pos[i, :n]
            out_l.append(p)
            out_s.append(p[flags[i, :n]])
        return np.concatenate(out_s), np.concatenate(out_l)

    # -- kernel 2: Merkle leaf digests --------------------------------------

    def _leaf_fn(self, shard_len: int, cap: int):
        key = (shard_len, cap)
        fn = self._leaf_cache.get(key)
        if fn is None:
            fn = _build_leaf_fn(self.mesh, shard_len, cap)
            self._leaf_cache[key] = fn
        return fn

    def _span_roots(self, data, shard_len: int,
                    chunks: list[tuple[int, int]]) -> list[str]:
        S = self.n_shards
        # Assign every leaf to the shard its start falls in; record
        # (shard, slot) per leaf for reassembly.
        per_shard: list[list[tuple[int, int]]] = [[] for _ in range(S)]
        placement: list[tuple[int, int]] = []  # leaf -> (shard, slot)
        spans: list[tuple[int, int]] = []      # chunk -> (first leaf, count)
        for start, clen in chunks:
            first = len(placement)
            n = blobid.leaf_count(clen)
            for k in range(n):
                off = start + k * _LEAF
                llen = min(_LEAF, start + clen - off)
                shard = off // shard_len
                slot = len(per_shard[shard])
                per_shard[shard].append((off - shard * shard_len, llen))
                placement.append((shard, slot))
            spans.append((first, n))

        cap = _pow2ceil(max((len(v) for v in per_shard), default=1),
                        max(shard_len // _LEAF // 8, 128))
        starts = np.zeros((S, cap), np.int32)
        lengths = np.zeros((S, cap), np.int32)
        for s in range(S):
            for slot, (off, llen) in enumerate(per_shard[s]):
                starts[s, slot] = off
                lengths[s, slot] = llen
        digests = np.asarray(
            self._leaf_fn(shard_len, cap)(data, starts, lengths)
        ).astype(">u4")  # [S, cap, 8] big-endian
        flat = digests.tobytes()

        def leaf_bytes(shard: int, slot: int) -> bytes:
            base = (shard * cap + slot) * 32
            return flat[base: base + 32]

        out = []
        for (first, n), (_, clen) in zip(spans, chunks):
            leaves = [leaf_bytes(*placement[first + k]) for k in range(n)]
            out.append(blobid.root_from_leaves(clen, leaves))
        return out


def _build_fused_fn(mesh, params: GearParams, shard_len: int,
                    cand_cap: int, chunk_cap: int, eof: bool):
    """shard_map kernel for the fused page-aligned segment protocol.

    Layout: data [S, Ls] with shard i holding bytes [i*Ls, (i+1)*Ls);
    Ls % LEAF == 0, so pages (== full Merkle leaves, align == LEAF)
    never cross seams and per-shard page hashing needs no collective.
    Per shard: page digests (ops/segment._page_digests_flat — the
    Pallas transpose + SHA lane kernel on TPU, the XLA scan on CPU) and
    aligned gear candidates. Then: all_gather of the digest words and
    the compacted candidate lists (sentinel-padded, re-sorted), psum'd
    counts, and the ops/segment walk + root loop on the replicated
    tables — every shard computes the identical ~20 KiB packed result.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from volsync_tpu.parallel.engine import _axis_size, shard_map

    from volsync_tpu.ops.gearcdc import gear_at_aligned
    from volsync_tpu.ops.segment import (
        _page_digests_flat,
        _root_digests_loop,
        _select_boundaries_device,
    )
    from volsync_tpu.ops.sha256 import (
        _LANE_TILE,
        sha256_chunks_device,
        use_pallas_leaves,
    )

    p = params
    S = mesh.devices.size
    align = p.align
    npp = shard_len // _LEAF  # real pages per shard
    npps = ((npp + _LANE_TILE - 1) // _LANE_TILE * _LANE_TILE
            if use_pallas_leaves() else npp)  # padded (Pallas lane grid)
    R = shard_len // align
    mask_s = np.uint32(p.mask_s)
    mask_l = np.uint32(p.mask_l)
    sentinel = jnp.int32(2**31 - 2)

    def local(data, valid_len):  # data: [1, Ls]
        i = jax.lax.axis_index(SEQ)
        row = data[0]
        valid_len = valid_len.astype(jnp.int32)

        # --- per-shard page digests (no halo: pages don't cross seams)
        # Always word-major here: the cross-shard word_index below
        # assumes the per-shard kernel layout regardless of the
        # single-chip VOLSYNC_PAGEMAJOR gate.
        flat_local = _page_digests_flat(row, npps,
                                        pagemajor=False)  # [8 * npps]
        flat_g = jax.lax.all_gather(flat_local, SEQ, axis=0)  # [S, 8*npps]
        flat_g = flat_g.reshape(S * 8 * npps)

        def word_index(j, page):  # word j of GLOBAL page p
            return (page // npp) * (8 * npps) + j * npps + page % npp

        # --- per-shard aligned candidates -> global sorted tables
        h = gear_at_aligned(row, p.seed, align)  # [R]
        pos = (i * shard_len
               + jnp.arange(R, dtype=jnp.int32) * align + (align - 1))
        ok = pos < valid_len
        is_s = ((h & mask_s) == 0) & ok
        is_l = ((h & mask_l) == 0) & ok
        ridx_l = jnp.nonzero(is_l, size=cand_cap, fill_value=R)[0]
        safe = jnp.clip(ridx_l, 0, R - 1)
        lpos = jnp.where(ridx_l < R, pos[safe], sentinel)
        lstrict = jnp.where(ridx_l < R, is_s[safe], False)
        spos = jnp.where(lstrict, lpos, sentinel)
        pos_l = jnp.sort(jax.lax.all_gather(lpos, SEQ, axis=0).reshape(-1))
        pos_s = jnp.sort(jax.lax.all_gather(spos, SEQ, axis=0).reshape(-1))
        nl = jax.lax.psum(jnp.sum(is_l).astype(jnp.int32), SEQ)
        ns = jax.lax.psum(jnp.sum(is_s).astype(jnp.int32), SEQ)
        worst = jax.lax.pmax(jnp.sum(is_l).astype(jnp.int32), SEQ)

        # --- replicated FastCDC walk (global positions are multiples of
        # align too, so the successor-table fast form applies with the
        # GLOBAL row count S*R)
        starts, lens, count, consumed = _select_boundaries_device(
            pos_s, jnp.minimum(ns, S * cand_cap),
            pos_l, jnp.minimum(nl, S * cand_cap),
            valid_len, min_size=p.min_size, avg_size=p.avg_size,
            max_size=p.max_size, chunk_cap=chunk_cap, eof=eof,
            align=align, n_rows=S * R)

        # --- the ONE possibly-partial tail leaf: hashed by its owner
        # shard, psum-broadcast, spliced into the gathered table.
        live = jnp.arange(chunk_cap, dtype=jnp.int32) < count
        end = jnp.where(count > 0,
                        starts[jnp.maximum(count - 1, 0)]
                        + lens[jnp.maximum(count - 1, 0)], 0)
        has_tail = (count > 0) & (end % _LEAF != 0)
        tail_page = jnp.maximum(end - 1, 0) // _LEAF
        tail_len = end - tail_page * _LEAF
        owner = tail_page // npp
        loc_off = (tail_page % npp) * _LEAF
        mine = has_tail & (owner == i)
        t_dig = sha256_chunks_device(
            row, loc_off[None], jnp.where(mine, tail_len, 0)[None],
            max_len=_LEAF)[0]
        t_dig = jax.lax.psum(
            jnp.where(mine, t_dig, jnp.uint32(0)), SEQ)
        ovr = jnp.where(has_tail,
                        word_index(jnp.arange(8, dtype=jnp.int32),
                                   tail_page),
                        S * 8 * npps)  # OOB -> dropped
        flat_g = flat_g.at[ovr].set(t_dig, mode="drop")

        # --- replicated roots + packed result
        nleaves = jnp.where(live, (lens + (_LEAF - 1)) // _LEAF, 0)
        page0 = starts // _LEAF
        roots = _root_digests_loop(flat_g, S * npp, page0, nleaves, lens,
                                   live, word_index=word_index)
        header = jnp.stack([count.astype(jnp.uint32),
                            consumed.astype(jnp.uint32),
                            worst.astype(jnp.uint32),
                            jnp.sum(nleaves).astype(jnp.uint32)])
        return jnp.concatenate([header, starts.astype(jnp.uint32),
                                lens.astype(jnp.uint32), roots.reshape(-1)])

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(SEQ, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def _build_cand_fn(mesh, params: GearParams, shard_len: int, cap: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from volsync_tpu.parallel.engine import _axis_size, shard_map

    from volsync_tpu.parallel.engine import _gear_doubling

    seed = np.uint32(params.seed & 0xFFFFFFFF)
    mask_s = np.uint32(params.mask_s)
    mask_l = np.uint32(params.mask_l)

    def local(data, valid_len):  # data: [1, Ls] this shard's slice
        n = _axis_size(SEQ)
        i = jax.lax.axis_index(SEQ)
        row = data[0]
        # Left halo: previous shard's 31-byte tail, shifted right around
        # the ring; shard 0 (true stream start) contributes zero table
        # values for its halo positions, reproducing the unsharded
        # recurrence's h=0 start (see parallel/engine.py local_step).
        halo = jax.lax.ppermute(
            row[-_HALO:], SEQ, [(j, (j + 1) % n) for j in range(n)])
        ext = jnp.concatenate([halo, row])
        g = _mix_u32(ext.astype(jnp.uint32) + seed)
        g = jnp.where((i == 0)
                      & (jnp.arange(ext.shape[0], dtype=jnp.int32) < _HALO),
                      jnp.uint32(0), g)
        h = _gear_doubling(g)[_HALO:]  # [Ls]
        pos = i * shard_len + jnp.arange(shard_len, dtype=jnp.int32)
        ok = pos < valid_len
        is_s = ((h & mask_s) == 0) & ok
        is_l = ((h & mask_l) == 0) & ok
        loc_s = jnp.nonzero(is_s, size=cap, fill_value=shard_len)[0]
        loc_l = jnp.nonzero(is_l, size=cap, fill_value=shard_len)[0]
        # Global positions; fill lanes fall off the end harmlessly (the
        # host slices each shard's list by its true count).
        return ((i * shard_len + loc_s)[None],
                jnp.sum(is_s)[None],
                (i * shard_len + loc_l)[None],
                jnp.sum(is_l)[None])

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(SEQ, None), P()),
        out_specs=(P(SEQ, None), P(SEQ), P(SEQ, None), P(SEQ)),
    )
    return jax.jit(sharded)


def _build_cand_aligned_fn(mesh, params: GearParams, shard_len: int,
                           cap: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from volsync_tpu.parallel.engine import _axis_size, shard_map

    from volsync_tpu.ops.gearcdc import gear_at_aligned

    align = params.align
    mask_s = np.uint32(params.mask_s)
    mask_l = np.uint32(params.mask_l)
    R = shard_len // align

    def local(data, valid_len):  # data: [1, Ls]
        i = jax.lax.axis_index(SEQ)
        h = gear_at_aligned(data[0], params.seed, align)  # [R], no halo
        pos = (i * shard_len
               + jnp.arange(R, dtype=jnp.int32) * align + (align - 1))
        ok = pos < valid_len
        is_s = ((h & mask_s) == 0) & ok
        is_l = ((h & mask_l) == 0) & ok
        ridx = jnp.nonzero(is_l, size=cap, fill_value=R)[0]
        safe = jnp.clip(ridx, 0, R - 1)
        flags = jnp.where(ridx < R, is_s[safe], False)
        out_pos = (i * shard_len + ridx.astype(jnp.int32) * align
                   + (align - 1))
        return out_pos[None], flags[None], jnp.sum(is_l)[None]

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(SEQ, None), P()),
        out_specs=(P(SEQ, None), P(SEQ, None), P(SEQ)),
    )
    return jax.jit(sharded)


def _build_leaf_fn(mesh, shard_len: int, cap: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from volsync_tpu.parallel.engine import _axis_size, shard_map

    from volsync_tpu.ops.sha256 import sha256_chunks_device

    assert shard_len >= _LEAF, "shards must cover at least one leaf"

    def local(data, starts, lengths):  # [1, Ls], [1, cap], [1, cap]
        n = _axis_size(SEQ)
        row = data[0]
        # Right halo: my leaves may run up to LEAF-1 bytes past my slice;
        # fetch the next shard's head (ring: the last shard's wrap-around
        # halo is never referenced — the stream ends inside it).
        halo = jax.lax.ppermute(
            row[: _LEAF - 1], SEQ, [(j, (j - 1) % n) for j in range(n)])
        ext = jnp.concatenate([row, halo])
        digests = sha256_chunks_device(
            ext, starts[0], lengths[0], max_len=_LEAF)
        return digests[None]  # [1, cap, 8]

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(SEQ, None), P(SEQ, None), P(SEQ, None)),
        out_specs=P(SEQ, None, None),
    )
    return jax.jit(sharded)
