"""Live statistics feeding the sync-protocol planner (engine/protoplan.py).

The planner's cost model is only as honest as its inputs, and all three
of them drift at run time:

- **change rate** — what fraction of a file's bytes the delta engine
  actually shipped as literals last time (engine/deltasync.delta_stats);
- **dedup hit ratio** — how often the CDC path's batched index queries
  hit (``volsync_index_queries_total{result}``, repo/shardedindex.py);
- **link bandwidth / latency** — wall time of successful byte-moving
  ``ResilientStore`` attempts (resilience.link_totals()).

``SyncStatsBook`` folds each signal into an exponentially weighted
moving average so one anomalous sync can't whipsaw protocol choice,
with every update guarded against hostile inputs (NaN, zero totals,
zero-duration timings) — a poisoned sample is dropped, never divided
by. Books are per-consumer (``book_for("rsync")``): the rsync mover's
observed churn must not contaminate the restic mover's dedup pricing.

Cold books are deliberately pessimistic: no delta history reads as
change rate 1.0 (every byte would ship as literal) and no dedup history
as hit ratio 0.0, which prices both fancy protocols above FULL_COPY
until a probe run seeds real observations (protoplan's ``probe``
reason).
"""

from __future__ import annotations

import dataclasses
import math

from volsync_tpu import envflags, resilience
from volsync_tpu.analysis import lockcheck

#: Cold-book priors: pessimistic on purpose (see module docstring).
COLD_CHANGE_RATE = 1.0
COLD_DEDUP_RATIO = 0.0
#: Cold link assumptions: a mid-range 100 MiB/s pipe with a 1 ms round
#: trip — only used to break ties before any transfer has been timed.
COLD_BANDWIDTH = 100.0 * (1 << 20)
COLD_LATENCY_S = 1e-3


def _finite_fraction(num: float, den: float):
    """num/den clamped to [0, 1], or None when the inputs can't yield a
    meaningful fraction (zero/negative/NaN/inf denominators included)."""
    if not (math.isfinite(num) and math.isfinite(den)) or den <= 0 or num < 0:
        return None
    return min(num / den, 1.0)


def _finite_rate(amount: float, seconds: float):
    """amount/seconds, or None when undefined — the divide-by-zero guard
    for bandwidth math (a zero-duration timing is clock granularity, not
    an infinitely fast link)."""
    if not (math.isfinite(amount) and math.isfinite(seconds)):
        return None
    if amount <= 0 or seconds <= 0:
        return None
    return amount / seconds


@dataclasses.dataclass(frozen=True)
class SyncStats:
    """Immutable snapshot the planner prices against."""

    change_rate: float        # fraction of bytes expected literal (0..1)
    dedup_hit_ratio: float    # fraction of chunks expected deduped (0..1)
    bandwidth_bps: float      # sustained link bytes/second
    latency_s: float          # per-round-trip link latency, seconds
    delta_samples: int        # how many delta runs informed change_rate
    dedup_samples: int        # how many dedup batches informed hit ratio
    link_samples: int         # how many timed transfers informed the link


class SyncStatsBook:
    """EWMA ledger of sync observations; thread-safe, one per consumer."""

    def __init__(self, *, alpha: float = None):
        self._alpha = alpha if alpha is not None else envflags.plan_ewma_alpha()
        self._lock = lockcheck.make_lock("engine.syncstats")
        self._change_rate = None
        self._dedup_ratio = None
        self._bandwidth = None
        self._latency = None
        self._delta_samples = 0
        self._dedup_samples = 0
        self._link_samples = 0
        # cursors for the cumulative external feeds (diffed per pull)
        self._link_cursor: dict = {}
        self._index_cursor = (0.0, 0.0)

    def _ewma(self, cur, x: float) -> float:
        return x if cur is None else self._alpha * x + (1 - self._alpha) * cur

    # -- observations -------------------------------------------------------

    def observe_delta(self, literal_bytes: float, total_bytes: float) -> None:
        """One completed delta run: ``literal_bytes`` shipped out of
        ``total_bytes`` of source. Unusable inputs are dropped."""
        ratio = _finite_fraction(literal_bytes, total_bytes)
        if ratio is None:
            return
        with self._lock:
            self._change_rate = self._ewma(self._change_rate, ratio)
            self._delta_samples += 1

    def observe_dedup(self, hits: float, total: float) -> None:
        """One batch of dedup-index queries: ``hits`` of ``total`` keys
        already present in the repository."""
        ratio = _finite_fraction(hits, total)
        if ratio is None:
            return
        with self._lock:
            self._dedup_ratio = self._ewma(self._dedup_ratio, ratio)
            self._dedup_samples += 1

    def observe_link(self, nbytes: float, seconds: float) -> None:
        """One timed bulk transfer -> bandwidth sample. Zero-duration or
        non-finite timings never reach the division."""
        rate = _finite_rate(nbytes, seconds)
        if rate is None:
            return
        with self._lock:
            self._bandwidth = self._ewma(self._bandwidth, rate)
            self._link_samples += 1

    def observe_rtt(self, seconds: float) -> None:
        """One timed small round trip -> latency sample."""
        if not math.isfinite(seconds) or seconds <= 0:
            return
        with self._lock:
            self._latency = self._ewma(self._latency, seconds)
            self._link_samples += 1

    # -- external feeds -----------------------------------------------------

    def pull_link_timings(self) -> None:
        """Fold new ResilientStore timings (resilience.link_totals())
        into the link EWMAs. Totals are cumulative, so each book diffs
        against its own cursor — pulling twice observes nothing twice."""
        now = resilience.link_totals()
        with self._lock:
            prev = self._link_cursor
            self._link_cursor = now
        d_bytes = now["large_bytes"] - prev.get("large_bytes", 0)
        d_secs = now["large_seconds"] - prev.get("large_seconds", 0.0)
        self.observe_link(d_bytes, d_secs)
        d_ops = now["small_ops"] - prev.get("small_ops", 0)
        d_small = now["small_seconds"] - prev.get("small_seconds", 0.0)
        if d_ops > 0:
            self.observe_rtt(d_small / d_ops)

    def pull_index_metrics(self, metrics=None) -> None:
        """Fold the global dedup-query counters
        (``volsync_index_queries_total{result}``) into the dedup EWMA,
        diffing against this book's cursor."""
        if metrics is None:
            from volsync_tpu.metrics import GLOBAL as metrics
        hit = metrics.index_queries.labels(result="hit")._value.get()
        miss = metrics.index_queries.labels(result="miss")._value.get()
        with self._lock:
            prev_hit, prev_miss = self._index_cursor
            self._index_cursor = (hit, miss)
        self.observe_dedup(hit - prev_hit, (hit - prev_hit) + (miss - prev_miss))

    # -- readout ------------------------------------------------------------

    def decay(self, factor: float = 0.5) -> None:
        """Age the book toward its cold priors: each average moves
        ``factor`` of the way back and the sample counts shrink, so a
        long-idle book re-probes instead of trusting stale confidence."""
        if not math.isfinite(factor):
            return
        factor = min(max(factor, 0.0), 1.0)
        with self._lock:
            if self._change_rate is not None:
                self._change_rate += factor * (COLD_CHANGE_RATE
                                               - self._change_rate)
            if self._dedup_ratio is not None:
                self._dedup_ratio += factor * (COLD_DEDUP_RATIO
                                               - self._dedup_ratio)
            self._delta_samples = int(self._delta_samples * (1 - factor))
            self._dedup_samples = int(self._dedup_samples * (1 - factor))

    def snapshot(self) -> SyncStats:
        with self._lock:
            return SyncStats(
                change_rate=(COLD_CHANGE_RATE if self._change_rate is None
                             else self._change_rate),
                dedup_hit_ratio=(COLD_DEDUP_RATIO if self._dedup_ratio is None
                                 else self._dedup_ratio),
                bandwidth_bps=(COLD_BANDWIDTH if self._bandwidth is None
                               else self._bandwidth),
                latency_s=(COLD_LATENCY_S if self._latency is None
                           else self._latency),
                delta_samples=self._delta_samples,
                dedup_samples=self._dedup_samples,
                link_samples=self._link_samples,
            )


# -- per-consumer registry ---------------------------------------------------

_books_lock = lockcheck.make_lock("engine.syncstats.books")
_books: dict = {}


def book_for(name: str) -> SyncStatsBook:
    """Process-wide book per consumer name ("rsync", "restic", ...)."""
    with _books_lock:
        book = _books.get(name)
        if book is None:
            book = _books[name] = SyncStatsBook()
        return book


def reset_books() -> None:
    """Drop all shared books (tests)."""
    with _books_lock:
        _books.clear()
