"""Snapshot restore (the `restic restore` equivalent).

What `/entry.sh restore` does in the reference (mover-restic/
entry.sh:203-229): select a snapshot by RESTORE_AS_OF / SELECT_PREVIOUS
(here: Repository.select_snapshot), then materialize its tree into the
target volume. Restores are idempotent: existing files matching the
snapshot entry's size+mtime_ns are skipped (mode still re-applied), and
extra files in the target can optionally be deleted (--delete semantics).
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional

import numpy as np

from volsync_tpu import envflags
from volsync_tpu.repo.repository import Repository


class TreeRestore:
    def __init__(self, repo: Repository, *, workers: Optional[int] = None,
                 pipeline: Optional[bool] = None):
        """``workers`` restores that many files concurrently (default 4,
        env VOLSYNC_RESTORE_WORKERS): blob reads (store IO + decrypt)
        overlap file writes across independent files. Directory
        modes/mtimes are applied in a bottom-up pass AFTER every file
        write, so concurrent writes can't bump an already-stamped parent
        mtime.

        ``pipeline`` selects the pack-aware restore data plane
        (engine/restorepipe.py): fetches are planned per PACK, pulled
        through a shared single-flight PackCache by a bounded async
        pool, device-verified in ~64 MiB batches, and written at
        planned offsets. Default from VOLSYNC_RESTORE_PIPELINE (on);
        ``pipeline=False`` is the serial per-blob oracle the golden
        suite compares against."""
        self.repo = repo
        if workers is None:
            workers = envflags.restore_workers()
        self.workers = max(1, workers)
        if pipeline is None:
            pipeline = envflags.restore_pipeline_enabled()
        self.pipelined = pipeline
        # a RestoreGroup injects its shared cache here; None means the
        # pipelined path builds a private one per run
        self.pack_cache = None
        # Device-batched blob verification (same knob as repository
        # check): per-byte re-hashing rides the page-grid kernel in
        # ~64 MiB batches, host keeps only decrypt/decompress. Batches
        # verify BEFORE their bytes are written, so corruption is
        # caught exactly as early as the host path would.
        from volsync_tpu.envflags import env_bool

        self.device_verify = env_bool("VOLSYNC_DEVICE_VERIFY")
        # Sparse materialization (the rsync -S analogue,
        # mover-rsync/source.sh:54): aligned all-zero pages become
        # holes. Content-identical; VOLSYNC_SPARSE=0 restores dense
        # writes.
        self.sparse = env_bool("VOLSYNC_SPARSE", default=True)

    def run(self, snap_id: str, manifest: dict, dest,
            *, delete_extra: bool = True) -> dict:
        # Shared lock: a concurrent exclusive prune must not repack and
        # delete the packs this restore is mid-way through reading.
        # restore_snapshot() already holds the lock and calls _run_locked
        # directly (selection and walk under ONE lock, not two).
        with self.repo.lock(exclusive=False):
            return self._run_locked(snap_id, manifest, dest,
                                    delete_extra=delete_extra)

    def _run_locked(self, snap_id: str, manifest: dict, dest,
                    *, delete_extra: bool = True) -> dict:
        dest = Path(dest)
        dest.mkdir(parents=True, exist_ok=True)
        stats = {"files": 0, "bytes": 0, "skipped": 0, "deleted": 0}
        jobs: list[tuple[dict, Path]] = []
        dirs: list[tuple[Path, dict]] = []
        links: list[tuple[dict, Path]] = []
        self._walk_tree(manifest["tree"], dest, stats, jobs, dirs, links,
                        delete_extra=delete_extra)
        if jobs:
            self._restore_files(jobs, stats)
        # Hardlinks AFTER the file pool: the link's source path is only
        # guaranteed to exist (with final content) once every file job
        # has run. Metadata is shared with the source inode, already
        # applied there.
        for entry, target in links:
            source = dest / entry["hardlink_to"]
            if target.exists() and not target.is_symlink() \
                    and os.path.samestat(target.lstat(), source.lstat()):
                stats["skipped"] += 1
                continue
            if target.is_symlink() or target.exists():
                _rmtree(target)
            os.link(source, target)
            stats["files"] += 1
        # Directory metadata last, children-first: any earlier write
        # inside a directory would overwrite its restored mtime.
        for path, entry in reversed(dirs):
            _apply_xattrs(path, entry)  # before chmod: a read-only
            _apply_owner(path, entry)   # mode would block setxattr;
            os.chmod(path, entry["mode"])  # chown clears suid -> last
            os.utime(path, ns=(entry["mtime_ns"], entry["mtime_ns"]))
        return stats

    def _walk_tree(self, tree_id: str, dirpath: Path, stats: dict,
                   jobs: list, dirs: list, links: list, *,
                   delete_extra: bool):
        """Iterative DFS (explicit stack): depth bounded by memory,
        not the interpreter recursion limit. The one ordering invariant
        — ``dirs`` holds a parent BEFORE every descendant, so the
        caller's reversed() metadata pass runs children-first — holds
        because a directory is appended when first visited and its
        subtree is pushed afterwards."""
        stack = [(tree_id, dirpath)]
        while stack:
            cur_id, cur_dir = stack.pop()
            tree = json.loads(self.repo.read_blob(cur_id))
            wanted = {e["name"] for e in tree["entries"]}
            if delete_extra:
                for child in cur_dir.iterdir():
                    if child.name not in wanted:
                        _rmtree(child)
                        stats["deleted"] += 1
            subdirs = []
            for entry in tree["entries"]:
                target = cur_dir / entry["name"]
                if entry["type"] == "dir":
                    if target.is_symlink() or (target.exists()
                                               and not target.is_dir()):
                        target.unlink()
                    target.mkdir(exist_ok=True)
                    dirs.append((target, entry))
                    subdirs.append((entry["subtree"], target))
                elif entry["type"] == "symlink":
                    if target.is_symlink() or target.exists():
                        _rmtree(target)
                    os.symlink(entry["target"], target)
                    _apply_owner(target, entry)
                    _apply_xattrs(target, entry)
                    os.utime(target,
                             ns=(entry["mtime_ns"], entry["mtime_ns"]),
                             follow_symlinks=False)
                elif entry["type"] == "special":
                    self._restore_special(entry, target, stats)
                elif entry["type"] == "file":
                    if entry.get("hardlink_to"):
                        links.append((entry, target))
                    else:
                        jobs.append((entry, target))
            # reversed: the LIFO pop then visits subtrees in entry
            # order, matching the recursive walk
            stack.extend(reversed(subdirs))

    def _restore_special(self, entry: dict, target: Path, stats: dict):
        """FIFOs/sockets/device nodes (rsync -D analogue). Device nodes
        need CAP_MKNOD — without it the node is skipped, the rest of
        the restore proceeds (the reference's mover logs and continues
        the same way)."""
        import stat as stat_mod

        fmt = entry["fmt"]
        mode = entry["mode"]
        if target.is_symlink() or target.exists():
            st = target.lstat()
            if (stat_mod.S_IFMT(st.st_mode) == fmt
                    and st.st_rdev == entry.get("rdev", 0)):
                _apply_xattrs(target, entry)
                _apply_owner(target, entry)
                os.chmod(target, mode)
                os.utime(target,
                         ns=(entry["mtime_ns"], entry["mtime_ns"]))
                stats["skipped"] += 1
                return
            _rmtree(target)
        if stat_mod.S_ISFIFO(fmt):
            os.mkfifo(target, mode)
        else:
            try:
                os.mknod(target, fmt | mode, entry.get("rdev", 0))
            except PermissionError:
                # device/socket nodes need CAP_MKNOD; degrade like the
                # reference mover outside privileged pods. Real IO
                # errors (EROFS/ENOSPC) still raise.
                stats["skipped"] += 1
                return
        _apply_owner(target, entry)
        _apply_xattrs(target, entry)
        os.chmod(target, mode)
        os.utime(target, ns=(entry["mtime_ns"], entry["mtime_ns"]))
        stats["files"] += 1

    def _restore_files(self, jobs: list, stats: dict) -> None:
        """Restore every (entry, target) file job. Pipelined mode
        (VOLSYNC_RESTORE_PIPELINE, default on) plans pack-granular
        fetches and device-verifies in batches
        (engine/restorepipe.py); the serial fallback reads blob by
        blob under the per-file worker pool — the golden oracle."""
        if self.pipelined:
            from volsync_tpu.engine.restorepipe import (
                restore_files_pipelined,
            )

            restore_files_pipelined(self, jobs, stats)
            return
        if self.workers > 1 and len(jobs) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(self.workers) as pool:
                results = list(pool.map(
                    lambda j: self._restore_file(*j), jobs))
        else:
            results = [self._restore_file(*j) for j in jobs]
        for key, nbytes in results:
            stats[key] += 1
            stats["bytes"] += nbytes

    def _skip_unchanged(self, entry: dict, target: Path) -> bool:
        """The unchanged-file heuristic (size+mtime_ns, same keys
        backup trusts). Skipped files still get owner/mode/xattrs
        re-applied: those drift without touching mtime (they update
        only ctime) — xattrs first (a read-only final mode would block
        setxattr for unprivileged restores), chown before chmod (chown
        clears setuid bits)."""
        if (target.is_file() and not target.is_symlink()
                and target.stat().st_size == entry["size"]
                and target.stat().st_mtime_ns == entry["mtime_ns"]):
            _apply_xattrs(target, entry)
            _apply_owner(target, entry)
            os.chmod(target, entry["mode"])
            return True
        return False

    def _clear_target(self, target: Path) -> None:
        """Make ``target`` writable as a fresh regular file."""
        if target.is_symlink() or target.is_dir():
            _rmtree(target)
        elif target.exists():
            st = target.lstat()
            import stat as stat_mod

            if not stat_mod.S_ISREG(st.st_mode):
                # A special occupies the path: opening it "wb" would
                # block on a reader-less FIFO or write INTO a device
                # node — remove it first.
                target.unlink()
            elif st.st_nlink > 1:
                # Break a pre-existing hardlink before writing: an
                # in-place open("wb") would write through the SHARED
                # inode and corrupt the other linked path (and race
                # against its own restore job under the worker pool).
                target.unlink()

    def _finalize_file(self, entry: dict, target: Path) -> None:
        """Post-content metadata stamp, shared by both restore paths:
        xattrs before chmod (read-only modes), chown before chmod
        (chown clears suid), mtime last."""
        _apply_xattrs(target, entry)
        _apply_owner(target, entry)
        os.chmod(target, entry["mode"])
        os.utime(target, ns=(entry["mtime_ns"], entry["mtime_ns"]))

    def _restore_file(self, entry: dict, target: Path) -> tuple[str, int]:
        if self._skip_unchanged(entry, target):
            return "skipped", 0
        self._clear_target(target)
        write = _write_sparse if self.sparse else (
            lambda f_, d: f_.write(d))
        with open(target, "wb") as f:
            if self.device_verify:
                self._write_device_verified(f, entry["content"], write)
            else:
                for blob_id in entry["content"]:
                    write(f, self.repo.read_blob(blob_id))
            if self.sparse:
                # materialize a trailing hole (seek alone doesn't extend)
                f.truncate(f.tell())
        self._finalize_file(entry, target)
        return "files", entry["size"]

    _VERIFY_BATCH = 64 * 1024 * 1024

    def _write_device_verified(self, f, content: list, write):
        """Raw blob reads in ~64 MiB groups, ONE device dispatch
        re-derives the group's blob ids, bytes hit the file only after
        their group verifies (engine/chunker.verify_blob_batch);
        ``write(f, data)`` is the caller's (possibly sparse) writer."""
        from volsync_tpu.engine.chunker import verify_blob_batch
        from volsync_tpu.repo import crypto

        group: list[tuple[str, bytes]] = []
        gbytes = 0

        def flush():
            nonlocal group, gbytes
            bad = verify_blob_batch(group)
            if bad:
                raise crypto.IntegrityError(
                    f"restore: blob {bad[0]} content hash mismatch")
            for _, data in group:
                write(f, data)
            group, gbytes = [], 0

        for blob_id in content:
            data = self.repo.read_blob_raw(blob_id)
            group.append((blob_id, data))
            gbytes += len(data)
            if gbytes >= self._VERIFY_BATCH:
                flush()
        flush()


def _apply_owner(path, entry: dict) -> None:
    """uid/gid (rsync -o -g analogue). Backup records them on EVERY
    entry (root:root drift must converge too); an ABSENT key means a
    pre-format snapshot — unknown owner, leave the destination alone.
    Unprivileged restores degrade silently — chown needs CAP_CHOWN —
    matching the reference mover's behavior outside privileged pods."""
    if "uid" not in entry:
        return
    try:
        os.chown(path, entry["uid"], entry["gid"], follow_symlinks=False)
    except OSError:
        pass


def _apply_xattrs(path, entry: dict) -> None:
    """Restore recorded extended attributes (rsync -A analogue);
    follow_symlinks=False throughout. Namespaces the filesystem rejects
    (e.g. user.* on symlinks) are skipped — fidelity degrades to what
    the destination supports, as the reference movers' setfacl
    --restore does.

    Drifted extras are removed ONLY when the entry actually recorded
    xattrs: backup encodes the key only-when-present, so an absent key
    is indistinguishable from a pre-xattr-format snapshot — stripping
    on absence would destroy every destination xattr when restoring an
    older snapshot."""
    import base64

    if "xattrs" not in entry:
        return
    want = entry["xattrs"]
    try:
        have = os.listxattr(path, follow_symlinks=False)
    except OSError:
        return
    for n in have:
        if n not in want:
            try:
                os.removexattr(path, n, follow_symlinks=False)
            except OSError:
                pass
    for n, v in want.items():
        try:
            os.setxattr(path, n, base64.b64decode(v),
                        follow_symlinks=False)
        except OSError:
            pass


_ZERO_PAGE = bytes(4096)


def _write_sparse(f, data) -> None:
    """rsync -S analogue: aligned runs of all-zero 4 KiB pages become
    seeks (holes) instead of writes — content identical, allocation
    not. Accepts any buffer (the zero-copy restore pipeline hands
    pack-slice memoryviews straight through); the zero-run scan is
    numpy so no ``bytes`` materialization happens here.

    Hole semantics are pinned to the historical writer: data with no
    4096-zero-byte RUN anywhere writes densely in one call; wholly-zero
    data seeks its full length (including a partial tail); otherwise
    page-ALIGNED all-zero pages seek and everything else (partial tail
    included, even when zero) writes."""
    view = memoryview(data).cast("B")
    n = len(view)
    if n == 0:
        f.write(view)
        return
    arr = np.frombuffer(view, np.uint8)
    nz = np.flatnonzero(arr)
    if nz.size == 0:
        if n < 4096:  # no zero page exists -> the dense short-circuit
            f.write(view)
        else:
            f.seek(n, os.SEEK_CUR)
        return
    gaps = np.diff(nz) - 1
    longest = max(int(nz[0]), int(n - 1 - nz[-1]),
                  int(gaps.max()) if gaps.size else 0)
    if longest < 4096:
        f.write(view)
        return
    full = n // 4096
    zero_pages = np.logical_not(
        arr[:full * 4096].reshape(full, 4096).any(axis=1))
    bounds = np.flatnonzero(np.diff(zero_pages)) + 1
    starts = np.concatenate(([0], bounds))
    ends = np.concatenate((bounds, [full]))
    for s, e in zip(starts, ends):
        if zero_pages[s]:
            f.seek((e - s) * 4096, os.SEEK_CUR)
        else:
            f.write(view[s * 4096:e * 4096])
    if full * 4096 < n:
        f.write(view[full * 4096:])


def _rmtree(path: Path):
    """Depth-safe recursive delete. Explicit stack rather than
    shutil.rmtree: the walkers' any-depth guarantee must hold for
    delete_extra too, on every supported interpreter (shutil.rmtree
    recurses per directory level before CPython 3.12)."""
    if path.is_dir() and not path.is_symlink():
        stack = [(path, False)]
        while stack:
            d, emptied = stack.pop()
            if emptied:
                try:
                    d.rmdir()
                except OSError:
                    pass
                continue
            stack.append((d, True))
            try:
                entries = list(os.scandir(d))
            except OSError:
                continue
            for e in entries:
                try:
                    if e.is_dir(follow_symlinks=False):
                        stack.append((Path(e.path), False))
                    else:
                        os.unlink(e.path)
                except OSError:
                    pass  # best-effort, like rmtree(ignore_errors=True)
    else:
        # symlinks, regular files, AND specials (FIFO/socket/device —
        # is_file() is False for those; rmtree would leave them behind)
        path.unlink(missing_ok=True)


def restore_snapshot(repo: Repository, dest, *,
                     restore_as_of=None, previous: int = 0,
                     delete_extra: bool = True) -> Optional[dict]:
    """Select + restore in one call; returns stats or None if no snapshot
    matches the selectors.

    Selection happens under the same shared lock as the tree walk (shared
    locks nest), and the index is re-read once locked — otherwise a prune
    between select and walk could delete the chosen snapshot's packs and
    the restore would die mid-way with delete_extra damage already done.
    """
    with repo.lock(exclusive=False):
        repo.load_index()
        selected = repo.select_snapshot(restore_as_of=restore_as_of,
                                        previous=previous)
        if selected is None:
            return None
        snap_id, manifest = selected
        return TreeRestore(repo)._run_locked(snap_id, manifest, dest,
                                             delete_extra=delete_extra)
