"""Tree backup into a dedup repository (the `restic backup` equivalent).

What `/entry.sh backup` achieves in the reference (mover-restic/
entry.sh:58-72) — walk the volume, chunk file contents, dedup blobs by
content hash, store packs/index, record a snapshot — with the chunk+hash
inner loop on the TPU (engine/chunker.py) instead of inside a wrapped
binary. Unchanged-file detection against the parent snapshot (size +
mtime_ns, restic's heuristic) skips re-reading stable data.
"""

from __future__ import annotations

import json
import os
import stat as stat_mod
from pathlib import Path
from typing import Optional

from volsync_tpu import envflags
from volsync_tpu.engine.chunker import (
    DeviceChunkHasher,
    params_from_config,
    stream_chunk_batches,
)
from volsync_tpu.repo import blobid
from volsync_tpu.repo.repository import (
    BLOB_DATA,
    BLOB_TREE,
    BackupStats,
    Repository,
)


def _tree_id(tree_json: bytes) -> str:
    return blobid.blob_id(tree_json)


def _read_xattrs(path) -> dict:
    """Extended attributes (incl. POSIX ACLs, which live in
    system.posix_acl_*) as {name: base64}; the reference's rsync -A /
    rclone getfacl round-trip analogue. Filesystems without xattr
    support contribute nothing."""
    import base64

    try:
        names = os.listxattr(path, follow_symlinks=False)
    except OSError:
        return {}
    out = {}
    for n in sorted(names):
        try:
            out[n] = base64.b64encode(
                os.getxattr(path, n, follow_symlinks=False)).decode()
        except OSError:
            continue
    return out


def _load_parent_files(repo: Repository, parent_tree: str,
                       prefix: str = "") -> dict:
    """Flatten the parent snapshot's tree into {relpath: file entry}.

    Iterative (explicit stack): directory depth is bounded by memory,
    not the interpreter's recursion limit — a legal-but-deep volume
    (the reference's engines stream arbitrary depth) must not crash
    the walk."""
    out = {}
    stack = [(parent_tree, prefix)]
    while stack:
        tree_id, pfx = stack.pop()
        tree = json.loads(repo.read_blob(tree_id))
        for entry in tree["entries"]:
            path = f"{pfx}{entry['name']}"
            if entry["type"] == "file":
                # Hardlink-secondary entries carry no content of their
                # own; offering them for unchanged-file dedup would
                # match a now-unlinked file (nlink 2->1 leaves mtime
                # untouched) and resolve it to empty content.
                if "hardlink_to" not in entry:
                    out[path] = entry
            elif entry["type"] == "dir":
                stack.append((entry["subtree"], path + "/"))
    return out


class TreeBackup:
    def __init__(self, repo: Repository, *, skip_if_empty: bool = True,
                 hasher=None, workers: Optional[int] = None,
                 protocol: str = "cdc"):
        """``hasher`` swaps the chunk+hash engine: single-chip
        DeviceChunkHasher (default) or the mesh-sharded
        parallel.sharded_chunker.MeshChunkHasher — both produce
        bit-identical chunks/ids, so snapshots are interchangeable.

        ``protocol`` selects how file CONTENT is stored: ``"cdc"``
        (default, the restic-equivalent content-defined chunking),
        ``"full"`` (whole-file blobs — no sub-file dedup, but no chunk
        scan either; files above envflags.plan_full_blob_cap() still
        chunk, the planner's ``size_cap`` rule), or ``"auto"`` (the
        cost-model planner prices full vs cdc per file against the
        "restic" SyncStatsBook — engine/protoplan.py). All three
        produce valid interchangeable snapshots; they differ only in
        blob granularity, i.e. dedup ratio vs scan cost.

        ``workers`` hashes that many FILES concurrently (default 4, env
        VOLSYNC_BACKUP_WORKERS). Files are independent streams, so their
        per-segment result round-trips overlap while the device
        serializes their kernels — the same concurrency the reference
        gets from parallel mover pods (MaxConcurrentReconciles), here
        inside one backup. Snapshot bits are identical for any worker
        count: tree assembly is deterministic and the repository dedups
        concurrent identical blobs under its lock.
        """
        self.repo = repo
        want = params_from_config(repo.chunker_params)
        self.hasher = hasher or DeviceChunkHasher(want)
        self.params = self.hasher.params
        # An injected hasher chunking under different parameters would
        # still produce a valid-looking snapshot — but one that shares no
        # boundaries with prior ones, silently killing dedup. Refuse.
        if self.params != want:
            raise ValueError(
                f"hasher params {self.params} != repository chunker "
                f"params {want}")
        self.skip_if_empty = skip_if_empty
        if workers is None:
            workers = envflags.backup_workers()
        # A hasher that doesn't declare thread-safety (the mesh-sharded
        # engine: collective enqueue order must match across devices)
        # forces serial file hashing regardless of the knob.
        if not getattr(self.hasher, "thread_safe", False):
            workers = 1
        self.workers = max(1, workers)
        if protocol not in ("cdc", "full", "auto"):
            raise ValueError(f"unknown backup protocol {protocol!r}")
        self.protocol = protocol

    def run(self, root, *, hostname: str = "volsync",
            tags: Optional[list] = None,
            parent: Optional[str] = None) -> tuple[Optional[str], BackupStats]:
        """Backup ``root`` -> (snapshot id, stats). Returns (None, stats)
        for an empty volume when skip_if_empty (the reference's
        "directory is empty, skipping backup" — entry.sh:44-50).

        Holds a shared repository lock so a concurrent prune (exclusive)
        can never sweep this backup's freshly written packs.
        """
        with self.repo.lock(exclusive=False):
            # Re-read the index now that the lock is held: entries loaded
            # before it could reference packs a prune swept in between,
            # and dedup'ing against those would produce a snapshot whose
            # blobs no longer exist (restic reloads after locking too).
            self.repo.load_index()
            return self._run_locked(root, hostname=hostname, tags=tags,
                                    parent=parent)

    def _run_locked(self, root, *, hostname, tags, parent):
        root = Path(root)
        stats = BackupStats()
        snaps = self.repo.list_snapshots()
        if parent is None and snaps:
            parent = snaps[-1][0]
        parent_files = {}
        parent_manifest = None
        if parent:
            parent_manifest = dict(snaps).get(parent)
            if parent_manifest:
                parent_files = _load_parent_files(
                    self.repo, parent_manifest["tree"])
        if self.skip_if_empty and not any(root.iterdir()):
            return None, stats
        # Single-threaded walk (stats + unchanged-file dedup decisions),
        # concurrent per-file hashing, deterministic tree assembly.
        jobs: list[tuple[Path, str, object]] = []
        inode_first: dict = {}  # (st_dev, st_ino) -> rel of first sight
        skeleton = self._walk_dir(root, "", parent_files, stats, jobs,
                                  inode_first)
        contents: dict = {}
        if jobs:
            if self.workers > 1 and len(jobs) > 1:
                from concurrent.futures import ThreadPoolExecutor

                from volsync_tpu.obs import carry_context

                # carry_context: worker-thread spans (plan.decide when
                # protocol="auto", repo store spans) keep the caller's
                # tenant/trace context instead of starting orphaned.
                with ThreadPoolExecutor(self.workers) as pool:
                    for rel, resolved in pool.map(
                            carry_context(
                                lambda j: self._hash_file(*j, stats)),
                            jobs):
                        contents[rel] = resolved
            else:
                for j in jobs:
                    rel, resolved = self._hash_file(*j, stats)
                    contents[rel] = resolved
        tree_id = self._assemble_tree(skeleton, contents, stats)
        manifest = {
            "hostname": hostname,
            "paths": [str(root)],
            "tags": tags or [],
            "tree": tree_id,
            "parent": parent,
            "stats": stats.as_dict(),
        }
        # Durability order matters (restic's invariant): packs and index
        # deltas must hit the store BEFORE the snapshot that references
        # them becomes visible, or a crash in between leaves a snapshot
        # pointing at unwritten blobs that poisons every later backup.
        self.repo.flush()
        snap_id = self.repo.save_snapshot(manifest)
        return snap_id, stats

    # -- internals ----------------------------------------------------------

    def _walk_dir(self, dirpath: Path, rel: str, parent_files: dict,
                  stats: BackupStats, jobs: list,
                  inode_first: dict) -> dict:
        """Single-threaded walk -> a skeleton tree. File entries that
        need hashing carry content=None and append a job; unchanged
        files resolve to the parent's content list immediately. All
        stats counted here (except per-blob counts, which the
        repository updates under its own lock) so worker threads never
        touch the shared counters.

        Iterative (one child-iterator frame per open directory):
        pushing a frame and resuming the parent's iterator afterwards
        reproduces the recursion's exact in-order DFS — inode_first's
        "first sighting" stays deterministic — while directory depth
        is bounded by memory, not the interpreter recursion limit
        (the reference's engines stream arbitrary depth)."""
        root_skel = {"entries": []}

        def children(d: Path):
            return iter(sorted(d.iterdir(), key=lambda p: p.name))

        stack = [(children(dirpath), rel, root_skel["entries"])]
        while stack:
            it, cur_rel, entries = stack[-1]
            descended = False
            for child in it:
                st = child.lstat()
                meta = {"name": child.name, "mode": st.st_mode & 0o7777,
                        "mtime_ns": st.st_mtime_ns}
                xs = _read_xattrs(child)
                if xs:
                    # only-when-present: tree ids of xattr-less trees
                    # stay identical to pre-xattr snapshots (parent
                    # dedup keeps working across the format addition)
                    meta["xattrs"] = xs
                # owner/group (rsync -o -g, part of the reference's -a;
                # mover-rsync/source.sh:54). Recorded unconditionally:
                # root:root must be restorable too (ownership drift on
                # a root-owned file has to converge back), and restore
                # treats an ABSENT key — a pre-format snapshot — as
                # "unknown, leave the destination's owner alone".
                meta["uid"] = st.st_uid
                meta["gid"] = st.st_gid
                if stat_mod.S_ISLNK(st.st_mode):
                    entries.append({**meta, "type": "symlink",
                                    "target": os.readlink(child)})
                elif stat_mod.S_ISDIR(st.st_mode):
                    sub = {"entries": []}
                    entries.append({**meta, "type": "dir",
                                    "skeleton": sub})
                    stack.append((children(child),
                                  f"{cur_rel}{child.name}/",
                                  sub["entries"]))
                    descended = True
                    break
                elif stat_mod.S_ISREG(st.st_mode):
                    self._walk_file(child, f"{cur_rel}{child.name}",
                                    st, meta, entries, parent_files,
                                    stats, jobs, inode_first)
                elif stat_mod.S_ISFIFO(st.st_mode) or stat_mod.S_ISSOCK(
                        st.st_mode) or stat_mod.S_ISBLK(st.st_mode) \
                        or stat_mod.S_ISCHR(st.st_mode):
                    # specials (rsync -D, part of the reference's -a):
                    # FIFOs and sockets recreate from the mode; device
                    # nodes also carry st_rdev. Restore degrades
                    # gracefully without CAP_MKNOD (devices need it;
                    # FIFOs/sockets don't).
                    special = {**meta, "type": "special",
                               "fmt": stat_mod.S_IFMT(st.st_mode)}
                    if stat_mod.S_ISBLK(st.st_mode) or stat_mod.S_ISCHR(
                            st.st_mode):
                        special["rdev"] = st.st_rdev
                    entries.append(special)
            if not descended:
                stack.pop()
        return root_skel

    def _walk_file(self, child: Path, frel: str, st, meta: dict,
                   entries: list, parent_files: dict, stats: BackupStats,
                   jobs: list, inode_first: dict) -> None:
        """Regular-file walk step (shared by every _walk_dir frame)."""
        stats.files += 1
        # Hardlink preservation (reference: rsync -H in
        # mover-rsync/source.sh:54): later sightings of a
        # multiply-linked inode record a link to the FIRST sighting's
        # path (deterministic — the walk is sorted and
        # single-threaded) instead of re-hashing content.
        if st.st_nlink > 1:
            ino = (st.st_dev, st.st_ino)
            first = inode_first.get(ino)
            if first is not None:
                entries.append({**meta, "type": "file",
                                "size": st.st_size,
                                "hardlink_to": first,
                                "content": [], "rel": frel})
                return
            inode_first[ino] = frel
        stats.bytes_scanned += st.st_size
        prev = parent_files.get(frel)
        # One vectorized dedup query covers the whole previous content
        # list (vs a lock/probe round-trip per blob) — unchanged-file
        # checks on a warm repo are the dominant query source.
        if (prev is not None and prev["size"] == st.st_size
                and prev["mtime_ns"] == st.st_mtime_ns
                and (not prev["content"]
                     or bool(self.repo.has_blobs(prev["content"]).all()))):
            stats.blobs_dedup += len(prev["content"])
            stats.bytes_dedup += st.st_size
            content = list(prev["content"])
        elif st.st_size == 0:
            content = []
        else:
            content = None  # resolved by _hash_file
            jobs.append((child, frel, st))
        entries.append({**meta, "type": "file", "size": st.st_size,
                        "content": content, "rel": frel})

    def _assemble_tree(self, skeleton: dict, contents: dict,
                       stats: BackupStats) -> str:
        """Deterministic bottom-up tree-blob construction from the walk
        skeleton + hashed file contents (independent of hashing order,
        so snapshots are bit-identical for any worker count). Iterative
        post-order — children's tree blobs are written before the
        parent serializes references to them, at any depth."""
        done: dict = {}  # id(skeleton node) -> tree id
        stack = [(skeleton, False)]
        while stack:
            node, ready = stack.pop()
            if not ready:
                stack.append((node, True))
                for e in node["entries"]:
                    if e.get("skeleton") is not None:
                        stack.append((e["skeleton"], False))
                continue
            entries = []
            for e in node["entries"]:
                if e.get("skeleton") is not None:
                    sub = done.pop(id(e["skeleton"]))
                    e = {k: v for k, v in e.items() if k != "skeleton"}
                    e["subtree"] = sub
                elif e.get("type") == "file":
                    e = dict(e)
                    rel = e.pop("rel")
                    if e["content"] is None:
                        content, size, mtime_ns = contents[rel]
                        # Metadata observed AT read time, not walk
                        # time: a file rewritten between the walk's
                        # lstat and the worker's read must not pair new
                        # content with stale size/mtime (restore's
                        # unchanged-skip heuristic keys on them).
                        e["content"] = content
                        e["size"] = size
                        e["mtime_ns"] = mtime_ns
                entries.append(e)
            tree_json = json.dumps({"entries": entries},
                                   sort_keys=True).encode()
            tid = _tree_id(tree_json)
            self.repo.add_blob(BLOB_TREE, tid, tree_json, stats)
            done[id(node)] = tid
        return done[id(skeleton)]

    def _hash_file(self, path: Path, rel: str, st,
                   stats: BackupStats) -> tuple[str, tuple]:
        """Worker body: chunk+hash one file, store its blobs. Returns
        (rel, (content, size, mtime_ns)) where size is the byte count
        actually hashed and mtime_ns a post-read lstat — the entry must
        describe the content that was stored, not the walk-time stat.
        Per-blob stats are updated by the repository under its lock;
        everything else was counted in the walk."""
        if st.st_size <= self.params.min_size or self._wants_full(st.st_size):
            data = path.read_bytes()
            digest = blobid.blob_id(data)
            self.repo.add_blob(BLOB_DATA, digest, data, stats)
            content = [digest]
            hashed = len(data)
        else:
            # Large files stream through the native readahead reader
            # when available (native/volio.cpp): disk IO for segment N+1
            # overlaps the device hashing of segment N (open() fallback).
            content = []
            hashed = 0
            reader_cm = self._open_stream(path)
            with reader_cm as reader:
                for batch in stream_chunk_batches(reader.read, self.params,
                                                  hasher=self.hasher):
                    # one batched dedup query + one lock acquisition
                    # per device segment, not per chunk
                    self.repo.add_blobs(
                        BLOB_DATA,
                        [(digest, chunk) for chunk, digest in batch],
                        stats)
                    for chunk, digest in batch:
                        content.append(digest)
                        hashed += len(chunk)
        try:
            mtime_ns = path.lstat().st_mtime_ns
        except OSError:  # deleted mid-backup: keep the walk-time stamp
            mtime_ns = st.st_mtime_ns
        return rel, (content, hashed, mtime_ns)

    def _wants_full(self, size: int) -> bool:
        """Whole-file blob storage for this file? Pinned ``"full"`` says
        yes up to the blob cap; ``"auto"`` asks the planner (which
        applies the same cap as its ``size_cap`` rule); ``"cdc"`` never.
        """
        if self.protocol == "cdc":
            return False
        cap = envflags.plan_full_blob_cap()
        if self.protocol == "auto":
            from volsync_tpu.movers import common

            proto = common.plan_protocol(
                "restic", size, candidates=("full", "cdc"),
                full_cap=cap).protocol
        else:
            proto = self.protocol
        return proto == "full" and size <= cap

    @staticmethod
    def _open_stream(path: Path):
        from volsync_tpu.engine.chunker import _open_readahead

        return _open_readahead(path, 32 * 1024 * 1024)
