"""Data-plane engines: device chunk+hash pipeline, backup, restore.

These are what the reference's mover *containers* do (SURVEY.md §2.2),
re-built around the TPU kernels: the CDC + SHA-256 inner loop runs on
device (engine/chunker.py); the repository/tree logic stays host-side.
"""

from volsync_tpu.engine.backup import TreeBackup
from volsync_tpu.engine.chunker import (
    DeviceChunkHasher,
    params_from_config,
    stream_chunks,
)
from volsync_tpu.engine.protoplan import PlanDecision, decide
from volsync_tpu.engine.restore import TreeRestore, restore_snapshot
from volsync_tpu.engine.restorepipe import RestoreGroup
from volsync_tpu.engine.syncstats import SyncStatsBook, book_for

__all__ = [
    "TreeBackup",
    "TreeRestore",
    "RestoreGroup",
    "restore_snapshot",
    "DeviceChunkHasher",
    "stream_chunks",
    "params_from_config",
    "PlanDecision",
    "decide",
    "SyncStatsBook",
    "book_for",
]
