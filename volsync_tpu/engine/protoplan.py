"""Adaptive sync-protocol planner: FULL_COPY vs DELTA vs CDC_DEDUP.

The reference picks a mover protocol statically per CR; "Enabling
Cost-Benefit Analysis of Data Sync Protocols" (PAPERS.md) shows the
optimal choice flips with change rate, dedup ratio, and link quality —
signals a live ``SyncStatsBook`` (engine/syncstats.py) now tracks. This
module prices every candidate protocol per file with an explicit cost
model and picks the cheapest:

    cost(p) = wire_bytes(p) / bandwidth
            + round_trips(p) * latency
            + device_s(p)

    FULL_COPY:  wire = size                          rt = 1  dev = 0
    DELTA:      wire = sig_bytes(size)               rt = 2  dev = scan
                     + change_rate * size
                     + op-stream overhead
    CDC_DEDUP:  wire = (1 - dedup_ratio) * size      rt = 2  dev = chunk
                     + per-chunk metadata

``sig_bytes`` comes from the engine's own geometry seam
(deltasync.signature_geometry) — the real wire cost of the signature
round trip, not a re-derived approximation. Every decision is recorded
as a ``plan.decide`` span carrying the losing scores (auditable in the
flight recorder) and bumps
``volsync_svc_protocol_selected_total{protocol,reason}``. The
``VOLSYNC_SYNC_PROTO=auto|full|delta|cdc`` env knob overrides the model
per call (reason ``override``); movers opt into probe runs that force
an unpriced protocol once to seed an empty book (reason ``probe``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from volsync_tpu import envflags
from volsync_tpu.engine.deltasync import signature_geometry
from volsync_tpu.engine.syncstats import SyncStats
from volsync_tpu.metrics import GLOBAL as GLOBAL_METRICS
from volsync_tpu.obs import span

#: Protocol names — also the VOLSYNC_SYNC_PROTO vocabulary and the
#: ``protocol`` label values of svc_protocol_selected_total.
FULL_COPY = "full"
DELTA = "delta"
CDC_DEDUP = "cdc"
PROTOCOLS = (FULL_COPY, DELTA, CDC_DEDUP)

#: Closed vocabulary of the ``reason`` label (metrics.py): why a
#: decision came out the way it did.
REASON_COST = "cost"          # the model won on price
REASON_OVERRIDE = "override"  # VOLSYNC_SYNC_PROTO pinned it
REASON_PROBE = "probe"        # exploration to seed an empty stat book
REASON_NO_BASIS = "no_basis"  # destination has no prior copy
REASON_SIZE_CAP = "size_cap"  # too large for a whole-file blob

#: Device-time model terms: sustained delta-scan and CDC chunk+hash
#: rates. Deliberately conservative constants rather than live
#: measurements — device time is the smallest cost term (the link
#: dominates by orders of magnitude on any realistic deployment), so a
#: rough floor is enough to break ties without letting a noisy kernel
#: timing flip protocol choice.
DEVICE_DELTA_BPS = 2.0 * (1 << 30)
DEVICE_CDC_BPS = 1.5 * (1 << 30)

#: DELTA op-stream framing overhead per source block (copy ops coalesce,
#: literal runs carry framing) and CDC per-chunk metadata on the wire
#: (blob id + offset/length in the chunk list).
DELTA_OP_OVERHEAD_PER_BLOCK = 8
CDC_CHUNK_META_BYTES = 64
#: Model's expected CDC chunk size (repo default target; only the
#: metadata term depends on it, so repo-config drift is second-order).
CDC_AVG_CHUNK_BYTES = 1 << 20


@dataclasses.dataclass(frozen=True)
class ProtocolScore:
    protocol: str
    wire_bytes: float
    round_trips: int
    device_s: float
    cost_s: float


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    protocol: str
    reason: str
    scores: dict  # protocol -> ProtocolScore, every scored candidate

    def losing(self) -> list:
        return [s for p, s in sorted(self.scores.items())
                if p != self.protocol]


def _safe_div(num: float, den: float, fallback: float) -> float:
    """num/den with the hostile-input contract of syncstats: a zero,
    negative, NaN, or infinite denominator prices as ``fallback``
    instead of raising or poisoning the comparison with inf/NaN."""
    if not (math.isfinite(num) and math.isfinite(den)) or den <= 0:
        return fallback
    return num / den


def score_protocols(size: int, stats: SyncStats, *,
                    candidates=PROTOCOLS,
                    block_len: Optional[int] = None) -> dict:
    """Price each candidate protocol for one ``size``-byte file under
    ``stats``. Returns {protocol: ProtocolScore}."""
    size = max(int(size), 0)
    latency = stats.latency_s if math.isfinite(stats.latency_s) else 0.0
    latency = max(latency, 0.0)
    #: a link whose bandwidth is unknown/zero/NaN prices every byte at
    #: this many seconds — large enough that wire bytes still dominate,
    #: finite so comparisons stay total-ordered.
    worst_s_per_byte = 1.0
    scores: dict = {}
    for proto in candidates:
        if proto == FULL_COPY:
            wire = float(size)
            rt = 1
            dev = 0.0
        elif proto == DELTA:
            geo = signature_geometry(size, block_len)
            change = min(max(stats.change_rate, 0.0), 1.0) \
                if math.isfinite(stats.change_rate) else 1.0
            wire = (geo.sig_bytes + change * size
                    + DELTA_OP_OVERHEAD_PER_BLOCK * geo.n_blocks)
            rt = 2  # signature exchange, then the op stream
            dev = _safe_div(size, DEVICE_DELTA_BPS, 0.0)
        elif proto == CDC_DEDUP:
            dedup = min(max(stats.dedup_hit_ratio, 0.0), 1.0) \
                if math.isfinite(stats.dedup_hit_ratio) else 0.0
            n_chunks = -(-size // CDC_AVG_CHUNK_BYTES) if size else 0
            wire = ((1.0 - dedup) * size
                    + CDC_CHUNK_META_BYTES * n_chunks)
            rt = 2  # batched dedup-index query, then the unique blobs
            dev = _safe_div(size, DEVICE_CDC_BPS, 0.0)
        else:
            raise ValueError(f"unknown protocol {proto!r}")
        transfer = _safe_div(wire, stats.bandwidth_bps,
                             wire * worst_s_per_byte)
        scores[proto] = ProtocolScore(
            protocol=proto, wire_bytes=wire, round_trips=rt,
            device_s=dev, cost_s=transfer + rt * latency + dev)
    return scores


#: Module-cached metric children (the shardedindex pattern): .labels()
#: is a lock + dict lookup per call — real money when the planner runs
#: per file. Both label sets are closed vocabularies, so the cache is
#: bounded at |PROTOCOLS| x |reasons|.
_SELECTED_CHILDREN: dict = {}


def _selected(protocol: str, reason: str):
    child = _SELECTED_CHILDREN.get((protocol, reason))
    if child is None:
        child = _SELECTED_CHILDREN[(protocol, reason)] = (
            GLOBAL_METRICS.svc_protocol_selected.labels(
                protocol=protocol, reason=reason))
    return child


def decide(size: int, stats: SyncStats, *,
           basis_exists: bool = True,
           candidates=PROTOCOLS,
           allow_probe: bool = False,
           full_cap: Optional[int] = None,
           block_len: Optional[int] = None) -> PlanDecision:
    """Pick a protocol for one file/segment and record the decision.

    ``basis_exists``: whether the destination holds a prior copy —
    without one DELTA has nothing to diff against and drops out.
    ``allow_probe``: movers that CAN run the fancier protocol set this
    so an empty book gets seeded by one forced run instead of the
    pessimistic cold priors locking the planner into FULL_COPY forever.
    ``full_cap``: hard byte ceiling for FULL_COPY on stores that would
    persist it as a single blob (envflags.plan_full_blob_cap()).
    """
    # The span name is a lint-bounded literal (VL301); variability —
    # including every losing score, so the flight recorder can answer
    # "why not delta?" after the fact — rides in the attributes,
    # attached before the span closes.
    with span("plan.decide") as h:
        cand = tuple(p for p in candidates if p in PROTOCOLS) or (FULL_COPY,)
        if basis_exists is False and DELTA in cand and len(cand) > 1:
            cand = tuple(p for p in cand if p != DELTA)
            no_basis = True
        else:
            no_basis = False
        scores = score_protocols(size, stats, candidates=cand,
                                 block_len=block_len)
        ranked = sorted(scores.values(),
                        key=lambda s: (s.cost_s, s.protocol))
        chosen, reason = ranked[0].protocol, REASON_COST
        if no_basis and chosen == FULL_COPY:
            reason = REASON_NO_BASIS
        if allow_probe:
            if (DELTA in cand and stats.delta_samples == 0
                    and chosen != DELTA):
                chosen, reason = DELTA, REASON_PROBE
            elif (CDC_DEDUP in cand and stats.dedup_samples == 0
                    and chosen == FULL_COPY):
                chosen, reason = CDC_DEDUP, REASON_PROBE
        if (full_cap is not None and chosen == FULL_COPY
                and size > full_cap and len(ranked) > 1):
            chosen = next(s.protocol for s in ranked
                          if s.protocol != FULL_COPY)
            reason = REASON_SIZE_CAP
        override = envflags.sync_protocol()
        if override != "auto" and override in scores:
            chosen, reason = override, REASON_OVERRIDE
        attrs = {"size": size, "chosen": chosen, "reason": reason}
        for p, s in sorted(scores.items()):
            attrs[f"cost_{p}_s"] = round(s.cost_s, 6)
            attrs[f"wire_{p}"] = int(s.wire_bytes)
        h.attrs = attrs
    _selected(chosen, reason).inc()
    return PlanDecision(protocol=chosen, reason=reason, scores=scores)
