"""Pipelined restore data plane: pack-aware fetch, device verify, write.

The serial seed-era restore (engine/restore.py) issues one
``repo.read_blob()`` store round trip per chunk — fine on a local
filesystem, ruinous against an object store with tens of milliseconds
per GET, and exactly the shape PR 1 removed from the *write* path. This
module mirrors that work for reads, in four stages:

1. **Plan** (``restore.plan``): resolve every file's content list
   through the index, derive each blob's byte offset within its file
   (``raw_length`` is the plaintext length, known before any fetch),
   group needed blobs by the pack that holds them, and order pack
   fetches by first need — each pack is downloaded ONCE and all ranges
   within it coalesce into that one GET.
2. **Fetch** (``restore.fetch``): a bounded async pool
   (``VOLSYNC_RESTORE_FETCHERS`` threads, ``VOLSYNC_RESTORE_FETCH_WINDOW``
   packs submitted ahead) pulls whole packs through the shared
   ``PackCache`` (repo/packcache.py) — LRU with a byte budget,
   single-flight across concurrent restores.
3. **Verify** (``restore.verify``): chunk hashes re-derive DEVICE-SIDE
   in ~64 MiB batches (engine/chunker.verify_blob_batch — the same
   page-grid kernel repository check uses) while later fetches are
   still in flight. A batch's bytes reach disk only after the batch
   verifies. A mismatch first attempts READ-REPAIR
   (``VOLSYNC_SCRUB_READ_REPAIR``, default on): one fetch of the
   owning pack's mirror copy (``VOLSYNC_PACK_COPIES=2``), proven
   byte-perfect against the content-addressed pack id, heals the
   primary with one overwriting PUT (verify-then-replace — the
   repo/scrub.py protocol) and the corrupt blobs re-decode from the
   healthy body — so a restore storm survives bit-rot the scrubber
   has not reached yet. When no byte-perfect mirror exists the heal
   falls through to Reed-Solomon RECONSTRUCTION from any k healthy
   shards of the pack's ``ec/`` stripe (``repo.ec_reconstruct``,
   which proves the content-addressed pack id before returning).
   Only when neither arm yields a provable body does the mismatch
   raise, before any byte of that batch is written, and the failed
   restore leaves no partial file behind.
4. **Write** (``restore.write``): verified blobs are written at their
   planned offsets with the serial path's sparse semantics (aligned
   all-zero pages become holes; chunk boundaries are page-aligned, so
   the hole grid matches the serial writer's byte for byte).

The pipeline runs under the caller's shared-mode repository lock for
its WHOLE fetch window, so a concurrent two-phase pruner can mark packs
pending-delete mid-restore but never sweep them out from under the
fetch stage — pending-delete packs stay readable through their grace
period by design (docs/robustness.md, "Multi-writer protocol").

``RestoreGroup`` runs N snapshot restores in parallel sharing ONE
PackCache: a restore storm over the same snapshot fetches each pack
once for the whole group (the chaos drill asserts store GET counts).

Byte identity with the serial oracle is pinned by
tests/test_restorepipe.py; VOLSYNC_RESTORE_PIPELINE=0 selects the
serial path at runtime.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Optional

from volsync_tpu import envflags
from volsync_tpu.metrics import GLOBAL as GLOBAL_METRICS
from volsync_tpu.objstore.store import NoSuchKey
from volsync_tpu.obs import current_context, record_trigger, span, use_context
from volsync_tpu.repo import crypto
from volsync_tpu.repo.packcache import PackCache
from volsync_tpu.repo.repository import (
    RepoError,
    mirror_key,
    pack_key,
)

_M_RESTORE_BYTES = GLOBAL_METRICS.restore_bytes
# read-repair shares the scrub's heal accounting (PR 6/8 cached-child
# convention): a restore-side mirror heal is the same event as a
# scrub-side one, just detected earlier
_M_HEALED = GLOBAL_METRICS.scrub_packs.labels(outcome="healed")

#: sentinel pack key for blobs still buffered in an active write
#: pipeline (IndexEntry.pack == "") — read via the repository, no GET
_BUFFERED = ""


class _FilePlan:
    """One file's restore state: where it goes, how many blob writes
    remain, and the final length to truncate to (trailing holes)."""

    __slots__ = ("entry", "target", "total", "remaining", "claimed")

    def __init__(self, entry: dict, target: Path):
        self.entry = entry
        self.target = target
        self.total = 0
        self.remaining = 0
        self.claimed = False


def restore_files_pipelined(tr, jobs: list, stats: dict) -> None:
    """Restore every (entry, target) file job through the four-stage
    pipeline. ``tr`` is the owning TreeRestore (skip/clear/finalize
    semantics and the sparse toggle are ITS methods, so the two paths
    cannot drift); must run under the repo's shared store lock."""
    repo = tr.repo
    cache = tr.pack_cache
    if cache is None:
        cache = PackCache(repo.store, rescue=repo.ec_reconstruct)
    with span("restore.plan"):
        plans, placements, groups = _plan(tr, jobs, stats)
    if not plans:
        return
    try:
        _execute(tr, repo, cache, plans, placements, groups, stats)
    except BaseException:
        # zero partial files on a failed restore: complete files stay,
        # every claimed-but-incomplete target is removed
        for plan in plans:
            if plan.claimed and plan.remaining > 0:
                plan.target.unlink(missing_ok=True)
        raise


def _plan(tr, jobs: list, stats: dict):
    """Stage 1: skip-unchanged filtering, target claiming, offset
    derivation, and pack grouping (module docstring)."""
    repo = tr.repo
    plans: list[_FilePlan] = []
    # blob_id -> [(plan, offset_in_file)] across ALL files (dedup means
    # one fetched blob may land in many places)
    placements: dict[str, list] = {}
    # pack id (or _BUFFERED) -> [(blob_id, offset_in_pack, length)],
    # ordered by first need so early files' packs fetch first
    groups: "OrderedDict[str, list]" = OrderedDict()
    for entry, target in jobs:
        if tr._skip_unchanged(entry, target):
            stats["skipped"] += 1
            continue
        tr._clear_target(target)
        plan = _FilePlan(entry, target)
        # claim: create/truncate now, so a failure ANYWHERE later knows
        # exactly which targets to clean up
        with open(target, "wb"):
            pass
        plan.claimed = True
        offset = 0
        for blob_id in entry["content"]:
            ie = repo._entry(blob_id)
            if ie is None:
                raise RepoError(f"blob {blob_id} not in index")
            known = placements.get(blob_id)
            if known is None:
                placements[blob_id] = [(plan, offset)]
                grp = groups.get(ie.pack)
                if grp is None:
                    grp = groups[ie.pack] = []
                grp.append((blob_id, ie.offset, ie.length, ie.raw_length))
            else:
                known.append((plan, offset))
            offset += ie.raw_length
            plan.remaining += 1
        plan.total = offset
        plans.append(plan)
        if plan.remaining == 0:
            _finish_file(tr, plan, stats)
    return plans, placements, groups


def _mirror_heal(repo, cache: PackCache, pack_id: str) -> Optional[bytes]:
    """Read-repair heal: fetch the mirror copy, prove it byte-perfect
    (the pack id is the SHA-256 of the whole sealed blob) — falling
    through to Reed-Solomon reconstruction from the pack's ``ec/``
    stripe when no provable mirror exists — then heal the primary with
    one overwriting PUT (verify-then-replace, never delete-first) and
    evict the poisoned cache body so every later fetch sees healthy
    bytes. The mirror arm runs FIRST (one GET beats k shard GETs plus
    a decode) and costs exactly one mirror fetch. Returns the healthy
    body, or None when neither arm proves out (single-copy repository,
    swept mirror, fewer than k provable shards)."""
    body = None
    try:
        mirror = repo.store.get(mirror_key(pack_id))
        if hashlib.sha256(mirror).hexdigest() == pack_id:
            body = mirror
    except NoSuchKey:
        pass
    if body is None:
        try:
            body = repo.ec_reconstruct(pack_id)
        except NoSuchKey:
            return None
    with span("scrub.heal"):
        repo.store.put(pack_key(pack_id), body)
    cache.invalidate(pack_id)
    _M_HEALED.inc()
    record_trigger("scrub_corruption", pack=pack_id,
                   source="read_repair", healed=True)
    return body


def _execute(tr, repo, cache: PackCache, plans, placements,
             groups: "OrderedDict[str, list]", stats: dict) -> None:
    """Stages 2-4: bounded async pack fetch -> decode -> device-batched
    verify -> positional writes, consuming packs in plan order."""
    ctx = current_context()

    def fetch(pack_id: str) -> Optional[bytes]:
        # pool thread: re-enter the caller's trace so restore.fetch
        # spans attribute to the restore being served
        with use_context(ctx):
            if pack_id == _BUFFERED:
                return None
            return cache.get_pack(pack_id)

    window = envflags.restore_fetch_window()
    batch: list[tuple[str, bytes]] = []
    batch_bytes = 0
    # read-repair state: blob -> (pack, offset, length, raw_length)
    # provenance for everything in ``batch``, and a per-pack memo of
    # heal attempts (None = no healthy mirror) so a corrupt pack costs
    # exactly ONE mirror re-fetch however many blobs/batches it spans
    src: dict[str, tuple[str, int, int, int]] = {}
    healed: dict[str, Optional[bytes]] = {}
    repair_on = envflags.scrub_read_repair_enabled()

    def healthy_body(pack_id: str) -> Optional[bytes]:
        if not repair_on:
            return None
        if pack_id not in healed:
            healed[pack_id] = _mirror_heal(repo, cache, pack_id)
        return healed[pack_id]

    def decode_member(body, blob_id: str, p_off: int, p_len: int,
                      raw_len: int):
        # zero-copy slice: the sealed segment decodes straight off the
        # cached pack body; on the unencrypted+incompressible path
        # ``data`` stays a memoryview all the way to the positional
        # file write
        data = repo._decode_blob(memoryview(body)[p_off:p_off + p_len])
        if len(data) != raw_len:
            raise crypto.IntegrityError(
                f"restore: blob {blob_id} length "
                f"{len(data)} != indexed {raw_len}")
        return data

    def repair_batch(bad: list) -> None:
        """Re-decode the corrupt entries of ``batch`` in place from
        healed pack bodies and re-verify exactly those; raises
        IntegrityError when any blob stays bad (no healthy mirror)."""
        from volsync_tpu.engine.chunker import verify_blob_batch

        bad_set = set(bad)
        repaired: list[tuple[str, bytes]] = []
        for i, (blob_id, _data) in enumerate(batch):
            if blob_id not in bad_set:
                continue
            prov = src.get(blob_id)
            body = healthy_body(prov[0]) if prov is not None else None
            if body is None:
                record_trigger("restore_verify_fail", blob=blob_id)
                raise crypto.IntegrityError(
                    f"restore: blob {blob_id} content hash mismatch")
            batch[i] = (blob_id, decode_member(body, blob_id, *prov[1:]))
            repaired.append(batch[i])
        with span("restore.verify"):
            still_bad = verify_blob_batch(repaired)
        if still_bad:
            record_trigger("restore_verify_fail", blob=still_bad[0])
            raise crypto.IntegrityError(
                f"restore: blob {still_bad[0]} content hash mismatch")

    def flush_batch():
        nonlocal batch, batch_bytes
        if not batch:
            return
        from volsync_tpu.engine.chunker import verify_blob_batch

        with span("restore.verify"):
            bad = verify_blob_batch(batch)
        if bad:
            # device verify caught wrong bytes: heal from the mirror
            # before giving up (module docstring, stage 3)
            repair_batch(bad)
        with span("restore.write"):
            for blob_id, data in batch:
                for plan, offset in placements[blob_id]:
                    _write_at(tr, plan, offset, data)
                    plan.remaining -= 1
                    if plan.remaining == 0:
                        _finish_file(tr, plan, stats)
                _M_RESTORE_BYTES.inc(len(data)
                                     * len(placements[blob_id]))
        batch, batch_bytes = [], 0

    order = deque(groups.items())
    pending: "deque[tuple[str, list, object]]" = deque()
    with ThreadPoolExecutor(max_workers=envflags.restore_fetchers(),
                            thread_name_prefix="restore-fetch") as pool:
        try:
            while order or pending:
                while order and len(pending) < window:
                    pack_id, members = order.popleft()
                    pending.append(
                        (pack_id, members, pool.submit(fetch, pack_id)))
                pack_id, members, fut = pending.popleft()
                body = fut.result()
                for blob_id, p_off, p_len, raw_len in members:
                    if body is None:
                        # buffered in an active write pipeline of this
                        # process — no pack object to fetch yet
                        data = repo.read_blob_raw(blob_id)
                        if len(data) != raw_len:
                            raise crypto.IntegrityError(
                                f"restore: blob {blob_id} length "
                                f"{len(data)} != indexed {raw_len}")
                    else:
                        src[blob_id] = (pack_id, p_off, p_len, raw_len)
                        try:
                            data = decode_member(body, blob_id, p_off,
                                                 p_len, raw_len)
                        except Exception:  # noqa: BLE001 — an
                            # undecodable segment (torn seal, decompress
                            # error, wrong length) is the same silent-
                            # corruption class the verify stage catches;
                            # try the mirror before dying
                            mbody = healthy_body(pack_id)
                            if mbody is None:
                                raise
                            data = decode_member(mbody, blob_id, p_off,
                                                 p_len, raw_len)
                    batch.append((blob_id, data))
                    batch_bytes += len(data)
                    if batch_bytes >= tr._VERIFY_BATCH:
                        flush_batch()
            flush_batch()
        except BaseException:
            for _, _, fut in pending:
                fut.cancel()
            for _, _, fut in pending:
                try:
                    fut.exception()
                except BaseException:  # lint: ignore[VL003] — draining
                    # cancelled/failed stragglers so no fetch thread
                    # outlives the pipeline; the primary error below
                    # carries the failure
                    pass
            raise


def _write_at(tr, plan: _FilePlan, offset: int, data: bytes) -> None:
    """One positional blob write with the serial path's sparse
    semantics. Opens per write: restores span more files than fd
    limits, and a blob's writes are MiB-scale so the open is noise."""
    from volsync_tpu.engine.restore import _write_sparse

    with open(plan.target, "r+b") as f:
        f.seek(offset)
        if tr.sparse:
            _write_sparse(f, data)
        else:
            f.write(data)


def _finish_file(tr, plan: _FilePlan, stats: dict) -> None:
    """All content written: materialize trailing holes and stamp
    metadata exactly as the serial writer does."""
    with open(plan.target, "r+b") as f:
        f.truncate(plan.total)
    tr._finalize_file(plan.entry, plan.target)
    stats["files"] += 1
    stats["bytes"] += plan.entry["size"]


class RestoreGroup:
    """Parallel multi-snapshot restore sharing one PackCache.

    Queue jobs with :meth:`add`, run them with :meth:`run`. Every job
    gets its own shared-mode repository lock and its own thread; all
    pack fetches for jobs over the same store funnel through one
    single-flight cache, so N restores of one snapshot cost each pack
    ONE store GET for the whole group. Pass each job its OWN
    Repository handle — handles are cheap, and per-job locks/indices
    must not interleave on one object."""

    def __init__(self, *, budget_bytes: Optional[int] = None):
        self._budget = budget_bytes
        # safe unlocked: run() pre-populates per-store caches before
        # any thread starts; job threads only read (Thread.start() is
        # the happens-before edge)
        self._caches: dict[int, PackCache] = {}  # lint: ignore[VL404]
        self._jobs: list[tuple] = []

    def cache_for(self, store, rescue=None) -> PackCache:
        """The group's shared cache for ``store`` (one per distinct
        store object). ``rescue`` (first caller wins) is the cache's
        missing-primary fallback — ec_reconstruct is content-addressed
        and store-scoped, so any job's repository handle over the same
        store derives identical bodies."""
        cache = self._caches.get(id(store))
        if cache is None:
            cache = PackCache(store, budget_bytes=self._budget,
                              rescue=rescue)
            self._caches[id(store)] = cache
        return cache

    def add(self, repo, dest, *, restore_as_of=None, previous: int = 0,
            delete_extra: bool = True) -> None:
        self._jobs.append((repo, dest, restore_as_of, previous,
                           delete_extra))

    def stats(self) -> list[dict]:
        return [c.stats() for c in self._caches.values()]

    def run(self) -> list[Optional[dict]]:
        """Run every queued job concurrently; returns per-job stats
        (None where no snapshot matched) in add() order. The first
        job failure re-raises after EVERY thread has joined — no
        orphaned fetch pool keeps reading behind the caller's back."""
        from volsync_tpu.engine.restore import TreeRestore

        results: list = [None] * len(self._jobs)
        errors: list = [None] * len(self._jobs)
        # caches are created up front, single-threaded: cache_for is
        # not synchronized and must not race inside the job threads
        for repo, *_ in self._jobs:
            self.cache_for(repo.store, rescue=repo.ec_reconstruct)

        def one(i: int, repo, dest, as_of, previous, delete_extra):
            try:
                with repo.lock(exclusive=False):
                    repo.load_index()
                    selected = repo.select_snapshot(
                        restore_as_of=as_of, previous=previous)
                    if selected is None:
                        return
                    snap_id, manifest = selected
                    tr = TreeRestore(repo, pipeline=True)
                    tr.pack_cache = self.cache_for(repo.store)
                    results[i] = tr._run_locked(
                        snap_id, manifest, dest,
                        delete_extra=delete_extra)
            except BaseException as e:  # noqa: BLE001 — collected and
                errors[i] = e           # re-raised by the coordinator

        threads: list[threading.Thread] = []
        for i, job in enumerate(self._jobs):
            t = threading.Thread(target=one, args=(i, *job),
                                 name=f"restore-group-{i}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e
        return results
