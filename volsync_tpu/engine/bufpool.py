"""Reusable page-granular byte buffers for the zero-copy data plane.

The chunker fills pooled ``bytearray`` segments with ``readinto()`` and
hands every downstream consumer memoryview slices of them (chunk
payloads into the seal path, pack segments into ``ObjectStore.put``),
so the pool is what makes "no per-hop staging" sustainable: buffers are
recycled instead of re-allocated per segment, and the ledger
(obs/copyledger.py) can prove no copy happened in between.

Release is safe by construction, not by protocol: a ``bytearray`` with
exported buffer views refuses to resize (CPython raises BufferError on
any length change while ``ob_exports`` > 0), so ``release()`` probes
with a 1-byte append/undo. A buffer whose views are still held — a
chunk slice sitting in a seal-pool future, a test keeping chunks
around — is PARKED instead of recycled and re-probed on later
acquires. A pooled buffer is therefore never handed out while any view
of it is alive, no matter what consumers do with the slices.

Capacities are rounded to the 4 KiB page grid so segment fills and the
device pad lane stay page-aligned.
"""

from __future__ import annotations

from collections import defaultdict

from volsync_tpu.analysis import lockcheck

_PAGE = 4096

#: Free-list byte budget: beyond it released buffers are dropped to the
#: allocator instead of retained (a restore storm must not pin every
#: segment buffer it ever touched).
_MAX_FREE_BYTES = 256 * 1024 * 1024
#: Parked buffers kept for re-probing; older ones are abandoned to GC
#: (their live views keep them alive exactly as long as needed).
_MAX_PARKED = 16


class BufferPool:
    """Size-bucketed free list of reusable ``bytearray`` buffers."""

    def __init__(self, max_free_bytes: int = _MAX_FREE_BYTES,
                 max_parked: int = _MAX_PARKED):
        self._lock = lockcheck.make_lock("engine.bufpool")
        self._free: defaultdict = defaultdict(list)  # size -> [bytearray]
        self._free_bytes = 0
        self._max_free_bytes = max_free_bytes
        self._parked: list = []
        self._max_parked = max_parked

    @staticmethod
    def _reusable(buf: bytearray) -> bool:
        """True iff no memoryview of ``buf`` is still exported (resize
        probe — see module docstring)."""
        try:
            buf.append(0)
        except BufferError:
            return False
        del buf[-1:]
        return True

    def acquire(self, size: int) -> bytearray:
        """A buffer of exactly ``size`` bytes (rounded up to the page
        grid), recycled when one is free, freshly allocated otherwise.
        Contents are UNDEFINED — callers track their own fill extent."""
        size = (size + _PAGE - 1) // _PAGE * _PAGE
        with self._lock:
            if self._parked:
                still = []
                for buf in self._parked:
                    if self._reusable(buf):
                        self._stash(buf)
                    else:
                        still.append(buf)
                self._parked = still
            bucket = self._free.get(size)
            if bucket:
                self._free_bytes -= size
                return bucket.pop()
        return bytearray(size)

    def release(self, buf: bytearray) -> None:
        """Return ``buf`` to the pool. Buffers with live exported views
        are parked, never recycled, so callers may release eagerly."""
        with self._lock:
            if not self._reusable(buf):
                self._parked.append(buf)
                if len(self._parked) > self._max_parked:
                    self._parked.pop(0)
                return
            self._stash(buf)

    def _stash(self, buf: bytearray) -> None:
        if self._free_bytes + len(buf) > self._max_free_bytes:
            return
        self._free[len(buf)].append(buf)
        self._free_bytes += len(buf)


#: Process-wide pool shared by every stream/restore worker — buffer
#: sizes converge to a handful of segment-geometry buckets, so sharing
#: maximizes reuse across concurrent streams.
GLOBAL = BufferPool()
