"""Streaming CDC chunk+hash pipeline: the mover's device hot path.

Replaces the chunk/hash core of the engine the reference wraps
(mover-restic/entry.sh:63 `restic backup` — Rabin CDC + per-blob SHA-256
on CPU): a segment of the input stream is uploaded to the device once,
gear-hash CDC candidates and per-chunk SHA-256 digests both run on that
resident buffer, and only (boundaries, digests) come back to the host.

Streaming determinism: each segment handed to the CDC starts exactly at a
chunk boundary, and no cut is eligible before min_size-1 >= 31 positions
in, so every eligible position sees its full 32-byte gear window within
the segment — boundaries are bit-identical to one-shot chunking of the
whole stream (see ops/gearcdc.py).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Iterator, Optional

import numpy as np

from volsync_tpu import envflags
from volsync_tpu.engine import bufpool
from volsync_tpu.obs import record_copy, span
from volsync_tpu.repo import blobid

from volsync_tpu.ops.gearcdc import (
    GearParams,
    cdc_candidates,
    cdc_candidates_aligned_packed,
    select_boundaries,
)
from volsync_tpu.ops.sha256 import (
    sha256_chunks_device,
    sha256_leaves_device,
)

log = logging.getLogger("volsync_tpu.engine")


def params_from_config(cfg: dict) -> GearParams:
    # Repos written before the aligned-cut format carry no "align" key;
    # they keep the fully shift-invariant align=1 behavior forever so
    # their existing chunk boundaries (and dedup) stay valid.
    return GearParams(min_size=cfg["min_size"], avg_size=cfg["avg_size"],
                      max_size=cfg["max_size"], seed=cfg["seed"],
                      align=cfg.get("align", 1))


def _pow2ceil(n: int, lo: int = 1) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _buffer_bucket(length: int) -> int:
    """Pad target for input buffers. Shapes are static under jit, so an
    unbounded variety of buffer lengths (every file tail is unique) would
    mean a fresh multi-second XLA compile each — pad into a small fixed
    set instead: pow2 up to 8 MiB, then multiples of 8 MiB."""
    if length <= 8 * 1024 * 1024:
        return _pow2ceil(length, 64 * 1024)
    m = 8 * 1024 * 1024
    return (length + m - 1) // m * m


class DeviceChunkHasher:
    """chunk+hash a byte buffer with one host->device upload.

    All device call shapes are drawn from small bounded bucket sets
    (padded buffer sizes, fixed candidate capacity, size-classed chunk
    batches with pow2 lane counts) so the jit cache converges after a few
    segments regardless of workload shape.

    With the page-aligned format (align == 4096, the repo default) the
    whole segment runs as ONE fused device program with ONE small result
    fetch (ops/segment.py): candidates, the FastCDC walk, leaf hashing,
    and Merkle-root assembly all stay on device, and only the chunk
    table + 32-byte roots come back (~40 bytes per ~1 MiB chunk instead
    of 32 bytes per 4 KiB leaf plus a candidate round-trip). The chunk
    list is then known only at ``finish()`` — segments of ONE stream
    serialize on that fetch, and scaling comes from concurrent streams
    (many CRs per chip), matching the reference's concurrency model
    (reference: controllers/replicationsource_controller.go:145).
    64 <= align < 4096 keeps the split-phase pipeline (synchronous
    boundary walk, leaf hashing left in flight); align=1 the legacy
    shift-invariant path.
    """

    #: Safe to drive from concurrent threads: no per-call mutable state
    #: (the fused hasher is stateless; jit caches are global/locked).
    thread_safe = True

    #: Owners that manage their own batching (MoverJaxServer) set this
    #: False so the process-wide VOLSYNC_BATCH_SEGMENTS hook cannot
    #: override their explicit per-request configuration.
    use_shared_batcher = True

    #: ``begin()`` takes ``valid_len``: stream_chunk_batches hands it a
    #: view already padded to the device bucket (zeroed pad lane), so no
    #: np.pad copy happens per segment. Hashers without the kwarg (mesh,
    #: bench hosts) get the exact-length view instead.
    accepts_prepadded = True

    def __init__(self, params: GearParams):
        self.params = params
        from volsync_tpu.ops.segment import LEAF_SIZE

        if params.align == LEAF_SIZE:  # the page-aligned fused format
            from volsync_tpu.ops.segment import FusedSegmentHasher

            self.fused = FusedSegmentHasher(params)
        else:
            self.fused = None

    def process(self, buffer, *, eof: bool = True) -> list[tuple[int, int, str]]:
        """-> [(start, length, sha256-hex)] covering ``buffer`` (the tail
        is withheld when not ``eof`` — the caller re-feeds it)."""
        return self.begin(buffer, eof=eof).finish()

    def begin(self, buffer, *, eof: bool = True,
              valid_len: Optional[int] = None) -> "PendingSegment":
        """Upload + dispatch the segment's device work, leaving it IN
        FLIGHT. On the fused path the chunk table itself is part of the
        one in-flight result, so ``.chunks``/``.end`` block until the
        fetch; on the split-phase path (align < 4096) the boundary walk
        runs synchronously here and only the leaf digests stay in
        flight.

        ``buffer`` may be bytes/bytearray/memoryview or a uint8 ndarray;
        it is never copied on the host here unless it must be padded to
        a device bucket. Callers that already hold a bucket-padded view
        (stream_chunk_batches' pooled segments) pass the padded view
        plus ``valid_len`` — the zero-pad np.pad copy then disappears.

        When batching is enabled (ops/batcher._batching_enabled:
        VOLSYNC_BATCH_SEGMENTS=1, or unset on a TPU backend — the
        backend-aware default) the fused path routes through the
        process-wide microbatcher: concurrent workers' segments —
        different files of one TreeBackup, different CRs' movers in one
        operator — coalesce into single cross-PVC batched
        dispatches."""
        import jax.numpy as jnp

        if isinstance(buffer, (bytes, bytearray, memoryview)):
            buffer = np.frombuffer(buffer, dtype=np.uint8)
        have = int(buffer.shape[0])
        length = have if valid_len is None else int(valid_len)
        if length == 0:
            return PendingSegment([], None, None)
        p = self.params
        if length <= p.min_size:
            if not eof:
                return PendingSegment([], None, None)
            # hashlib consumes the ndarray view directly — no tobytes()
            # round-trip for the small-buffer host path.
            return PendingSegment(
                [(0, length, blobid.blob_id(buffer[:length]))], None, None)

        if (self.use_shared_batcher and self.fused is not None
                and self.fused.segment_device_fn is None):
            from volsync_tpu.ops.batcher import shared_batcher

            batcher = shared_batcher(p)
            if batcher is not None:
                # consumed == the last chunk's end by the walk's
                # semantics, which is exactly what PendingSegment.end
                # derives from the chunk list. The ndarray passes
                # through uncopied (submit blocks, so it stays alive).
                chunks, _consumed = batcher.submit(buffer, length, eof)
                return PendingSegment(chunks, None, None)

        padded = _buffer_bucket(length)
        if have < padded:
            record_copy("device.pad", length)
            buffer = np.pad(buffer, (0, padded - have))
        elif have > padded:
            buffer = buffer[:padded]
        return self.begin_device(jnp.asarray(buffer), length, eof=eof)

    def begin_device(self, dev, length: int, *,
                     eof: bool = True) -> "PendingSegment":
        from volsync_tpu.obs import span

        p = self.params
        if self.fused is not None:
            with span("engine.fused_dispatch"):
                inflight = self.fused.dispatch(dev, length, eof=eof)
            return PendingSegment.fused_segment(
                self.fused, dev, length, inflight, eof)
        with span("engine.candidates"):
            idx_s, idx_l = self._candidates(dev, length)
        with span("engine.boundary_walk"):
            chunks = select_boundaries(idx_s, idx_l, length, p, eof=eof)
        if not chunks:
            return PendingSegment([], None, None)
        if p.align >= 64:
            # Split-phase aligned path (64 <= align < 4096): leaf digests
            # stay in flight; chunks are known synchronously.
            plan = _leaf_plan(chunks)
            dev_digests = _dispatch_leaves(
                dev, plan[0], plan[1], plan[2],
                leaf_fn=self.leaf_device_fn)
            return PendingSegment.split_phase(chunks, (plan, dev_digests))
        # Legacy unaligned path: synchronous gather hashing.
        hexes = device_span_roots(dev, chunks, aligned=False)
        return PendingSegment(
            [(int(s), int(l), h) for (s, l), h in zip(chunks, hexes)],
            None, None)

    def process_device(self, dev, length: int, *,
                       eof: bool = True) -> list[tuple[int, int, str]]:
        """The device pipeline on an already-resident padded buffer —
        what process() runs after upload, and what bench.py measures:
        one fused dispatch (candidates -> on-device walk -> leaf digests
        -> roots) plus its single result fetch."""
        return self.begin_device(dev, length, eof=eof).finish()

    def _candidates(self, dev, length: int):
        p = self.params
        padded = int(dev.shape[0])
        if p.align > 1:
            cand = self.cand_device_fn or (
                lambda d, cap: cdc_candidates_aligned_packed(
                    d, seed=p.seed, mask_s=p.mask_s, mask_l=p.mask_l,
                    align=p.align, max_candidates=cap, valid_len=length))
            cap = 4096  # expected count: padded/avg_size << 4096
            while True:
                packed = np.asarray(cand(dev, cap))
                c = int(packed[-1])
                if c <= cap:
                    break
                cap = _pow2ceil(c, cap * 2)
            pos = packed[:c]
            flags = packed[cap: cap + c].astype(bool)
            return pos[flags], pos
        # Classic unaligned path: one candidate per 64 bytes covers any
        # mask down to 2^-6 density; denser (adversarial) data retries
        # with a doubled cap.
        cap = padded // 64
        while True:
            # valid_len masks the zero-padded tail on device: padding can
            # neither add candidates nor inflate the overflow counts.
            idx_s, count_s, idx_l, count_l = cdc_candidates(
                dev, seed=p.seed, mask_s=p.mask_s, mask_l=p.mask_l,
                max_candidates=cap, valid_len=length,
            )
            cs, cl = int(count_s), int(count_l)
            if cs <= cap and cl <= cap:
                break
            cap = _pow2ceil(max(cs, cl), cap * 2)
        return np.asarray(idx_s)[:cs], np.asarray(idx_l)[:cl]

    #: Override points for the two fused device dispatches (benchmarks
    #: compose a content-salt into the same programs; None = the library
    #: kernels sha256_leaves_device / cdc_candidates_aligned_packed).
    leaf_device_fn = None
    cand_device_fn = None


def device_leaf_digests(dev, leaf_starts: list[int],
                        leaf_lengths: list[int]) -> list[bytes]:
    """SHA-256 digests of arbitrary <=4 KiB slices of a device buffer,
    every slice an independent lane (wide batch, 65-step scan, a single
    compiled shape per lane-count bucket)."""
    import jax.numpy as jnp

    lanes = _pow2ceil(len(leaf_starts), 128)
    starts = np.zeros((lanes,), np.int32)
    lengths = np.zeros((lanes,), np.int32)
    starts[: len(leaf_starts)] = leaf_starts
    lengths[: len(leaf_lengths)] = leaf_lengths
    digests = np.asarray(sha256_chunks_device(  # lint: ignore[VL501] one batched 32 B/lane digest download — this helper's contract
        dev, jnp.asarray(starts), jnp.asarray(lengths),
        max_len=blobid.LEAF_SIZE,
    )).astype(">u4")
    # Digest download: 32 B per lane, metadata not payload.
    leaf_bytes = digests.tobytes()  # lint: ignore[VL106] digest lanes
    return [leaf_bytes[32 * k : 32 * (k + 1)]
            for k in range(len(leaf_starts))]


def _leaf_plan(chunks: list[tuple[int, int]]):
    """Host-side leaf assignment for a chunk list (aligned cuts): which
    leaves are full (strided path) vs short tails (gather path), plus the
    bookkeeping to reassemble per-chunk leaf sequences afterwards."""
    full_rows: list[int] = []
    short_starts: list[int] = []
    short_lengths: list[int] = []
    slot: list[tuple[bool, int]] = []      # leaf -> (is_full, index)
    spans: list[tuple[int, int]] = []      # chunk -> (first leaf, count)
    for start, length in chunks:
        first = len(slot)
        n = blobid.leaf_count(length)
        for k in range(n):
            off = k * blobid.LEAF_SIZE
            s = start + off
            l = min(blobid.LEAF_SIZE, length - off)
            if l == blobid.LEAF_SIZE:
                assert s % 64 == 0, "aligned path requires 64B leaf starts"
                slot.append((True, len(full_rows)))
                full_rows.append(s // 64)
            else:
                slot.append((False, len(short_starts)))
                short_starts.append(s)
                short_lengths.append(l)
        spans.append((first, n))
    return full_rows, short_starts, short_lengths, slot, spans


def _dispatch_leaves(dev, full_rows, short_starts, short_lengths,
                     leaf_fn=None):
    """Launch the single fused leaf dispatch; returns the in-flight
    [F + T, 8] device array (callers fetch it as late as possible)."""
    import jax.numpy as jnp

    lanes_f = _pow2ceil(len(full_rows), 128)
    lanes_t = _pow2ceil(max(len(short_starts), 1), 8)
    rows = np.zeros((lanes_f,), np.int32)
    rows[: len(full_rows)] = full_rows
    ts = np.zeros((lanes_t,), np.int32)
    tl = np.zeros((lanes_t,), np.int32)
    ts[: len(short_starts)] = short_starts
    tl[: len(short_lengths)] = short_lengths
    return (leaf_fn or sha256_leaves_device)(
        dev, jnp.asarray(rows), jnp.asarray(ts), jnp.asarray(tl),
        leaf_len=blobid.LEAF_SIZE), lanes_f


def _assemble_roots(chunks, plan, digests_np, lanes_f) -> list[str]:
    full_rows, short_starts, _, slot, spans = plan
    flat = digests_np.astype(">u4").tobytes()  # lint: ignore[VL106] 32 B/leaf digest wire form, metadata not payload

    def leaf(is_full: bool, i: int) -> bytes:
        base = (i if is_full else lanes_f + i) * 32
        return flat[base: base + 32]

    return [
        blobid.root_from_leaves(length,
                                [leaf(*slot[first + k]) for k in range(n)])
        for (first, n), (_, length) in zip(spans, chunks)
    ]


class PendingSegment:
    """A segment whose device work may still be in flight.

    Split-phase (64 <= align < 4096) and legacy (align=1) segments know
    their chunk list immediately; the fused path (align == 4096,
    ops/segment.py) learns it from the one result fetch, so ``chunks``
    / ``end`` force ``finish()`` there. Either way the public protocol
    is: ``.end`` = bytes consumed, ``finish()`` ->
    [(start, length, blob-id-hex)]."""

    def __init__(self, done, chunks, inflight):
        self._done = done
        self._inflight = inflight
        self._fused = None
        self._chunks = (chunks if chunks is not None
                        else [(s, l) for s, l, _ in (done or [])])

    @classmethod
    def fused_segment(cls, fsh, dev, length, inflight, eof):
        seg = cls([], None, None)
        seg._done = None
        seg._chunks = None
        seg._fused = (fsh, dev, length, inflight, eof)
        return seg

    @classmethod
    def split_phase(cls, chunks, inflight):
        seg = cls([], None, None)
        seg._done = None
        seg._chunks = list(chunks)
        seg._inflight = inflight
        return seg

    @property
    def chunks(self) -> list[tuple[int, int]]:
        if self._chunks is None:
            self.finish()
        return self._chunks

    @property
    def end(self) -> int:
        """One past the last covered byte (0 if nothing was emitted)."""
        if self._fused is not None and self._done is None:
            self.finish()
            return self._consumed
        if not self.chunks:
            return 0
        s, l = self.chunks[-1][0], self.chunks[-1][1]
        return int(s) + int(l)

    def finish(self) -> list[tuple[int, int, str]]:
        if self._done is not None:
            return self._done
        from volsync_tpu.obs import span

        if self._fused is not None:
            fsh, dev, length, inflight, eof = self._fused
            with span("engine.fused_fetch"):
                chunks, consumed = fsh.finish(dev, length, inflight, eof=eof)
            self._done = chunks
            self._chunks = [(s, l) for s, l, _ in chunks]
            self._consumed = consumed
            return self._done
        (plan, (dev_digests, lanes_f)) = self._inflight
        with span("engine.leaf_fetch_assemble"):
            hexes = _assemble_roots(self._chunks, plan,
                                    np.asarray(dev_digests), lanes_f)
        self._done = [(int(s), int(l), h)
                      for (s, l), h in zip(self._chunks, hexes)]
        self._inflight = None
        return self._done


def device_span_roots(dev, chunks: list[tuple[int, int]], *,
                      aligned: bool = False, leaf_fn=None) -> list[str]:
    """Merkle blob ids for (start, length) slices of the device buffer
    (repo/blobid.py): every 4 KiB leaf of every chunk hashes as one
    independent lane, then the tiny roots combine host-side.

    ``aligned=True`` asserts every chunk start is 64-byte aligned
    (GearParams.align >= 64): full leaves then take the strided
    row-gather path and only each chunk's short tail leaf (<4 KiB)
    pays the generic gather kernel, in ONE fused dispatch.
    """
    if aligned:
        plan = _leaf_plan(chunks)
        dev_digests, lanes_f = _dispatch_leaves(
            dev, plan[0], plan[1], plan[2], leaf_fn=leaf_fn)
        return _assemble_roots(chunks, plan, np.asarray(dev_digests),
                               lanes_f)
    leaf_starts: list[int] = []
    leaf_lengths: list[int] = []
    spans: list[tuple[int, int]] = []  # (first leaf index, count) per chunk
    for start, length in chunks:
        first = len(leaf_starts)
        n = blobid.leaf_count(length)
        for k in range(n):
            off = k * blobid.LEAF_SIZE
            leaf_starts.append(start + off)
            leaf_lengths.append(min(blobid.LEAF_SIZE, length - off))
        spans.append((first, n))
    leaves = device_leaf_digests(dev, leaf_starts, leaf_lengths)
    return [
        blobid.root_from_leaves(length, leaves[first : first + n])
        for (first, n), (_, length) in zip(spans, chunks)
    ]


def _upload_padded(buffer):
    """Host bytes/array -> device array padded to a bucketed length.
    Already-bucketed inputs (the staging buffers callers preallocate)
    upload without any host-side pad copy."""
    import jax.numpy as jnp

    if isinstance(buffer, (bytes, bytearray, memoryview)):
        buffer = np.frombuffer(buffer, dtype=np.uint8)
    length = int(buffer.shape[0])
    padded = _buffer_bucket(max(length, 1))
    if padded != length:
        record_copy("device.pad", length)
        buffer = np.pad(buffer, (0, padded - length))
    return jnp.asarray(buffer)


def _spans_page_disjoint(spans: list[tuple[int, int]]) -> bool:
    """True iff every span starts on the 4 KiB page grid and no two
    spans touch the same page — the precondition for the shared
    page-digest table in ops/segment.span_roots_device (its per-span
    tail override mutates that table in place). Zero-length spans touch
    no pages (they're hashed host-side)."""
    last_page = -1
    for s, l in sorted(spans):
        if s % blobid.LEAF_SIZE != 0:
            return False
        if l <= 0:
            continue
        if s // blobid.LEAF_SIZE <= last_page:
            return False
        last_page = (s + l - 1) // blobid.LEAF_SIZE
    return True


def hash_spans(buffer, spans: list[tuple[int, int]]) -> list[str]:
    """Device-batched blob ids for (start, length) spans of one buffer.

    The checksum-compare primitive for the rclone-style mover (the
    reference's `rclone sync --checksum`, mover-rclone/active.sh:19).
    When every span start is 4 KiB-aligned (the mover's packer pads to
    the page grid), this is ONE fused dispatch + ONE [N, 8] fetch:
    all full leaves are pages of the buffer (contiguous hashing, no
    gather) and only each span's short tail pays the gather path
    (ops/segment.span_roots_device). Unaligned spans fall back to the
    generic per-leaf gather batch.
    """
    if not spans:
        return []
    if _spans_page_disjoint(spans):
        import jax.numpy as jnp

        from volsync_tpu.ops.segment import span_roots_device

        n_cap = _pow2ceil(len(spans), 128)
        starts = np.full((n_cap,), 0, np.int32)
        lengths = np.full((n_cap,), -1, np.int32)  # padding lanes
        starts[: len(spans)] = [s for s, _ in spans]
        lengths[: len(spans)] = [l for _, l in spans]
        # Zero-length spans consume no pages, so their device tail
        # override would collide with whatever span owns that page —
        # their id is a constant anyway.
        empty = lengths[: len(spans)] == 0
        lengths[: len(spans)][empty] = -1
        roots = np.asarray(span_roots_device(  # lint: ignore[VL501] one batched 32 B/span root download — metadata, not payload
            _upload_padded(buffer), jnp.asarray(starts),
            jnp.asarray(lengths))).astype(">u4")
        empty_id = blobid.blob_id(b"")
        return [empty_id if empty[i]
                else roots[i].tobytes().hex()  # lint: ignore[VL106] 32 B span-root ids, metadata not payload
                for i in range(len(spans))]
    return device_span_roots(_upload_padded(buffer), spans)


def _open_readahead(path, segment_size: int):
    """Open ``path`` through the native double-buffered readahead
    (native/volio.cpp) when available — disk IO for segment N+1
    overlaps the device hashing of segment N — else plain open()."""
    try:
        from volsync_tpu.io import ReadaheadReader, available

        if available():
            return ReadaheadReader(path, segment_size)
    except Exception as ex:  # noqa: BLE001 — native is optional
        log.debug("native readahead unavailable for %s, using plain "
                  "open(): %s", path, ex)
    return open(path, "rb")


def verify_blob_batch(pairs: list) -> list:
    """Device-batch blob-id verification: ``pairs`` is
    [(expected-id-hex, plaintext bytes)]; returns the ids whose content
    re-derives to something else. One fused dispatch per call (blobs
    pack page-aligned — hash_spans' fast path); decrypt/decompress
    stay with the caller, only the per-byte hashing rides the device.
    Shared by Repository.check's device path and TreeRestore."""
    if not pairs:
        return []
    spans = []
    off = payload = 0
    for _, data in pairs:
        spans.append((off, len(data)))
        payload += len(data)
        off += len(data) + (-len(data) % blobid.LEAF_SIZE)
    # One zeroed bucket-sized staging buffer, one copy per blob into its
    # page-aligned slot (the single sanctioned copy of this path —
    # replaces the old pieces-list + b"".join + np.pad double copy);
    # hash_spans then uploads it with no further host-side pad.
    staging = np.zeros((_buffer_bucket(max(off, 1)),), np.uint8)
    for (start, _), (_, data) in zip(spans, pairs):
        n = len(data)
        if n:
            staging[start: start + n] = np.frombuffer(
                data, np.uint8, count=n)
    record_copy("verify.stage", payload)
    got = hash_spans(staging, spans)
    return [bid for (bid, _), d in zip(pairs, got) if d != bid]


def hash_file_streaming(path, *, segment_size: int = 32 * 1024 * 1024) -> str:
    """Blob id of an arbitrarily large file with bounded memory: leaf
    digests are computed on device one ~32 MiB segment at a time and the
    root combines host-side (repo/blobid.py).

    Every leaf of a whole-file stream is a PAGE of its segment
    (segment_size % 4 KiB == 0), so the device hashes pages contiguously
    (ops/segment._page_digests_flat — no gather) and only the file's
    final partial leaf is hashed host-side from bytes already in hand.
    One digest fetch per segment, 32 bytes per 4 KiB; reads go through
    the native readahead so disk IO hides behind device time."""
    import hashlib

    from volsync_tpu.ops.segment import page_digests

    assert segment_size % blobid.LEAF_SIZE == 0
    leaves: list[bytes] = []
    total = 0
    # One reused pooled segment buffer for the whole file: readinto()
    # fills it in place (zero host copies for plain file readers);
    # read()-only sources pay the single sanctioned ingest copy into it.
    buf = bufpool.GLOBAL.acquire(segment_size)
    try:
        view = memoryview(buf)
        arr = np.frombuffer(buf, np.uint8)
        with _open_readahead(path, segment_size) as f:
            readinto = getattr(f, "readinto", None)
            while True:
                n = 0
                while n < segment_size:
                    if readinto is not None:
                        got = readinto(view[n:segment_size])
                        got = 0 if got is None else int(got)
                        if got == 0:
                            break
                    else:
                        piece = f.read(segment_size - n)
                        got = len(piece)
                        if got == 0:
                            break
                        view[n: n + got] = piece
                        record_copy("chunker.ingest", got)
                    n += got
                if n == 0:
                    break
                total += n
                full = n // blobid.LEAF_SIZE
                if full:
                    dev = _upload_padded(arr[: full * blobid.LEAF_SIZE])
                    dig = page_digests(dev)[:full].astype(">u4")
                    leaves.extend(
                        dig[k].tobytes()  # lint: ignore[VL106] 32 B leaf digest rows, metadata not payload
                        for k in range(full))
                if n % blobid.LEAF_SIZE:
                    leaves.append(hashlib.sha256(
                        view[full * blobid.LEAF_SIZE: n]).digest())
                if n < segment_size:
                    break  # EOF landed mid-segment
    finally:
        view.release()
        del arr
        bufpool.GLOBAL.release(buf)
    if total == 0:
        return blobid.blob_id(b"")
    return blobid.root_from_leaves(total, leaves)


def _resolve_reader(reader):
    """(read_fn, readinto_fn) for a stream source. ``reader`` is the
    classic ``reader(n) -> bytes`` callable; when it is a bound
    ``read`` method of an object that also exposes ``readinto`` (plain
    files, io.BytesIO, io.ReadaheadReader), segment fills go straight
    into the pooled buffer — zero host copies on ingest."""
    readinto = getattr(reader, "readinto", None)
    if readinto is None:
        readinto = getattr(getattr(reader, "__self__", None),
                           "readinto", None)
    read = getattr(reader, "read", None) or reader
    return read, readinto


class _SegmentFill:
    """Fills pooled segment buffers for stream_chunk_batches.

    Buffer layout: ``[0, head)`` is reserved for the previous segment's
    carried tail (head == max_size bounds it — a non-eof device walk
    always leaves less than max_size unconsumed); new stream bytes fill
    ``[head, head + target)`` where target == segment_size + max_size,
    the same per-dispatch window the pre-pool implementation
    accumulated. ``readinto()`` sources fill the buffer in place; plain
    ``read()`` sources pay one sanctioned ``chunker.ingest`` copy. The
    extra page-bucket slack past the fill window lets the consumer hand
    the device a pre-padded view with no np.pad copy."""

    def __init__(self, reader: Callable[[int], bytes], piece_size: int,
                 max_size: int):
        self._read, self._readinto = _resolve_reader(reader)
        self._piece = piece_size
        self.head = max_size
        self.target = piece_size + max_size
        # head + fill window + bucket slack for the device pad lane
        # (bucket(tail + fill) never reaches past this).
        self.capacity = max_size + _buffer_bucket(self.target + max_size)
        self._eof = False
        self._carry: Optional[memoryview] = None  # over-returned piece

    def next_segment(self) -> tuple[bytearray, int, bool]:
        """-> (pooled buffer, fill end, eof). Data lives in
        ``[head, fill)``; at most one more segment follows eof=True."""
        buf = bufpool.GLOBAL.acquire(self.capacity)
        try:
            view = memoryview(buf)
            fill = self.head
            limit = self.head + self.target
            while not self._eof and fill < limit:
                if self._carry is not None:
                    take = min(len(self._carry), limit - fill)
                    view[fill: fill + take] = self._carry[:take]
                    record_copy("chunker.ingest", take)
                    self._carry = (self._carry[take:]
                                   if take < len(self._carry) else None)
                    fill += take
                    continue
                want = min(self._piece, limit - fill)
                with span("engine.read"):
                    if self._readinto is not None:
                        got = self._readinto(view[fill: fill + want])
                        got = 0 if got is None else int(got)
                        if got == 0:
                            self._eof = True
                        fill += got
                    else:
                        piece = self._read(want)
                        if not piece:
                            self._eof = True
                        else:
                            p = memoryview(piece)
                            take = min(len(p), limit - fill)
                            view[fill: fill + take] = p[:take]
                            record_copy("chunker.ingest", take)
                            if take < len(p):  # reader over-returned
                                self._carry = p[take:]
                            fill += take
        except BaseException:
            # ownership only transfers to the caller on success — give
            # the slot back to the pool before propagating
            view.release()
            bufpool.GLOBAL.release(buf)
            raise
        view.release()
        return buf, fill, self._eof


class _SegmentReadahead:
    """Read-ahead stage of the backup pipeline: a producer thread runs
    _SegmentFill ahead of the consumer so the next segment's host read
    overlaps the current segment's device round-trip. Complements the
    native double-buffer (_open_readahead), which only covers file
    readers — this wraps ANY reader source. Fill exceptions propagate
    to the consumer; ``close()`` (or consumer GC) stops the thread."""

    def __init__(self, fill: _SegmentFill, depth: int):
        from volsync_tpu.metrics import GLOBAL as _METRICS

        self.head = fill.head
        self._fill = fill
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._gauge = _METRICS.pipeline_depth.labels(stage="read")
        # the consumer's trace context, handed across the thread seam
        # so engine.read spans attribute to the request being served
        from volsync_tpu.obs import current_context
        self._trace_ctx = current_context()
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="vtpk-readahead")
        self._thread.start()

    def _produce(self):
        from volsync_tpu.obs import use_context
        with use_context(self._trace_ctx):
            self._produce_loop()

    def _produce_loop(self):
        try:
            while not self._stop.is_set():
                item = self._fill.next_segment()
                done = item[2]
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue  # poll stop: a closed consumer must
                        # not leave this thread blocked forever
                self._gauge.set(self._q.qsize())
                if done:
                    return
        except Exception as ex:  # noqa: BLE001 — re-raised by consumer
            while not self._stop.is_set():
                try:
                    self._q.put(ex, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def next_segment(self) -> tuple[bytearray, int, bool]:
        item = self._q.get()
        self._gauge.set(self._q.qsize())
        if isinstance(item, Exception):
            raise item
        return item

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        # Hand buffers the consumer never saw back to the pool.
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if not isinstance(item, Exception):
                bufpool.GLOBAL.release(item[0])


def stream_chunk_batches(reader: Callable[[int], bytes],
                         params: GearParams,
                         segment_size: int = 32 * 1024 * 1024,
                         hasher: Optional[DeviceChunkHasher] = None,
                         readahead: Optional[int] = None,
                         ) -> Iterator[list[tuple[memoryview, str]]]:
    """Chunk an arbitrary-length stream -> per-segment batches of
    (chunk payload, sha256 hex).

    Each yielded list is one device segment's full cut list — the
    natural unit for the repository's batched dedup query
    (``Repository.add_blobs``): the device already hashes a whole
    segment per dispatch, so its chunks arrive together anyway.
    Flattening the batches reproduces ``stream_chunks`` exactly (same
    chunks, same digests, same order).

    Chunk payloads are zero-copy ``memoryview`` slices of pooled
    segment buffers (engine/bufpool.py) that the stream fills with
    ``readinto()`` when the reader supports it; the only per-segment
    host copy left on this path is the sub-max_size tail carried
    between segments (ledger site ``chunker.tail_carry``). Consumers
    may hold the views as long as they like — a pooled buffer is never
    recycled while any view of it is alive.

    ``reader(n)`` returns up to n bytes, b"" at EOF (a bound file
    ``read`` additionally unlocks the readinto fill). Segments are
    chunked on device; the unterminated tail of each segment is carried
    into the next so boundaries match one-shot chunking.

    On the fused path (align == 4096, the repo default) each segment is
    one device dispatch and one small result fetch; the buffer can only
    advance once that fetch lands, so segments of one stream serialize
    on a single round-trip each (sub-ms on a TPU VM). Aggregate
    throughput scales across concurrent streams — one per
    ReplicationSource, mirroring the reference's
    MaxConcurrentReconciles=100 concurrency model — and with the
    segment size. 64 <= align < 4096 keeps the split-phase pipeline
    (synchronous boundary walk, leaf digests in flight across loop
    iterations); align=1 the legacy synchronous path.

    ``readahead`` (default: env VOLSYNC_TPU_READAHEAD, 0 under
    VOLSYNC_TPU_PIPELINE=0) runs the segment fill that many buffers
    ahead on a producer thread so host reads overlap device work — the
    read-ahead stage of the backup pipeline. Chunk boundaries and
    digests are identical either way.
    """
    hasher = hasher or DeviceChunkHasher(params)
    if readahead is None:
        readahead = envflags.readahead_segments()
    src = _SegmentFill(reader, segment_size, params.max_size)
    ra: Optional[_SegmentReadahead] = None
    if readahead > 0:
        ra = src = _SegmentReadahead(src, readahead)
    head = src.head
    begin = getattr(hasher, "begin", None)
    prepadded = begin is not None and getattr(
        hasher, "accepts_prepadded", False)

    def _dispatch(buf, start, fill, eof):
        length = fill - start
        with span("engine.device"):
            if length == 0:
                return PendingSegment([], None, None)
            arr = np.frombuffer(buf, np.uint8)
            if prepadded:
                # Hand the device a view already padded to its bucket:
                # zero the pad lane in place (a memset over recycled
                # buffer slack, not a payload copy) — no np.pad.
                plen = _buffer_bucket(length)
                arr[fill: start + plen] = 0
                return begin(arr[start: start + plen], eof=eof,
                             valid_len=length)
            if begin is not None:
                return begin(arr[start:fill], eof=eof)
            # Engines without split-phase support (e.g. the mesh
            # hasher) still work, just without the overlap.
            return PendingSegment(
                hasher.process(arr[start:fill], eof=eof), None, None)

    def _finish(prev):
        buf, start, token = prev
        with span("engine.device"):
            cuts = list(token.finish())
        if cuts:
            base = memoryview(buf).toreadonly()
            return [(base[start + s: start + s + length], digest)
                    for s, length, digest in cuts]
        return None

    try:
        tail: Optional[memoryview] = None  # lives in prev's buffer
        prev = None  # (buf, start, token)
        while True:
            buf, fill, eof = src.next_segment()
            t = len(tail) if tail is not None else 0
            start = head - t
            if t:
                # The one inter-segment copy: the unterminated tail
                # (< max_size) moves into the next buffer's reserve.
                memoryview(buf)[start:head] = tail
                record_copy("chunker.tail_carry", t)
            tail = None
            token = _dispatch(buf, start, fill, eof)
            consumed = token.end
            tail = memoryview(buf)[start + consumed: fill]
            if len(tail) == 0:
                tail = None
            if prev is not None:
                batch = _finish(prev)
                if batch:
                    yield batch
                bufpool.GLOBAL.release(prev[0])
            prev = (buf, start, token)
            if eof:
                batch = _finish(prev)
                if batch:
                    yield batch
                bufpool.GLOBAL.release(buf)
                return
            # A non-eof pass over more than max_size bytes always emits
            # at least one chunk (max_size forces a cut), so progress is
            # guaranteed; assert to fail loudly rather than loop forever.
            assert consumed > 0, "chunker made no progress"
    finally:
        if ra is not None:
            ra.close()


def stream_chunks(reader: Callable[[int], bytes], params: GearParams,
                  segment_size: int = 32 * 1024 * 1024,
                  hasher: Optional[DeviceChunkHasher] = None,
                  readahead: Optional[int] = None,
                  ) -> Iterator[tuple[bytes, str]]:
    """Flattened ``stream_chunk_batches``: chunk a stream ->
    (chunk bytes, sha256 hex), one tuple per chunk. Byte-identical to
    the batched form; callers that can act on a whole segment at once
    (the backup engine's dedup query) should take the batches."""
    for batch in stream_chunk_batches(reader, params,
                                      segment_size=segment_size,
                                      hasher=hasher, readahead=readahead):
        yield from batch
