"""Streaming CDC chunk+hash pipeline: the mover's device hot path.

Replaces the chunk/hash core of the engine the reference wraps
(mover-restic/entry.sh:63 `restic backup` — Rabin CDC + per-blob SHA-256
on CPU): a segment of the input stream is uploaded to the device once,
gear-hash CDC candidates and per-chunk SHA-256 digests both run on that
resident buffer, and only (boundaries, digests) come back to the host.

Streaming determinism: each segment handed to the CDC starts exactly at a
chunk boundary, and no cut is eligible before min_size-1 >= 31 positions
in, so every eligible position sees its full 32-byte gear window within
the segment — boundaries are bit-identical to one-shot chunking of the
whole stream (see ops/gearcdc.py).
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional

import numpy as np

from volsync_tpu.repo import blobid

from volsync_tpu.ops.gearcdc import GearParams, cdc_candidates, select_boundaries
from volsync_tpu.ops.sha256 import sha256_chunks_device


def params_from_config(cfg: dict) -> GearParams:
    return GearParams(min_size=cfg["min_size"], avg_size=cfg["avg_size"],
                      max_size=cfg["max_size"], seed=cfg["seed"])


def _pow2ceil(n: int, lo: int = 1) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _buffer_bucket(length: int) -> int:
    """Pad target for input buffers. Shapes are static under jit, so an
    unbounded variety of buffer lengths (every file tail is unique) would
    mean a fresh multi-second XLA compile each — pad into a small fixed
    set instead: pow2 up to 8 MiB, then multiples of 8 MiB."""
    if length <= 8 * 1024 * 1024:
        return _pow2ceil(length, 64 * 1024)
    m = 8 * 1024 * 1024
    return (length + m - 1) // m * m


class DeviceChunkHasher:
    """chunk+hash a byte buffer with one host->device upload.

    All device call shapes are drawn from small bounded bucket sets
    (padded buffer sizes, fixed candidate capacity, size-classed chunk
    batches with pow2 lane counts) so the jit cache converges after a few
    segments regardless of workload shape.
    """

    def __init__(self, params: GearParams):
        self.params = params

    def process(self, buffer, *, eof: bool = True) -> list[tuple[int, int, str]]:
        """-> [(start, length, sha256-hex)] covering ``buffer`` (the tail
        is withheld when not ``eof`` — the caller re-feeds it)."""
        import jax.numpy as jnp

        if isinstance(buffer, (bytes, bytearray, memoryview)):
            buffer = np.frombuffer(buffer, dtype=np.uint8)
        length = int(buffer.shape[0])
        if length == 0:
            return []
        p = self.params
        if length <= p.min_size:
            if not eof:
                return []
            return [(0, length, blobid.blob_id(buffer.tobytes()))]

        padded = _buffer_bucket(length)
        if padded != length:
            buffer = np.pad(buffer, (0, padded - length))
        dev = jnp.asarray(buffer)
        # Candidate capacity: one boundary candidate per 64 bytes covers
        # any mask down to 2^-6 density (avg_size >= 256B with the
        # default normalization), so ordinary data never retries; only
        # candidate-dense adversarial data takes the doubling path below.
        cap = padded // 64
        while True:
            # valid_len masks the zero-padded tail on device: padding can
            # neither add candidates nor inflate the overflow counts.
            idx_s, count_s, idx_l, count_l = cdc_candidates(
                dev, seed=p.seed, mask_s=p.mask_s, mask_l=p.mask_l,
                max_candidates=cap, valid_len=length,
            )
            cs, cl = int(count_s), int(count_l)
            if cs <= cap and cl <= cap:
                break
            # Candidate-dense (e.g. adversarial) data overflowed the
            # capacity: silently truncating would make streaming
            # boundaries diverge from one-shot chunking. Retry with a
            # doubled cap (rare; costs one recompile when it happens).
            cap = _pow2ceil(max(cs, cl), cap * 2)
        idx_s = np.asarray(idx_s)[:cs]
        idx_l = np.asarray(idx_l)[:cl]
        chunks = select_boundaries(idx_s, idx_l, length, p, eof=eof)
        if not chunks:
            return []
        hexes = self._hash_chunks(dev, chunks)
        return [(int(s), int(l), h) for (s, l), h in zip(chunks, hexes)]

    def _hash_chunks(self, dev, chunks: list[tuple[int, int]]) -> list[str]:
        return device_span_roots(dev, chunks)


def device_leaf_digests(dev, leaf_starts: list[int],
                        leaf_lengths: list[int]) -> list[bytes]:
    """SHA-256 digests of arbitrary <=4 KiB slices of a device buffer,
    every slice an independent lane (wide batch, 65-step scan, a single
    compiled shape per lane-count bucket)."""
    import jax.numpy as jnp

    lanes = _pow2ceil(len(leaf_starts), 128)
    starts = np.zeros((lanes,), np.int32)
    lengths = np.zeros((lanes,), np.int32)
    starts[: len(leaf_starts)] = leaf_starts
    lengths[: len(leaf_lengths)] = leaf_lengths
    digests = np.asarray(sha256_chunks_device(
        dev, jnp.asarray(starts), jnp.asarray(lengths),
        max_len=blobid.LEAF_SIZE,
    )).astype(">u4")
    leaf_bytes = digests.tobytes()  # 32 bytes per lane, row-major
    return [leaf_bytes[32 * k : 32 * (k + 1)]
            for k in range(len(leaf_starts))]


def device_span_roots(dev, chunks: list[tuple[int, int]]) -> list[str]:
    """Merkle blob ids for (start, length) slices of the device buffer
    (repo/blobid.py): every 4 KiB leaf of every chunk hashes as one
    independent lane, then the tiny roots combine host-side."""
    leaf_starts: list[int] = []
    leaf_lengths: list[int] = []
    spans: list[tuple[int, int]] = []  # (first leaf index, count) per chunk
    for start, length in chunks:
        first = len(leaf_starts)
        n = blobid.leaf_count(length)
        for k in range(n):
            off = k * blobid.LEAF_SIZE
            leaf_starts.append(start + off)
            leaf_lengths.append(min(blobid.LEAF_SIZE, length - off))
        spans.append((first, n))
    leaves = device_leaf_digests(dev, leaf_starts, leaf_lengths)
    return [
        blobid.root_from_leaves(length, leaves[first : first + n])
        for (first, n), (_, length) in zip(spans, chunks)
    ]


def _upload_padded(buffer):
    """Host bytes/array -> device array padded to a bucketed length."""
    import jax.numpy as jnp

    if isinstance(buffer, (bytes, bytearray, memoryview)):
        buffer = np.frombuffer(buffer, dtype=np.uint8)
    length = int(buffer.shape[0])
    padded = _buffer_bucket(max(length, 1))
    if padded != length:
        buffer = np.pad(buffer, (0, padded - length))
    return jnp.asarray(buffer)


def hash_spans(buffer, spans: list[tuple[int, int]]) -> list[str]:
    """Device-batched blob ids for (start, length) spans of one buffer.

    The checksum-compare primitive for the rclone-style mover (the
    reference's `rclone sync --checksum`, mover-rclone/active.sh:19):
    many files are packed into one host buffer, uploaded once, and every
    4 KiB leaf of every span hashes as an independent lane.
    """
    if not spans:
        return []
    return device_span_roots(_upload_padded(buffer), spans)


def hash_file_streaming(path, *, segment_size: int = 32 * 1024 * 1024) -> str:
    """Blob id of an arbitrarily large file with bounded memory: leaf
    digests are computed on device one ~32 MiB segment at a time and the
    root combines host-side (repo/blobid.py)."""
    assert segment_size % blobid.LEAF_SIZE == 0
    leaves: list[bytes] = []
    total = 0
    with open(path, "rb") as f:
        while True:
            seg = f.read(segment_size)
            if not seg:
                break
            total += len(seg)
            dev = _upload_padded(seg)
            n = blobid.leaf_count(len(seg))
            starts = [k * blobid.LEAF_SIZE for k in range(n)]
            lengths = [min(blobid.LEAF_SIZE, len(seg) - s) for s in starts]
            leaves.extend(device_leaf_digests(dev, starts, lengths))
    if total == 0:
        return blobid.blob_id(b"")
    return blobid.root_from_leaves(total, leaves)


def stream_chunks(reader: Callable[[int], bytes], params: GearParams,
                  segment_size: int = 32 * 1024 * 1024,
                  hasher: Optional[DeviceChunkHasher] = None,
                  ) -> Iterator[tuple[bytes, str]]:
    """Chunk an arbitrary-length stream -> (chunk bytes, sha256 hex).

    ``reader(n)`` returns up to n bytes, b"" at EOF. Segments are chunked
    on device; the unterminated tail of each segment is carried into the
    next so boundaries match one-shot chunking.
    """
    hasher = hasher or DeviceChunkHasher(params)
    pending = b""
    eof = False
    while True:
        while not eof and len(pending) < segment_size + params.max_size:
            piece = reader(segment_size)
            if not piece:
                eof = True
            else:
                pending += piece
        consumed = 0
        for start, length, digest in hasher.process(
                np.frombuffer(pending, np.uint8), eof=eof):
            yield pending[start : start + length], digest
            consumed = start + length
        pending = pending[consumed:]
        if eof:
            return
        # A non-eof pass over more than max_size bytes always emits at
        # least one chunk (max_size forces a cut), so progress is
        # guaranteed; assert to fail loudly rather than loop forever.
        assert consumed > 0, "chunker made no progress"
