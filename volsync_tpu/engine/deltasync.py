"""rsync-style delta synchronization engine (device-accelerated).

The algorithm of the reference's `rsync -aAhHSxz --delete` hot loop
(mover-rsync/source.sh:54), re-expressed on TPU primitives
(ops/rolling.py, ops/delta.py, ops/md5.py):

  destination:  per-block signature = (weak32, MD5) per block_len block
  source:       rolling weak checksum at EVERY offset in one parallel
                pass -> membership vs the signature's sorted weak set ->
                batched MD5 verification of candidate windows -> greedy
                left-to-right op selection on host (sparse matches only)
  ops stream:   COPY(block_index, n_blocks) | DATA(bytes), applied on the
                destination against its current file

Block size follows rsync's heuristic (~sqrt(file size), bounded), bucket-
rounded so device call shapes stay bounded (see engine/chunker.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterator, Optional

import numpy as np

from volsync_tpu.ops.delta import (
    build_signature,
    match_offsets,
    match_offsets_batch,
    verify_candidates,
    verify_candidates_batch,
)
from volsync_tpu.ops.rolling import weak_checksum_host

MIN_BLOCK = 4096
MAX_BLOCK = 128 * 1024

#: Wire cost of one signature block: weak32 + 16-byte MD5 (to_wire).
SIG_BYTES_PER_BLOCK = 4 + 16
#: Wire cost of a signature's fixed fields (size + block_len ints).
SIG_HEADER_BYTES = 16


def pick_block_len(size: int) -> int:
    """rsync-style block size: ~sqrt(size), pow2-bounded [4 KiB, 128 KiB]."""
    if size <= 0:
        return MIN_BLOCK
    target = int(size ** 0.5)
    b = MIN_BLOCK
    while b < target and b < MAX_BLOCK:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class SigGeometry:
    """The block geometry the engine would pick for a file of ``size``
    bytes, plus the exact signature wire cost that geometry implies.
    This is the pricing seam the protocol planner (engine/protoplan.py)
    uses: DELTA's first round trip ships ``sig_bytes`` for real, so the
    estimate must come from here, not a re-derived approximation."""

    block_len: int
    n_blocks: int      # includes the short tail block, matching to_wire
    sig_bytes: int


def signature_geometry(size: int,
                       block_len: Optional[int] = None) -> SigGeometry:
    """Geometry + signature wire size for a ``size``-byte destination
    file (``block_len`` overrides the heuristic, as build_file_signature
    allows)."""
    block_len = block_len or pick_block_len(size)
    n_blocks = 0 if size <= 0 else -(-size // block_len)
    return SigGeometry(block_len=block_len, n_blocks=n_blocks,
                       sig_bytes=SIG_HEADER_BYTES
                       + n_blocks * SIG_BYTES_PER_BLOCK)


@dataclasses.dataclass
class FileSignature:
    size: int
    block_len: int
    weak: np.ndarray          # [nb] uint32 (includes short tail block)
    strong: list[bytes]       # [nb] 16-byte MD5 digests

    def to_wire(self) -> dict:
        return {"size": self.size, "block_len": self.block_len,
                "weak": self.weak.tobytes(),  # lint: ignore[VL106] signature wire form
                "strong": b"".join(self.strong)}  # lint: ignore[VL106] signature wire form

    @classmethod
    def from_wire(cls, d: dict) -> "FileSignature":
        weak = np.frombuffer(d["weak"], dtype=np.uint32).copy()
        strong = [d["strong"][i : i + 16]
                  for i in range(0, len(d["strong"]), 16)]
        return cls(size=d["size"], block_len=d["block_len"], weak=weak,
                   strong=strong)


def build_file_signature(data: bytes,
                         block_len: Optional[int] = None) -> FileSignature:
    """Destination side: checksum every block (device for the full blocks,
    host for the short tail)."""
    import jax.numpy as jnp

    block_len = block_len or pick_block_len(len(data))
    if len(data) == 0:
        return FileSignature(0, block_len, np.zeros((0,), np.uint32), [])
    arr = np.frombuffer(data, np.uint8)
    n_full = len(data) // block_len
    if n_full == 0:
        weak = np.array([weak_checksum_host(data)], dtype=np.uint32)
        return FileSignature(len(data), block_len, weak,
                             [hashlib.md5(data).digest()])
    dev = jnp.asarray(arr)
    weak_dev, strong_dev = build_signature(dev, block_len=block_len)
    weak = np.asarray(weak_dev)  # includes tail at its true length
    strong = [np.asarray(strong_dev)[i].astype("<u4").tobytes()  # lint: ignore[VL106] 16 B digests
              for i in range(n_full)]
    tail = data[n_full * block_len :]
    if tail:
        strong.append(hashlib.md5(tail).digest())
    else:
        weak = weak[:n_full]
    return FileSignature(len(data), block_len, weak, strong)


# Delta ops: ("copy", first_block, n_blocks) | ("data", bytes)
Op = tuple


def compute_delta(src: bytes, sig: FileSignature) -> list[Op]:
    """Source side: the delta scan. Returns ops that rebuild ``src`` from
    the destination's blocks + literal data."""
    import jax.numpy as jnp

    L = len(src)
    if L == 0:
        return []
    block_len = sig.block_len
    n_full_dst = sig.size // block_len
    # Only full blocks participate in the rolling scan; the destination
    # tail block (if any) can only match at the very end of src.
    full_weak = sig.weak[:n_full_dst]
    if len(full_weak) == 0 or L < block_len:
        return _with_tail_match(src, sig, [("data", src)])

    arr = np.frombuffer(src, np.uint8)
    dev = jnp.asarray(arr)
    sort_idx = np.argsort(full_weak, kind="stable")
    sorted_weak = full_weak[sort_idx]
    cap = max(1024, _pow2ceil(L // block_len * 4))
    while True:
        cand_dev, count = match_offsets(
            dev, jnp.asarray(sorted_weak), window=block_len,
            max_candidates=cap,
        )
        n = int(count)
        if n <= cap:
            cand = np.asarray(cand_dev)[:n]
            break
        cap = _pow2ceil(n)
    if len(cand) == 0:
        return _with_tail_match(src, sig, [("data", src)])

    # Strong verification, batched on device.
    strongs = verify_candidates(dev, cand, block_len=block_len)
    strong_bytes = [strongs[i].astype("<u4").tobytes()  # lint: ignore[VL106] 16 B digests
                    for i in range(len(cand))]
    return _select_ops(src, arr, sig, full_weak, cand, strong_bytes)


def _select_ops(src: bytes, arr: np.ndarray, sig: FileSignature,
                full_weak: np.ndarray, cand, strong_bytes: list) -> list[Op]:
    """Host-side tail of the delta scan, shared verbatim by the serial
    and batched paths (byte-identity between them reduces to the device
    stages producing the same candidate set): map verified candidates
    to destination blocks, then greedy left-to-right op selection over
    the sparse matches."""
    L = len(src)
    block_len = sig.block_len
    # weak -> destination block ids (handle duplicate weak values)
    by_weak: dict[int, list[int]] = {}
    for orig_idx in range(len(full_weak)):
        by_weak.setdefault(int(full_weak[orig_idx]), []).append(orig_idx)
    # offset -> destination block index for verified matches
    verified: dict[int, int] = {}
    weak_at = _weak_at_offsets(arr, cand, block_len)
    for i, off in enumerate(cand):
        w = weak_at[i]
        if w not in by_weak:
            continue
        for dst_block in by_weak[w]:
            if sig.strong[dst_block] == strong_bytes[i]:
                verified[int(off)] = dst_block
                break

    # Greedy left-to-right selection over sparse verified offsets.
    ops: list[Op] = []
    lit_start = 0
    pos = 0
    offsets = sorted(verified)
    oi = 0
    while pos + block_len <= L:
        while oi < len(offsets) and offsets[oi] < pos:
            oi += 1
        if oi < len(offsets) and offsets[oi] == pos:
            if lit_start < pos:
                ops.append(("data", src[lit_start:pos]))
            blk = verified[pos]
            if ops and ops[-1][0] == "copy" and (
                    ops[-1][1] + ops[-1][2] == blk):
                ops[-1] = ("copy", ops[-1][1], ops[-1][2] + 1)
            else:
                ops.append(("copy", blk, 1))
            pos += block_len
            lit_start = pos
        else:
            # No verified match at pos: jump straight to the next verified
            # offset instead of advancing byte-by-byte — the unmatched
            # region is already covered by lit_start, and a per-byte
            # Python loop would cost O(file bytes) interpreter steps.
            if oi < len(offsets) and offsets[oi] > pos:
                pos = offsets[oi]
            else:
                break
    if lit_start < L:
        ops.append(("data", src[lit_start:]))
    return _with_tail_match(src, sig, ops)


def _with_tail_match(src: bytes, sig: FileSignature,
                     ops: list[Op]) -> list[Op]:
    """If src's final bytes equal the destination's short tail block,
    replace the trailing literal with a copy of the tail block."""
    n_full = sig.size // sig.block_len
    tail_len = sig.size - n_full * sig.block_len
    if tail_len == 0 or n_full >= len(sig.strong):
        return ops
    if not ops or ops[-1][0] != "data" or len(ops[-1][1]) < tail_len:
        return ops
    lit = ops[-1][1]
    if hashlib.md5(lit[-tail_len:]).digest() == sig.strong[n_full]:
        remainder = lit[:-tail_len]
        ops = ops[:-1]
        if remainder:
            ops.append(("data", remainder))
        ops.append(("copy", n_full, 1))
    return ops


def delta_scan_batch(items) -> list[list[Op]]:
    """Multi-file delta scan: the device stages of ``compute_delta``
    (rolling weak scan -> signature membership -> batched MD5 verify)
    run once per GROUP of files instead of once per file.

    ``items`` is a sequence of ``(src_bytes, FileSignature)`` pairs;
    returns one op stream per item, byte-identical to calling
    ``compute_delta`` on each (the golden oracle —
    tests/test_delta_batch.py): the host-side greedy selection is the
    shared ``_select_ops``, and the batched kernels produce the same
    per-file candidate sets because padding rows to a common bucketed
    length only adds scan offsets that the per-row valid-length mask
    discards.

    Files are grouped by block length (pick_block_len emits few distinct
    pow2 values) and each group is padded to a bucket-rounded row length
    (engine/chunker._buffer_bucket), so jit cache entries stay bounded
    exactly like the CDC path's segment buffers. Host-only short
    circuits (empty files, sub-block files, signatures with no full
    block) never reach the device — same as the serial engine.
    """
    import jax.numpy as jnp

    from volsync_tpu.engine.chunker import _buffer_bucket

    results: list = [None] * len(items)
    groups: dict[int, list[int]] = {}
    for i, (src, sig) in enumerate(items):
        if len(src) == 0:
            results[i] = []
            continue
        n_full_dst = sig.size // sig.block_len
        if n_full_dst == 0 or len(src) < sig.block_len:
            results[i] = _with_tail_match(src, sig, [("data", src)])
            continue
        groups.setdefault(sig.block_len, []).append(i)

    for block_len, idxs in groups.items():
        arrs = [np.frombuffer(items[i][0], np.uint8) for i in idxs]
        lens = [len(a) for a in arrs]
        L = _buffer_bucket(max(lens))
        n = len(idxs)
        data = np.zeros((n, L), np.uint8)
        for r, a in enumerate(arrs):
            data[r, : len(a)] = a
        full_weaks = [items[i][1].weak[: items[i][1].size // block_len]
                      for i in idxs]
        nb = np.array([len(w) for w in full_weaks], np.int32)
        nb_cap = _pow2ceil(int(nb.max()))
        sorted_weak = np.full((n, nb_cap), 0xFFFFFFFF, np.uint32)
        for r, w in enumerate(full_weaks):
            sorted_weak[r, : len(w)] = np.sort(w, kind="stable")
        nscan = np.array([ln - block_len + 1 for ln in lens], np.int32)
        width = L - block_len + 1
        # The loop variable here is a block_len BUCKET, not a file: each
        # iteration uploads and matches one whole padded [n, L] batch —
        # this IS the batched path (one dispatch per distinct block_len).
        dev = jnp.asarray(data)  # lint: ignore[VL502] per-bucket batch upload
        sw_dev = jnp.asarray(sorted_weak)  # lint: ignore[VL502] per-bucket batch upload
        nb_dev = jnp.asarray(nb)  # lint: ignore[VL502] per-bucket batch upload
        ns_dev = jnp.asarray(nscan)  # lint: ignore[VL502] per-bucket batch upload
        cap = max(1024, _pow2ceil(sum(ln // block_len for ln in lens) * 4))
        while True:
            cand_dev, count = match_offsets_batch(  # lint: ignore[VL502] one dispatch per bucket batch
                dev, sw_dev, nb_dev, ns_dev, window=block_len,
                max_candidates=cap)
            total = int(count)
            if total <= cap:
                flat = np.asarray(cand_dev)[:total]
                break
            cap = _pow2ceil(total)
        rows = flat // width
        offs = flat % width
        states = verify_candidates_batch(dev, rows, offs,
                                         block_len=block_len)
        strong_all = [states[k].astype("<u4").tobytes()  # lint: ignore[VL106] 16 B digests
                      for k in range(len(flat))]
        for r, i in enumerate(idxs):
            picks = np.nonzero(rows == r)[0]
            src, sig = items[i]
            if len(picks) == 0:
                results[i] = _with_tail_match(src, sig, [("data", src)])
                continue
            results[i] = _select_ops(
                src, arrs[r], sig, full_weaks[r], offs[picks],
                [strong_all[k] for k in picks])
    return results


def apply_delta(ops: list[Op], dest: bytes, block_len: int) -> bytes:
    """Destination side: rebuild the file from its own blocks + literals."""
    out = bytearray()
    for op in ops:
        if op[0] == "data":
            out += op[1]
        else:
            _, first, count = op
            start = first * block_len
            out += dest[start : start + count * block_len]
    return bytes(out)  # lint: ignore[VL106] rebuilt file is the return contract


def delta_stats(ops: list[Op], block_len: int) -> dict:
    copied = sum(op[2] * block_len for op in ops if op[0] == "copy")
    literal = sum(len(op[1]) for op in ops if op[0] == "data")
    return {"copied_bytes": copied, "literal_bytes": literal}


def _pow2ceil(n: int) -> int:
    v = 1
    while v < n:
        v *= 2
    return v


def _weak_at_offsets(arr: np.ndarray, offsets, block_len: int) -> np.ndarray:
    """Weak checksums at given offsets via numpy prefix sums (vectorized;
    identical arithmetic to ops/rolling.py)."""
    if len(offsets) == 0:
        return np.zeros((0,), np.uint32)
    x = arr.astype(np.uint32)
    j = np.arange(len(arr), dtype=np.uint32)
    with np.errstate(over="ignore"):
        S = np.concatenate([[0], np.cumsum(x, dtype=np.uint32)])
        T = np.concatenate([[0], np.cumsum(j * x, dtype=np.uint32)])
        off = np.asarray(offsets, dtype=np.int64)
        dS = S[off + block_len] - S[off]
        dT = T[off + block_len] - T[off]
        a = dS & np.uint32(0xFFFF)
        b = ((off.astype(np.uint32) + np.uint32(block_len)) * dS - dT) & np.uint32(0xFFFF)
    return (a | (b << np.uint32(16))).astype(np.uint32)
