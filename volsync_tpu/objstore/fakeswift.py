"""In-process Swift + Keystone server — the "Swift All In One" analogue.

Serves the object API subset the movers use (PUT, conditional PUT,
GET/Range-GET, HEAD, DELETE, container LIST with marker pagination)
plus BOTH auth families the client speaks: Keystone v3 password auth
(``POST /v3/auth/tokens`` — credentials verified against the
configured user, token minted per auth, catalog pointing back at this
server) and legacy v1 auth (``GET /auth/v1.0`` with
X-Auth-User/X-Auth-Key). Every storage request's ``X-Auth-Token`` is
checked against the minted-token set, so a client auth bug fails
loudly in tests instead of surfacing only against real Swift — the
same design as fakeazure.FakeAzureServer / fakes3.FakeS3Server.

``revoke_tokens()`` invalidates everything outstanding to exercise the
client's mid-run 401 re-auth path (token expiry).
"""

from __future__ import annotations

import http.server
import json
import secrets
import threading
from typing import Optional
from urllib.parse import parse_qsl, unquote, urlsplit

from volsync_tpu.analysis import lockcheck

_ACCOUNT = "AUTH_test"


class FakeSwiftServer:
    def __init__(self, *, username: str = "testuser",
                 password: str = "testpass", project: str = "testproj",
                 region: str = "RegionOne", host: str = "127.0.0.1",
                 port: int = 0, max_results: int = 500):
        self.username = username
        self.password = password
        self.project = project
        self.region = region
        self.max_results = max_results
        self._objs: dict[tuple[str, str], bytes] = {}  # (container, name)
        self._tokens: set = set()
        self._lock = lockcheck.make_lock("objstore.fakeswift")
        self.auth_count = 0  # minted tokens (v1 + v3) — re-auth proof
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes = b"",
                       headers: Optional[dict] = None):
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or 0)
                return self.rfile.read(n) if n else b""

            def _mint(self) -> str:
                token = secrets.token_hex(16)
                with outer._lock:
                    outer._tokens.add(token)
                    outer.auth_count += 1
                return token

            def _authed(self) -> bool:
                token = self.headers.get("X-Auth-Token", "")
                with outer._lock:
                    return token in outer._tokens

            # -- auth endpoints -------------------------------------------

            def _keystone(self, body: bytes):
                try:
                    req = json.loads(body)
                    pw = req["auth"]["identity"]["password"]["user"]
                    scope = req["auth"]["scope"]["project"]
                except (ValueError, KeyError, TypeError):
                    return self._reply(400, b"malformed auth request")
                if (pw.get("name") != outer.username
                        or pw.get("password") != outer.password
                        or scope.get("name") != outer.project):
                    return self._reply(401, b"invalid credentials")
                token = self._mint()
                catalog = [{
                    "type": "object-store",
                    "endpoints": [
                        # A foreign-region endpoint FIRST: a client that
                        # ignores OS_REGION_NAME dials a dead port and
                        # fails the test.
                        {"interface": "public", "region": "OtherRegion",
                         "url": "http://127.0.0.1:1/v1/AUTH_other"},
                        {"interface": "admin", "region": outer.region,
                         "url": outer.endpoint + "/v1/ADMIN_wrong"},
                        {"interface": "public", "region": outer.region,
                         "url": outer.endpoint + f"/v1/{_ACCOUNT}"},
                    ],
                }]
                self._reply(201, json.dumps(
                    {"token": {"catalog": catalog}}).encode(),
                    {"X-Subject-Token": token,
                     "Content-Type": "application/json"})

            def _v1_auth(self):
                if (self.headers.get("X-Auth-User") != outer.username
                        or self.headers.get("X-Auth-Key")
                        != outer.password):
                    return self._reply(401, b"invalid v1 credentials")
                token = self._mint()
                self._reply(200, b"", {
                    "X-Auth-Token": token,
                    "X-Storage-Url": outer.endpoint + f"/v1/{_ACCOUNT}"})

            # -- routing --------------------------------------------------

            def _route(self):
                u = urlsplit(self.path)
                path = unquote(u.path).lstrip("/")
                query = dict(parse_qsl(u.query, keep_blank_values=True))
                parts = path.split("/", 3)  # v1 / account / container / obj
                if len(parts) < 3 or parts[0] != "v1" \
                        or parts[1] != _ACCOUNT:
                    return None
                container = parts[2]
                name = parts[3] if len(parts) > 3 else ""
                return container, name, query

            def do_POST(self):  # noqa: N802
                body = self._read_body()
                if urlsplit(self.path).path.rstrip("/").endswith(
                        "/auth/tokens"):
                    return self._keystone(body)
                self._reply(404)

            def do_PUT(self):  # noqa: N802
                body = self._read_body()
                if not self._authed():
                    return self._reply(401, b"bad or expired token")
                routed = self._route()
                if routed is None:
                    return self._reply(404)
                container, name, _ = routed
                if not name:
                    return self._reply(201)  # create container
                with outer._lock:
                    if (self.headers.get("If-None-Match") == "*"
                            and (container, name) in outer._objs):
                        return self._reply(412, b"object exists")
                    outer._objs[(container, name)] = body
                self._reply(201)

            def do_GET(self):  # noqa: N802
                if urlsplit(self.path).path.rstrip("/").endswith(
                        "/auth/v1.0"):
                    return self._v1_auth()
                if not self._authed():
                    return self._reply(401, b"bad or expired token")
                routed = self._route()
                if routed is None:
                    return self._reply(404)
                container, name, query = routed
                if not name:
                    return self._list(container, query)
                with outer._lock:
                    obj = outer._objs.get((container, name))
                if obj is None:
                    return self._reply(404, b"not found")
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    lo, _, hi = rng[len("bytes="):].partition("-")
                    lo = int(lo)
                    hi = min(int(hi), len(obj) - 1) if hi else len(obj) - 1
                    part = obj[lo: hi + 1]
                    return self._reply(
                        206, part, {"Content-Range":
                                    f"bytes {lo}-{hi}/{len(obj)}"})
                self._reply(200, obj)

            def do_HEAD(self):  # noqa: N802
                if not self._authed():
                    return self._reply(401)
                routed = self._route()
                if routed is None:
                    return self._reply(404)
                container, name, _ = routed
                with outer._lock:
                    obj = outer._objs.get((container, name))
                if obj is None:
                    return self._reply(404)
                self._reply(200, obj)  # _reply suppresses HEAD bodies

            def do_DELETE(self):  # noqa: N802
                if not self._authed():
                    return self._reply(401)
                routed = self._route()
                if routed is None:
                    return self._reply(404)
                container, name, _ = routed
                with outer._lock:
                    existed = outer._objs.pop((container, name),
                                              None) is not None
                self._reply(204 if existed else 404)

            def _list(self, container: str, query: dict):
                prefix = query.get("prefix", "")
                marker = query.get("marker", "")
                with outer._lock:
                    names = sorted(
                        n for c, n in outer._objs
                        if c == container and n.startswith(prefix)
                        and n > marker)
                page = names[: outer.max_results]
                if not page:
                    return self._reply(204)
                body = ("\n".join(page) + "\n").encode()
                self._reply(200, body, {"Content-Type": "text/plain"})

            def log_message(self, *args):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       Handler)
        self.endpoint = (f"http://{self._server.server_address[0]}:"
                         f"{self._server.server_address[1]}")

    def revoke_tokens(self):
        """Simulate token expiry: every outstanding token now 401s."""
        with self._lock:
            self._tokens.clear()

    def start(self) -> "FakeSwiftServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="fake-swift",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if getattr(self, "_thread", None) is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
