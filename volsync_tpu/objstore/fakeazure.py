"""In-process Azure Blob server — the Azurite analogue.

Serves the BlockBlob subset the movers use (PUT, conditional PUT,
GET/Range-GET, HEAD, DELETE, container LIST with marker pagination),
storing blobs in memory and **verifying every request's SharedKey
signature** with the same string-to-sign builder the client uses
(objstore/azure.py) — client-side signing bugs fail loudly in tests
instead of surfacing only against real Azure, the same design as
fakes3.FakeS3Server for the MinIO role (hack/run-minio.sh analogue).
"""

from __future__ import annotations

import hmac
import http.server
import threading
from typing import Optional
from urllib.parse import parse_qsl, unquote, urlsplit
from xml.sax.saxutils import escape

from volsync_tpu.analysis import lockcheck
from volsync_tpu.objstore.azure import sign, string_to_sign


class FakeAzureServer:
    def __init__(self, *, account: str = "testaccount",
                 key_b64: str = "dGVzdC1henVyZS1rZXk=",  # "test-azure-key"
                 host: str = "127.0.0.1", port: int = 0,
                 max_results: int = 500):
        self.account = account
        self.key_b64 = key_b64
        self.max_results = max_results
        self._blobs: dict[tuple[str, str], bytes] = {}  # (container, name)
        self._lock = lockcheck.make_lock("objstore.fakeazure")
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes = b"",
                       headers: Optional[dict] = None):
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _verify(self, body: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                want_prefix = f"SharedKey {outer.account}:"
                if not auth.startswith(want_prefix):
                    return False
                u = urlsplit(self.path)
                query = dict(parse_qsl(u.query, keep_blank_values=True))
                headers = {k: v for k, v in self.headers.items()}
                sts = string_to_sign(self.command, outer.account,
                                     unquote(u.path), query, headers,
                                     len(body))
                want = sign(outer.key_b64, sts)
                return hmac.compare_digest(
                    want, auth[len(want_prefix):])

            def _route(self):
                u = urlsplit(self.path)
                parts = unquote(u.path).lstrip("/").split("/", 1)
                container = parts[0]
                name = parts[1] if len(parts) > 1 else ""
                query = dict(parse_qsl(u.query, keep_blank_values=True))
                return container, name, query

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or 0)
                return self.rfile.read(n) if n else b""

            def do_PUT(self):  # noqa: N802
                body = self._read_body()
                if not self._verify(body):
                    return self._reply(403, b"AuthenticationFailed")
                container, name, _ = self._route()
                if not name:
                    return self._reply(201)  # create container
                with outer._lock:
                    if (self.headers.get("If-None-Match") == "*"
                            and (container, name) in outer._blobs):
                        return self._reply(409, b"BlobAlreadyExists")
                    outer._blobs[(container, name)] = body
                self._reply(201)

            def do_GET(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403, b"AuthenticationFailed")
                container, name, query = self._route()
                if query.get("comp") == "list":
                    return self._list(container, query)
                with outer._lock:
                    blob = outer._blobs.get((container, name))
                if blob is None:
                    return self._reply(404, b"BlobNotFound")
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    lo, _, hi = rng[len("bytes="):].partition("-")
                    lo = int(lo)
                    hi = min(int(hi), len(blob) - 1) if hi else len(blob) - 1
                    part = blob[lo: hi + 1]
                    return self._reply(
                        206, part, {"Content-Range":
                                    f"bytes {lo}-{hi}/{len(blob)}"})
                self._reply(200, blob)

            def do_HEAD(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403)
                container, name, _ = self._route()
                with outer._lock:
                    blob = outer._blobs.get((container, name))
                if blob is None:
                    return self._reply(404)
                self._reply(200, blob)  # _reply suppresses HEAD bodies

            def do_DELETE(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403, b"AuthenticationFailed")
                container, name, _ = self._route()
                with outer._lock:
                    existed = outer._blobs.pop((container, name),
                                               None) is not None
                self._reply(202 if existed else 404)

            def _list(self, container: str, query: dict):
                prefix = query.get("prefix", "")
                marker = query.get("marker", "")
                with outer._lock:
                    names = sorted(
                        n for c, n in outer._blobs
                        if c == container and n.startswith(prefix)
                        and n > marker)
                page = names[: outer.max_results]
                next_marker = (page[-1]
                               if len(names) > outer.max_results else "")
                blobs = "".join(
                    f"<Blob><Name>{escape(n)}</Name></Blob>" for n in page)
                body = (
                    "<?xml version='1.0' encoding='utf-8'?>"
                    f"<EnumerationResults><Blobs>{blobs}</Blobs>"
                    f"<NextMarker>{escape(next_marker)}</NextMarker>"
                    "</EnumerationResults>").encode()
                self._reply(200, body,
                            {"Content-Type": "application/xml"})

            def log_message(self, *args):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       Handler)
        self.endpoint = (f"http://{self._server.server_address[0]}:"
                         f"{self._server.server_address[1]}")

    def start(self) -> "FakeAzureServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="fake-azure",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if getattr(self, "_thread", None) is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
