"""S3-compatible object store client (AWS Signature V4 over HTTP).

The reference's restic/rclone movers reach any S3-compatible endpoint via
~35 passthrough env vars from the repository Secret
(controllers/mover/restic/mover.go:317-364: AWS_ACCESS_KEY_ID,
AWS_SECRET_ACCESS_KEY, AWS_DEFAULT_REGION, ...; restic's repository URL
form is ``s3:http://endpoint/bucket/prefix``). This client speaks the
same subset the movers need — PUT/GET/Range-GET/HEAD/DELETE/ListObjectsV2
with pagination — using only the standard library (no egress in this
environment; tests run against the in-process ``fakes3`` server, the
MinIO analogue of hack/run-minio.sh).

Signing is real SigV4 (payload-hash signed headers), so the fake server
can *verify* signatures and the client is wire-correct against MinIO/S3.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import os
import threading
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Iterator, Optional
from urllib.parse import quote, urlsplit

from volsync_tpu.objstore.store import NoSuchKey, _check_key
from volsync_tpu.resilience import RetryPolicy

_ALGO = "AWS4-HMAC-SHA256"
_SAFE = "-_.~"  # RFC 3986 unreserved (minus alnum, handled by quote)


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def signing_key(secret_key: str, datestamp: str, region: str) -> bytes:
    k = _hmac(("AWS4" + secret_key).encode(), datestamp.encode())
    k = _hmac(k, region.encode())
    k = _hmac(k, b"s3")
    return _hmac(k, b"aws4_request")


def canonical_query(query: dict) -> str:
    return "&".join(
        f"{quote(str(k), safe=_SAFE)}={quote(str(v), safe=_SAFE)}"
        for k, v in sorted(query.items())
    )


def string_to_sign(method: str, uri: str, query: dict, host: str,
                   payload_hash: str, amz_date: str, region: str,
                   ) -> tuple[str, str]:
    """Build (string-to-sign, credential scope) for one request. Shared
    verbatim by the client and the fake server's verifier so a signing
    bug cannot hide."""
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    creq = "\n".join([
        method, quote(uri, safe="/" + _SAFE), canonical_query(query),
        canonical_headers, signed, payload_hash,
    ])
    datestamp = amz_date[:8]
    scope = f"{datestamp}/{region}/s3/aws4_request"
    sts = "\n".join([_ALGO, amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    return sts, scope


def sign_request(method: str, uri: str, query: dict, host: str,
                 payload_hash: str, access_key: str, secret_key: str,
                 region: str,
                 now: Optional[datetime.datetime] = None) -> dict:
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    sts, scope = string_to_sign(method, uri, query, host, payload_hash,
                                amz_date, region)
    sig = hmac.new(signing_key(secret_key, amz_date[:8], region),
                   sts.encode(), hashlib.sha256).hexdigest()
    auth = (f"{_ALGO} Credential={access_key}/{scope}, "
            f"SignedHeaders=host;x-amz-content-sha256;x-amz-date, "
            f"Signature={sig}")
    return {"Authorization": auth, "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash}


class S3Error(RuntimeError):
    def __init__(self, status: int, body: bytes):
        super().__init__(f"S3 error {status}: {body[:300]!r}")
        self.status = status


class SinkRetryRefused(RuntimeError):
    """A GET into an unseekable sink failed after bytes were already
    written; retrying would duplicate them. Plain RuntimeError so
    resilience.classify treats it as fatal."""


class S3ObjectStore:
    """Bucket + key-prefix view over an S3-compatible endpoint."""

    def __init__(self, endpoint: str, bucket: str, prefix: str = "", *,
                 access_key: str, secret_key: str,
                 region: str = "us-east-1"):
        u = urlsplit(endpoint if "//" in endpoint else f"http://{endpoint}")
        if u.scheme not in ("http", "https"):
            raise ValueError(f"unsupported endpoint scheme {u.scheme!r}")
        self.scheme = u.scheme
        self.host = u.netloc
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self._local = threading.local()
        # Transport-level policy: the old behavior was exactly one
        # reconnect on a stale pooled connection; op-level retry (with
        # the full attempt budget and the backend breaker) layers on
        # top in ResilientStore via open_store().
        self._transport_policy = RetryPolicy.from_env(
            "objstore.s3.transport", max_attempts=2, deadline=None,
            base_delay=0.02, max_delay=0.25)

    # -- URL / env plumbing --------------------------------------------------

    @classmethod
    def from_url(cls, url: str, env: Optional[dict] = None) -> "S3ObjectStore":
        """Open ``s3:http://endpoint/bucket/prefix`` (restic's URL form)
        or ``s3://bucket/prefix`` (endpoint from AWS_S3_ENDPOINT), with
        credentials from the env mapping — the exact passthrough contract
        of the reference's Secret->env plumbing (restic/mover.go:317-364).
        """
        env = dict(os.environ if env is None else env)
        access = env.get("AWS_ACCESS_KEY_ID", "")
        secret = env.get("AWS_SECRET_ACCESS_KEY", "")
        region = (env.get("AWS_DEFAULT_REGION") or env.get("AWS_REGION")
                  or "us-east-1")
        if url.startswith("s3://"):
            endpoint = env.get("AWS_S3_ENDPOINT")
            if not endpoint:
                raise ValueError(
                    "s3://bucket URLs need AWS_S3_ENDPOINT in the env")
            rest = url[len("s3://"):]
        elif url.startswith("s3:"):
            tail = url[len("s3:"):]
            if "://" in tail:
                u = urlsplit(tail)
                endpoint = f"{u.scheme}://{u.netloc}"
                rest = u.path.lstrip("/")
            else:
                # restic's scheme-less form s3:host/bucket/prefix
                # defaults to HTTPS (restic's documented behavior).
                host, _, rest = tail.partition("/")
                endpoint = f"https://{host}"
        else:
            raise ValueError(f"not an s3 URL: {url!r}")
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"s3 URL {url!r} has no bucket")
        return cls(endpoint, bucket, prefix, access_key=access,
                   secret_key=secret, region=region)

    # -- request core --------------------------------------------------------

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self.scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(self.host, timeout=60)
            self._local.conn = conn
        return conn

    def _uri(self, key: str = "") -> str:
        parts = [self.bucket]
        full = f"{self.prefix}/{key}" if self.prefix else key
        if full:
            parts.append(full)
        return "/" + "/".join(parts)

    def _request(self, method: str, key: str = "", query: Optional[dict] = None,
                 body=b"", headers: Optional[dict] = None,
                 uri: Optional[str] = None,
                 payload_hash: Optional[str] = None,
                 content_length: Optional[int] = None,
                 sink=None) -> tuple[int, dict, bytes]:
        """One signed request. ``body`` may be bytes or a seekable file
        object (then ``payload_hash``/``content_length`` are required —
        SigV4 signs the payload hash, so file bodies are hashed by the
        caller in a first pass and streamed on send). With ``sink`` the
        response body streams into it in 1 MiB chunks instead of being
        returned (bounded-memory GET)."""
        query = query or {}
        uri = uri if uri is not None else self._uri(key)
        if payload_hash is None:
            payload_hash = hashlib.sha256(body).hexdigest()
        hdrs = sign_request(method, uri, query, self.host, payload_hash,
                            self.access_key, self.secret_key, self.region)
        if content_length is not None:
            # Explicit length makes http.client stream a file body as-is
            # (no chunked transfer-encoding, which S3 SigV4 doesn't sign).
            hdrs["Content-Length"] = str(content_length)
        hdrs.update(headers or {})
        qs = canonical_query(query)
        path = quote(uri, safe="/" + _SAFE) + (f"?{qs}" if qs else "")
        # Sink retry hazard: a connection drop AFTER sink.write() has
        # consumed bytes must not replay those bytes. Seekable sinks are
        # rewound (seek + truncate) to their pre-request position at the
        # start of every attempt; an unseekable sink that has drained
        # bytes refuses the retry with a fatal SinkRetryRefused.
        sink_start: Optional[int] = None
        if sink is not None:
            try:
                sink_start = sink.tell()
            except (OSError, AttributeError):
                sink_start = None

        def one_attempt() -> tuple[int, dict, bytes]:
            if sink is not None and sink_start is not None:
                if sink.tell() != sink_start:
                    sink.seek(sink_start)
                    sink.truncate()
            conn = self._conn()
            drained = 0
            try:
                if hasattr(body, "seek"):
                    body.seek(0)
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                if sink is not None and resp.status in (200, 206):
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        sink.write(chunk)
                        drained += len(chunk)
                    return resp.status, dict(resp.getheaders()), b""
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            except (http.client.HTTPException, OSError) as exc:
                # Stale pooled connection: drop it so the next attempt
                # dials fresh.
                self._local.conn = None
                if sink is not None and sink_start is None and drained:
                    raise SinkRetryRefused(
                        f"GET {key!r}: connection lost after {drained} "
                        f"bytes reached an unseekable sink") from exc
                raise

        return self._transport_policy.call(one_attempt)

    # -- ObjectStore protocol ------------------------------------------------

    def put(self, key: str, data) -> None:
        # SigV4 hashes the payload, so the transport needs one
        # contiguous body — body_bytes is the ledger-sanctioned
        # assemble site for iovec PutBody parts.
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        status, _, body = self._request("PUT", key, body=body_bytes(data))
        if status not in (200, 201, 204):
            raise S3Error(status, body)

    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Conditional PUT with If-None-Match: * (S3's native
        create-if-absent; MinIO and AWS support it) — 412 means another
        writer won the race.

        Retry hazard: _request re-sends once on a dropped connection, so
        if OUR first PUT committed server-side before the connection
        died, the retry sees a 412 for our own object and this returns
        False. Callers must treat False as "the key exists" (and read it
        back) — NOT as "someone else's data is there"; don't build a
        lock/lease on this primitive without an ETag check."""
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        status, _, body = self._request(
            "PUT", key, body=body_bytes(data),
            headers={"If-None-Match": "*"})
        if status in (200, 201, 204):
            return True
        if status in (409, 412):
            return False
        raise S3Error(status, body)

    def get(self, key: str) -> bytes:
        status, _, body = self._request("GET", key)
        if status == 404:
            raise NoSuchKey(key)
        if status != 200:
            raise S3Error(status, body)
        return body

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        if length <= 0:
            return b""
        status, _, body = self._request(
            "GET", key,
            headers={"Range": f"bytes={offset}-{offset + length - 1}"})
        if status == 404:
            raise NoSuchKey(key)
        if status not in (200, 206):
            raise S3Error(status, body)
        return body if status == 206 else body[offset: offset + length]

    def exists(self, key: str) -> bool:
        status, _, _ = self._request("HEAD", key)
        if status == 200:
            return True
        if status == 404:
            return False
        # Anything else (403 throttle, 5xx outage) must NOT read as
        # "absent": Repository.init guards against clobbering an existing
        # repo with exists("config"), and a transient error mapped to
        # False would overwrite its config/salt — losing every snapshot.
        raise S3Error(status, b"")

    def delete(self, key: str) -> None:
        status, _, body = self._request("DELETE", key)
        if status not in (200, 204, 404):
            raise S3Error(status, body)

    def size(self, key: str) -> int:
        status, headers, body = self._request("HEAD", key)
        if status == 404:
            raise NoSuchKey(key)
        if status != 200:
            raise S3Error(status, body)
        return int(headers.get("Content-Length", "0"))

    def list(self, prefix: str = "") -> Iterator[str]:
        """ListObjectsV2 with continuation-token pagination."""
        full_prefix = (f"{self.prefix}/{prefix}" if self.prefix else prefix)
        token = None
        while True:
            query = {"list-type": "2", "prefix": full_prefix}
            if token:
                query["continuation-token"] = token
            status, _, body = self._request("GET", uri=f"/{self.bucket}",
                                            query=query)
            if status != 200:
                raise S3Error(status, body)
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            strip = len(self.prefix) + 1 if self.prefix else 0
            for contents in root.iter(f"{ns}Contents"):
                key = contents.find(f"{ns}Key").text
                yield key[strip:] if strip else key
            truncated = root.find(f"{ns}IsTruncated")
            if truncated is None or truncated.text != "true":
                return
            token = root.find(f"{ns}NextContinuationToken").text

    # -- bounded-memory file transfer ---------------------------------------

    def put_file(self, key: str, src) -> None:
        """Bounded-memory upload: SigV4 needs the payload hash up front,
        so the file is read twice — a hash pass, then a streamed send."""
        _check_key(key)
        src = Path(src)
        h = hashlib.sha256()
        with open(src, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        size = src.stat().st_size
        with open(src, "rb") as f:
            status, _, body = self._request(
                "PUT", key, body=f, payload_hash=h.hexdigest(),
                content_length=size)
        if status not in (200, 201, 204):
            raise S3Error(status, body)

    def get_file(self, key: str, dst) -> int:
        """Bounded-memory download: the response streams straight into a
        temp file, made visible atomically (rename)."""
        dst = Path(dst)
        tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
        with open(tmp, "wb") as sink:
            status, headers, body = self._request("GET", key, sink=sink)
        if status != 200:
            tmp.unlink(missing_ok=True)
            if status == 404:
                raise NoSuchKey(key)
            raise S3Error(status, body)
        n = tmp.stat().st_size
        tmp.replace(dst)
        return n
