"""Object-store abstraction for repository backends.

The reference's restic/rclone movers talk HTTPS to any S3-compatible
endpoint via ~35 passthrough env vars (controllers/mover/restic/
mover.go:317-364). Here the store is a minimal key/value interface with a
filesystem implementation (the MinIO-in-kind analogue of the e2e tier —
hack/run-minio.sh) and an in-memory one for tests; a real S3 client can
slot in behind the same interface when network egress exists.
"""

from volsync_tpu.objstore.store import (
    FsObjectStore,
    MemObjectStore,
    ObjectStore,
    open_store,
)

__all__ = ["ObjectStore", "FsObjectStore", "MemObjectStore", "open_store"]
