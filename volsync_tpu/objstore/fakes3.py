"""In-process S3-compatible server — the MinIO analogue.

The reference's e2e tier deploys MinIO as the S3 endpoint for the
restic/rclone movers (hack/run-minio.sh); this serves the same role for
the TPU build's tests without containers: an HTTP server implementing the
object subset the movers use (PUT/GET/Range-GET/HEAD/DELETE/
ListObjectsV2 with pagination), storing objects in memory, and
**verifying every request's SigV4 signature** against its configured
credentials — so client-side signing bugs fail loudly in tests instead
of surfacing only against real S3.
"""

from __future__ import annotations

import hashlib
import hmac
import http.server
import threading
from typing import Optional
from urllib.parse import parse_qsl, unquote, urlsplit
from xml.sax.saxutils import escape

from volsync_tpu.analysis import lockcheck
from volsync_tpu.objstore.s3 import signing_key, string_to_sign


class FakeS3Server:
    def __init__(self, *, access_key: str = "test-access",
                 secret_key: str = "test-secret",
                 region: str = "us-east-1", host: str = "127.0.0.1",
                 port: int = 0, max_keys: int = 1000):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.max_keys = max_keys
        self._objects: dict[tuple[str, str], bytes] = {}  # (bucket, key)
        self._lock = lockcheck.make_lock("objstore.fakes3")
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes = b"",
                       headers: Optional[dict] = None):
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _verify(self, body: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                amz_date = self.headers.get("x-amz-date", "")
                payload_hash = self.headers.get("x-amz-content-sha256", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return False
                if hashlib.sha256(body).hexdigest() != payload_hash:
                    return False
                fields = dict(
                    part.strip().split("=", 1)
                    for part in auth[len("AWS4-HMAC-SHA256 "):].split(",")
                )
                cred = fields.get("Credential", "")
                if not cred.startswith(outer.access_key + "/"):
                    return False
                u = urlsplit(self.path)
                query = dict(parse_qsl(u.query, keep_blank_values=True))
                sts, _ = string_to_sign(
                    self.command, unquote(u.path), query,
                    self.headers.get("Host", ""), payload_hash, amz_date,
                    outer.region)
                want = hmac.new(
                    signing_key(outer.secret_key, amz_date[:8], outer.region),
                    sts.encode(), hashlib.sha256).hexdigest()
                return hmac.compare_digest(want, fields.get("Signature", ""))

            def _route(self):
                u = urlsplit(self.path)
                parts = unquote(u.path).lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                query = dict(parse_qsl(u.query, keep_blank_values=True))
                return bucket, key, query

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or 0)
                return self.rfile.read(n) if n else b""

            def do_PUT(self):  # noqa: N802
                body = self._read_body()
                if not self._verify(body):
                    return self._reply(403, b"<Error>SignatureDoesNotMatch</Error>")
                bucket, key, _ = self._route()
                if not key:
                    return self._reply(200)  # CreateBucket
                with outer._lock:
                    if (self.headers.get("If-None-Match") == "*"
                            and (bucket, key) in outer._objects):
                        return self._reply(
                            412, b"<Error>PreconditionFailed</Error>")
                    outer._objects[(bucket, key)] = body
                self._reply(200)

            def do_GET(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403, b"<Error>SignatureDoesNotMatch</Error>")
                bucket, key, query = self._route()
                if not key:
                    return self._list(bucket, query)
                with outer._lock:
                    data = outer._objects.get((bucket, key))
                if data is None:
                    return self._reply(404, b"<Error>NoSuchKey</Error>")
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    lo_s, _, hi_s = rng[len("bytes="):].partition("-")
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else len(data) - 1
                    part = data[lo: hi + 1]
                    return self._reply(206, part, {
                        "Content-Range":
                            f"bytes {lo}-{lo + len(part) - 1}/{len(data)}"})
                self._reply(200, data)

            def do_HEAD(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403)
                bucket, key, _ = self._route()
                with outer._lock:
                    data = outer._objects.get((bucket, key))
                if data is None:
                    return self._reply(404)
                # BaseHTTPRequestHandler suppresses bodies for HEAD; the
                # Content-Length header carries the size.
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()

            def do_DELETE(self):  # noqa: N802
                if not self._verify(b""):
                    return self._reply(403)
                bucket, key, _ = self._route()
                with outer._lock:
                    outer._objects.pop((bucket, key), None)
                self._reply(204)

            def _list(self, bucket: str, query: dict):
                prefix = query.get("prefix", "")
                token = query.get("continuation-token", "")
                with outer._lock:
                    keys = sorted(k for (b, k) in outer._objects
                                  if b == bucket and k.startswith(prefix))
                start = 0
                if token:
                    # token = last key of the previous page
                    import bisect

                    start = bisect.bisect_right(keys, token)
                page = keys[start: start + outer.max_keys]
                truncated = start + len(page) < len(keys)
                xml = ["<?xml version='1.0'?><ListBucketResult>"]
                xml.append(f"<IsTruncated>{'true' if truncated else 'false'}"
                           "</IsTruncated>")
                if truncated:
                    xml.append(f"<NextContinuationToken>{escape(page[-1])}"
                               "</NextContinuationToken>")
                for k in page:
                    xml.append(f"<Contents><Key>{escape(k)}</Key></Contents>")
                xml.append("</ListBucketResult>")
                self._reply(200, "".join(xml).encode(),
                            {"Content-Type": "application/xml"})

            def log_message(self, *args):  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.endpoint = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="fake-s3")

    def start(self) -> "FakeS3Server":
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
