"""Azure Blob Storage client (SharedKey auth over HTTP, stdlib-only).

The reference's restic mover passes the AZURE_ACCOUNT_NAME /
AZURE_ACCOUNT_KEY env family straight through to its engine
(controllers/mover/restic/mover.go:341-345; repository URLs of the form
``azure:container:/path``). This is the wire-correct equivalent:
BlockBlob PUT/GET/Range-GET/HEAD/DELETE and container LIST with marker
pagination, signed with the 2015+ SharedKey scheme. The string-to-sign
builder is shared verbatim with the in-process verifying fake
(objstore/fakeazure.py), so a signing bug cannot hide — the same
pattern as the S3 client + fakes3 pair.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import threading
import xml.etree.ElementTree as ET
from typing import Iterator, Optional
from urllib.parse import quote, urlsplit

from volsync_tpu.objstore.store import NoSuchKey, _check_key
from volsync_tpu.resilience import RetryPolicy

API_VERSION = "2021-08-06"
_SAFE = "-_.~/"


def string_to_sign(method: str, account: str, path: str, query: dict,
                   headers: dict, content_length: int) -> str:
    """SharedKey string-to-sign (version 2015-02-21+: empty
    Content-Length when zero). ``headers`` must already carry the
    x-ms-* set; standard headers not in the fixed list are empty."""
    xms = {k.lower(): v for k, v in headers.items()
           if k.lower().startswith("x-ms-")}
    canon_headers = "".join(f"{k}:{xms[k]}\n" for k in sorted(xms))
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    return "\n".join([
        method,
        "",  # Content-Encoding
        "",  # Content-Language
        str(content_length) if content_length else "",
        "",  # Content-MD5
        headers.get("Content-Type", ""),
        "",  # Date (x-ms-date is used instead)
        "",  # If-Modified-Since
        "",  # If-Match
        headers.get("If-None-Match", ""),
        "",  # If-Unmodified-Since
        headers.get("Range", ""),
    ]) + "\n" + canon_headers + canon_resource


def sign(key_b64: str, sts: str) -> str:
    digest = hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                      hashlib.sha256).digest()
    return base64.b64encode(digest).decode()


class AzureError(RuntimeError):
    def __init__(self, status: int, body: bytes):
        super().__init__(f"HTTP {status}: {body[:200]!r}")
        self.status = status


class AzureBlobStore:
    """ObjectStore over one container + key prefix."""

    def __init__(self, endpoint: str, account: str, key_b64: str,
                 container: str, prefix: str = ""):
        u = urlsplit(endpoint)
        self.scheme = u.scheme or "https"
        self.netloc = u.netloc or u.path
        self.account = account
        self.key_b64 = key_b64
        self.container = container
        self.prefix = prefix.strip("/")
        self._local = threading.local()
        # Transport-level policy: one reconnect on a stale keep-alive
        # socket (the old inline loop's budget); op-level retry layers
        # on in ResilientStore via open_store().
        self._transport_policy = RetryPolicy.from_env(
            "objstore.azure.transport", max_attempts=2, deadline=None,
            base_delay=0.02, max_delay=0.25)

    @classmethod
    def from_url(cls, url: str, env: dict) -> "AzureBlobStore":
        """``azure:container:/path`` (restic's URL form) with the
        AZURE_* env family. AZURE_ENDPOINT overrides the public cloud
        endpoint (tests point it at the in-process fake; sovereign
        clouds set their suffix through it too)."""
        account = env.get("AZURE_ACCOUNT_NAME", "")
        key = env.get("AZURE_ACCOUNT_KEY", "")
        if not account or not key:
            raise ValueError(
                "azure: repository needs AZURE_ACCOUNT_NAME and "
                "AZURE_ACCOUNT_KEY in the repository Secret "
                "(restic/mover.go:341-345 passthrough)")
        rest = url[len("azure:"):]
        container, _, prefix = rest.partition(":")
        if not container:
            raise ValueError(f"azure URL {url!r} has no container")
        prefix = prefix.lstrip("/")
        endpoint = env.get(
            "AZURE_ENDPOINT", f"https://{account}.blob.core.windows.net")
        return cls(endpoint, account, key, container, prefix)

    # -- request core -------------------------------------------------------

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            c = (http.client.HTTPSConnection if self.scheme == "https"
                 else http.client.HTTPConnection)
            conn = self._local.conn = c(self.netloc, timeout=60)
        return conn

    def _path(self, key: str = "") -> str:
        parts = [self.container]
        full = "/".join(p for p in (self.prefix, key) if p)
        if full:
            parts.append(full)
        return "/" + "/".join(parts)

    def _request(self, method: str, key: str = "",
                 query: Optional[dict] = None, body: bytes = b"",
                 headers: Optional[dict] = None, *, want_body: bool = True,
                 path: Optional[str] = None) -> tuple[int, bytes, dict]:
        import datetime

        query = query or {}
        path = path if path is not None else self._path(key)
        hdrs = dict(headers or {})
        hdrs["x-ms-date"] = datetime.datetime.now(
            datetime.timezone.utc).strftime("%a, %d %b %Y %H:%M:%S GMT")
        hdrs["x-ms-version"] = API_VERSION
        sts = string_to_sign(method, self.account, path, query, hdrs,
                             len(body))
        hdrs["Authorization"] = (
            f"SharedKey {self.account}:{sign(self.key_b64, sts)}")
        qs = "&".join(f"{quote(k, safe=_SAFE)}={quote(str(v), safe=_SAFE)}"
                      for k, v in sorted(query.items()))
        target = quote(path, safe=_SAFE) + (f"?{qs}" if qs else "")
        def one_attempt() -> tuple[int, bytes, dict]:
            conn = self._conn()
            try:
                conn.request(method, target, body=body or None,
                             headers=hdrs)
                resp = conn.getresponse()
                data = resp.read() if want_body else resp.read()
                return resp.status, data, dict(resp.getheaders())
            except (http.client.HTTPException, ConnectionError, OSError):
                # stale keep-alive: drop it so the retry dials fresh
                self._local.conn = None
                raise

        return self._transport_policy.call(one_attempt)

    # -- ObjectStore protocol ----------------------------------------------

    def put(self, key: str, data) -> None:
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        st, body, _ = self._request(
            "PUT", key, body=body_bytes(data),
            headers={"x-ms-blob-type": "BlockBlob"})
        if st not in (201,):
            raise AzureError(st, body)

    def put_if_absent(self, key: str, data) -> bool:
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        st, body, _ = self._request(
            "PUT", key, body=body_bytes(data),
            headers={"x-ms-blob-type": "BlockBlob", "If-None-Match": "*"})
        if st == 201:
            return True
        if st in (409, 412):  # BlobAlreadyExists / condition not met
            return False
        raise AzureError(st, body)

    def get(self, key: str) -> bytes:
        _check_key(key)
        st, body, _ = self._request("GET", key)
        if st == 404:
            raise NoSuchKey(key)
        if st != 200:
            raise AzureError(st, body)
        return body

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        _check_key(key)
        if length <= 0:
            return b""
        st, body, _ = self._request(
            "GET", key,
            headers={"Range": f"bytes={offset}-{offset + length - 1}"})
        if st == 404:
            raise NoSuchKey(key)
        if st not in (200, 206):
            raise AzureError(st, body)
        return body

    def exists(self, key: str) -> bool:
        _check_key(key)
        st, _, _ = self._request("HEAD", key, want_body=False)
        if st == 200:
            return True
        if st == 404:
            return False
        raise AzureError(st, b"")

    def size(self, key: str) -> int:
        _check_key(key)
        st, _, hdrs = self._request("HEAD", key, want_body=False)
        if st == 404:
            raise NoSuchKey(key)
        if st != 200:
            raise AzureError(st, b"")
        return int(hdrs.get("Content-Length", "0"))

    def delete(self, key: str) -> None:
        _check_key(key)
        st, body, _ = self._request("DELETE", key)
        if st not in (202, 404):
            raise AzureError(st, body)

    def list(self, prefix: str = "") -> Iterator[str]:
        # Always keep the "/" after a store prefix (the S3 backend's
        # form): joining without it makes list("") match sibling
        # containers of the prefix and mis-strip their keys.
        full = f"{self.prefix}/{prefix}" if self.prefix else prefix
        strip = len(self.prefix) + 1 if self.prefix else 0
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list"}
            if full:
                query["prefix"] = full
            if marker:
                query["marker"] = marker
            st, body, _ = self._request("GET", query=query,
                                        path=f"/{self.container}")
            if st != 200:
                raise AzureError(st, body)
            root = ET.fromstring(body)
            for name in root.iter("Name"):
                yield (name.text or "")[strip:]
            marker = (root.findtext("NextMarker") or "").strip()
            if not marker:
                return
