"""Key/value object stores: filesystem-backed and in-memory.

Keys are slash-separated paths (``data/ab/abcdef...``). Writes are
atomic (temp file + rename) so a crashed backup never leaves a torn
object — the repository layer relies on this for its crash-consistency
story (objects are immutable once visible, like S3 PUTs).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Iterator, Optional, Protocol


class ObjectStore(Protocol):
    def put(self, key: str, data: bytes) -> None: ...
    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Atomic create-if-absent; False = the key already exists.
        Required: Repository.init's no-clobber guarantee rests on it."""
        ...
    def get(self, key: str) -> bytes: ...
    def get_range(self, key: str, offset: int, length: int) -> bytes: ...
    def exists(self, key: str) -> bool: ...
    def delete(self, key: str) -> None: ...
    def list(self, prefix: str = "") -> Iterator[str]: ...
    def size(self, key: str) -> int: ...


def put_file(store, key: str, src) -> None:
    """Upload a local file as one object with bounded memory when the
    store supports it (multipart-upload analogue); whole-bytes fallback
    otherwise."""
    fn = getattr(store, "put_file", None)
    if fn is not None:
        fn(key, src)
    else:
        store.put(key, Path(src).read_bytes())


def get_file(store, key: str, dst) -> int:
    """Download an object into a local file with bounded memory when the
    store supports it; returns bytes written. The write is atomic
    (temp + rename) so a crashed transfer never leaves a torn file."""
    fn = getattr(store, "get_file", None)
    if fn is not None:
        return fn(key, dst)
    data = store.get(key)
    dst = Path(dst)
    tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
    tmp.write_bytes(data)
    tmp.replace(dst)
    return len(data)


class NoSuchKey(KeyError):
    pass


def _check_key(key: str):
    parts = key.split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise ValueError(f"invalid object key {key!r}")


class FsObjectStore:
    """Directory-backed store; the shape of the S3 bucket the reference's
    movers write to, minus the network."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        _check_key(key)
        return self.root / key

    def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        tmp.write_bytes(data)
        tmp.rename(p)  # atomic visibility

    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Atomic create-if-absent (hard link fails if the target
        exists): the primitive Repository.init uses so two movers racing
        to initialize one repository can never clobber each other's
        config/salt."""
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        tmp.write_bytes(data)
        try:
            os.link(tmp, p)
            return True
        except FileExistsError:
            return False
        finally:
            tmp.unlink(missing_ok=True)

    def get(self, key: str) -> bytes:
        try:
            return self._path(key).read_bytes()
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        """Ranged read (S3 Range-GET analogue) — how blob fetches avoid
        pulling whole packs."""
        try:
            with open(self._path(key), "rb") as f:
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()

    def delete(self, key: str) -> None:
        try:
            self._path(key).unlink()
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> Iterator[str]:
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                if f.startswith(".tmp."):
                    continue
                key = str(Path(dirpath, f).relative_to(self.root))
                key = key.replace(os.sep, "/")
                if key.startswith(prefix):
                    yield key

    def size(self, key: str) -> int:
        try:
            return self._path(key).stat().st_size
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def put_file(self, key: str, src) -> None:
        import shutil

        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        shutil.copyfile(src, tmp)
        tmp.rename(p)

    def get_file(self, key: str, dst) -> int:
        import shutil

        dst = Path(dst)
        tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
        try:
            shutil.copyfile(self._path(key), tmp)
        except FileNotFoundError:
            raise NoSuchKey(key) from None
        n = tmp.stat().st_size
        tmp.replace(dst)
        return n


class MemObjectStore:
    """In-memory store for unit tests (the fake backend of SURVEY.md §4)."""

    def __init__(self):
        self._objs: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes) -> None:
        _check_key(key)
        with self._lock:
            self._objs[key] = bytes(data)

    def put_if_absent(self, key: str, data: bytes) -> bool:
        _check_key(key)
        with self._lock:
            if key in self._objs:
                return False
            self._objs[key] = bytes(data)
            return True

    def get(self, key: str) -> bytes:
        with self._lock:
            try:
                return self._objs[key]
            except KeyError:
                raise NoSuchKey(key) from None

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        return self.get(key)[offset : offset + length]

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._objs

    def delete(self, key: str) -> None:
        with self._lock:
            self._objs.pop(key, None)

    def list(self, prefix: str = "") -> Iterator[str]:
        with self._lock:
            keys = sorted(self._objs)
        for k in keys:
            if k.startswith(prefix):
                yield k

    def size(self, key: str) -> int:
        return len(self.get(key))


def open_store(url: str, env: Optional[dict] = None) -> ObjectStore:
    """Open a store by URL: ``s3:http://endpoint/bucket/prefix`` or
    ``s3://bucket/prefix`` (restic's repository URL forms, credentials
    from ``env`` — the Secret->env passthrough contract of
    controllers/mover/restic/mover.go:317-364), ``file:///path``,
    ``mem:``, or a bare path."""
    if url.startswith("s3:"):
        from volsync_tpu.objstore.s3 import S3ObjectStore

        return S3ObjectStore.from_url(url, env=env)
    if url.startswith("mem:"):
        return MemObjectStore()
    if url.startswith("file://"):
        return FsObjectStore(url[len("file://"):])
    return FsObjectStore(url)
