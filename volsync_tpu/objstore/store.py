"""Key/value object stores: filesystem-backed and in-memory.

Keys are slash-separated paths (``data/ab/abcdef...``). Writes are
atomic (temp file + rename) so a crashed backup never leaves a torn
object — the repository layer relies on this for its crash-consistency
story (objects are immutable once visible, like S3 PUTs).

``put``/``put_if_absent`` bodies are a *PutBody*: one buffer (bytes,
bytearray, memoryview) OR an iovec — a list/tuple of such buffers whose
logical concatenation is the object. The iovec form is the zero-copy
seal path's contract: the repository hands the pack down as its sealed
segment list and NO monolithic pack-body ``bytes`` is ever built on the
write path. Backends that can scatter-write (the filesystem store's
``writelines``) consume the parts directly; backends whose transport
needs one contiguous body (HTTP stores, the in-memory map) materialize
via ``body_bytes`` — the ledger-sanctioned ``objstore.assemble`` copy
site (docs/performance.md, "Zero-copy data movement").
"""

from __future__ import annotations

import os
import threading
import time

from volsync_tpu.analysis import lockcheck
from pathlib import Path
from typing import Iterator, Optional, Protocol, Sequence, Union

#: A put() body: one buffer or an iovec of buffers (see module doc).
PutBody = Union[bytes, bytearray, memoryview, Sequence[Union[
    bytes, bytearray, memoryview]]]


def body_parts(data: PutBody) -> Sequence:
    """Normalize a PutBody to its buffer parts (no copying)."""
    if isinstance(data, (list, tuple)):
        return data
    return (data,)


def body_len(data: PutBody) -> int:
    """Total byte length of a PutBody (no copying)."""
    if isinstance(data, (list, tuple)):
        return sum(len(p) for p in data)
    return len(data)


def body_bytes(data: PutBody) -> bytes:
    """One contiguous ``bytes`` for a PutBody — the single sanctioned
    materialization for backends whose transport needs it. Pass-through
    (copy-free) when the body already IS ``bytes``."""
    if isinstance(data, bytes):
        return data
    from volsync_tpu.obs import record_copy

    if isinstance(data, (list, tuple)):
        out = b"".join(data)
    else:
        out = bytes(data)
    record_copy("objstore.assemble", len(out))
    return out


class ObjectStore(Protocol):
    def put(self, key: str, data: PutBody) -> None: ...
    def put_if_absent(self, key: str, data: PutBody) -> bool:
        """Atomic create-if-absent; False = the key already exists.
        Required: Repository.init's no-clobber guarantee rests on it."""
        ...
    def get(self, key: str) -> bytes: ...
    def get_range(self, key: str, offset: int, length: int) -> bytes: ...
    def exists(self, key: str) -> bool: ...
    def delete(self, key: str) -> None: ...
    def list(self, prefix: str = "") -> Iterator[str]: ...
    def size(self, key: str) -> int: ...


def put_file(store, key: str, src) -> None:
    """Upload a local file as one object with bounded memory when the
    store supports it (multipart-upload analogue); whole-bytes fallback
    otherwise."""
    fn = getattr(store, "put_file", None)
    if fn is not None:
        fn(key, src)
    else:
        store.put(key, Path(src).read_bytes())


def get_file(store, key: str, dst) -> int:
    """Download an object into a local file with bounded memory when the
    store supports it; returns bytes written. The write is atomic
    (temp + rename) so a crashed transfer never leaves a torn file."""
    fn = getattr(store, "get_file", None)
    if fn is not None:
        return fn(key, dst)
    data = store.get(key)
    dst = Path(dst)
    tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
    tmp.write_bytes(data)
    tmp.replace(dst)
    return len(data)


class NoSuchKey(KeyError):
    pass


def _check_key(key: str):
    parts = key.split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise ValueError(f"invalid object key {key!r}")


class FsObjectStore:
    """Directory-backed store; the shape of the S3 bucket the reference's
    movers write to, minus the network."""

    def __init__(self, root):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        _check_key(key)
        return self.root / key

    def put(self, key: str, data: PutBody) -> None:
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        # writelines scatter-writes the iovec parts straight to the OS —
        # the seal path's segment list never becomes one Python blob.
        with open(tmp, "wb") as f:
            f.writelines(body_parts(data))
        tmp.rename(p)  # atomic visibility

    def put_if_absent(self, key: str, data: PutBody) -> bool:
        """Atomic create-if-absent (hard link fails if the target
        exists): the primitive Repository.init uses so two movers racing
        to initialize one repository can never clobber each other's
        config/salt."""
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        with open(tmp, "wb") as f:
            f.writelines(body_parts(data))
        try:
            os.link(tmp, p)
            return True
        except FileExistsError:
            return False
        finally:
            tmp.unlink(missing_ok=True)

    def get(self, key: str) -> bytes:
        try:
            return self._path(key).read_bytes()
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        """Ranged read (S3 Range-GET analogue) — how blob fetches avoid
        pulling whole packs."""
        try:
            with open(self._path(key), "rb") as f:
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()

    def delete(self, key: str) -> None:
        try:
            self._path(key).unlink()
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> Iterator[str]:
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                if f.startswith(".tmp."):
                    continue
                key = str(Path(dirpath, f).relative_to(self.root))
                key = key.replace(os.sep, "/")
                if key.startswith(prefix):
                    yield key

    def size(self, key: str) -> int:
        try:
            return self._path(key).stat().st_size
        except FileNotFoundError:
            raise NoSuchKey(key) from None

    def put_file(self, key: str, src) -> None:
        import shutil

        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.parent / f".tmp.{os.getpid()}.{threading.get_ident()}.{p.name}"
        shutil.copyfile(src, tmp)
        tmp.rename(p)

    def get_file(self, key: str, dst) -> int:
        import shutil

        dst = Path(dst)
        tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
        try:
            shutil.copyfile(self._path(key), tmp)
        except FileNotFoundError:
            raise NoSuchKey(key) from None
        n = tmp.stat().st_size
        tmp.replace(dst)
        return n


class MemObjectStore:
    """In-memory store for unit tests (the fake backend of SURVEY.md §4)."""

    def __init__(self):
        self._objs: dict[str, bytes] = {}
        self._lock = lockcheck.make_lock("objstore.mem")

    def put(self, key: str, data: PutBody) -> None:
        _check_key(key)
        body = body_bytes(data)
        with self._lock:
            self._objs[key] = body

    def put_if_absent(self, key: str, data: PutBody) -> bool:
        _check_key(key)
        body = body_bytes(data)
        with self._lock:
            if key in self._objs:
                return False
            self._objs[key] = body
            return True

    def get(self, key: str) -> bytes:
        with self._lock:
            try:
                return self._objs[key]
            except KeyError:
                raise NoSuchKey(key) from None

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        return self.get(key)[offset : offset + length]

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._objs

    def delete(self, key: str) -> None:
        with self._lock:
            self._objs.pop(key, None)

    def list(self, prefix: str = "") -> Iterator[str]:
        with self._lock:
            keys = sorted(self._objs)
        for k in keys:
            if k.startswith(prefix):
                yield k

    def size(self, key: str) -> int:
        return len(self.get(key))


class LatencyStore:
    """Wrap any ObjectStore with synthetic per-op latency (seconds) —
    the fake-cloud backend for pipeline benchmarks and backpressure
    tests (a MemObjectStore put is ~1 µs; a real store put is tens of
    ms, which is the regime the async upload stage exists for). Also
    counts ops and tracks high-water marks of concurrent puts/gets so
    tests can assert the upload window is honored and the restore
    drills can account store GETs (``pack_gets`` isolates data-pack
    fetches — the number the single-flight cache bounds). Zero-latency
    instances double as pure op counters."""

    def __init__(self, inner: ObjectStore, *, put_latency: float = 0.0,
                 get_latency: float = 0.0):
        self.inner = inner
        self.put_latency = put_latency
        self.get_latency = get_latency
        self.puts = 0
        self.max_concurrent_puts = 0
        self._active_puts = 0
        self.gets = 0            # get + get_range arrivals
        self.pack_gets = 0       # ... with a data/ key (any read)
        self.pack_fetches = 0    # whole-object data/ GETs only — the
        #                          count the single-flight cache bounds
        #                          (ranged tree-blob reads excluded)
        self.max_concurrent_gets = 0
        self._active_gets = 0
        self._lock = lockcheck.make_lock("objstore.latency")

    def _enter_get(self, key: str, whole: bool = False) -> None:
        with self._lock:
            self.gets += 1
            if key.startswith("data/"):
                self.pack_gets += 1
                if whole:
                    self.pack_fetches += 1
            self._active_gets += 1
            self.max_concurrent_gets = max(self.max_concurrent_gets,
                                           self._active_gets)

    def _exit_get(self) -> None:
        with self._lock:
            self._active_gets -= 1

    def put(self, key: str, data: PutBody) -> None:
        with self._lock:
            self.puts += 1
            self._active_puts += 1
            self.max_concurrent_puts = max(self.max_concurrent_puts,
                                           self._active_puts)
        try:
            if self.put_latency:
                time.sleep(self.put_latency)
            self.inner.put(key, data)
        finally:
            with self._lock:
                self._active_puts -= 1

    def put_if_absent(self, key: str, data: PutBody) -> bool:
        if self.put_latency:
            time.sleep(self.put_latency)
        return self.inner.put_if_absent(key, data)

    def get(self, key: str) -> bytes:
        self._enter_get(key, whole=True)
        try:
            if self.get_latency:
                time.sleep(self.get_latency)
            return self.inner.get(key)
        finally:
            self._exit_get()

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        self._enter_get(key)
        try:
            if self.get_latency:
                time.sleep(self.get_latency)
            return self.inner.get_range(key, offset, length)
        finally:
            self._exit_get()

    def exists(self, key: str) -> bool:
        return self.inner.exists(key)

    def delete(self, key: str) -> None:
        self.inner.delete(key)

    def list(self, prefix: str = "") -> Iterator[str]:
        return self.inner.list(prefix)

    def size(self, key: str) -> int:
        return self.inner.size(key)


def open_store(url: str, env: Optional[dict] = None) -> ObjectStore:
    """Open a store by repository URL with credentials from ``env`` —
    the Secret->env passthrough contract of
    controllers/mover/restic/mover.go:317-364.

    Supported forms (restic's URL vocabulary):
      ``s3:http://endpoint/bucket/prefix`` / ``s3://bucket/prefix``,
      ``azure:container:/path`` (SharedKey client, objstore/azure.py),
      ``b2:bucket:/path`` (via B2's S3-compatible endpoint),
      ``gs:bucket:/path`` (via GCS's S3-interop XML API, HMAC keys),
      ``swift:container:/path`` (Keystone v3 / v1 auth,
      objstore/swift.py), ``file:///path``, ``mem:``, or a bare path.

    Network backends come back wrapped in the shared retry policy +
    per-backend circuit breaker (resilience.ResilientStore; opt out
    with VOLSYNC_STORE_RESILIENCE=0). Local/mem stores are not wrapped
    — their failures are programming errors, not weather. Setting
    VOLSYNC_FAULT_SEED arms the deterministic fault-injection wrapper
    (objstore/faultstore.py) UNDER the resilience layer, exactly where
    real faults occur.
    """
    import os as _os

    from volsync_tpu import envflags as _envflags
    from volsync_tpu.resilience import ResilientStore

    def _resilient(store: ObjectStore, backend: str) -> ObjectStore:
        from volsync_tpu.objstore.faultstore import maybe_wrap

        store = maybe_wrap(store)
        if not _envflags.store_resilience_enabled():
            return store
        return ResilientStore(store, backend=backend)

    env_map = dict(_os.environ if env is None else env)
    if url.startswith("s3:"):
        from volsync_tpu.objstore.s3 import S3ObjectStore

        return _resilient(S3ObjectStore.from_url(url, env=env), "s3")
    if url.startswith("azure:"):
        from volsync_tpu.objstore.azure import AzureBlobStore

        return _resilient(AzureBlobStore.from_url(url, env_map), "azure")
    if url.startswith("b2:"):
        return _resilient(_b2_store(url, env_map), "b2")
    if url.startswith("gs:"):
        return _resilient(_gs_store(url, env_map), "gs")
    if url.startswith("swift:") or url.startswith("swift-temp:"):
        from volsync_tpu.objstore.swift import SwiftObjectStore

        return _resilient(SwiftObjectStore.from_url(url, env_map), "swift")
    if url.startswith("mem:"):
        from volsync_tpu.objstore.faultstore import maybe_wrap

        return maybe_wrap(MemObjectStore())
    if url.startswith("file://"):
        from volsync_tpu.objstore.faultstore import maybe_wrap

        return maybe_wrap(FsObjectStore(url[len("file://"):]))
    from volsync_tpu.objstore.faultstore import maybe_wrap

    return maybe_wrap(FsObjectStore(url))


def unwrap(store: ObjectStore) -> ObjectStore:
    """Peel resilience/fault-injection wrappers off a store opened via
    open_store() — diagnostics and tests that need the concrete backend
    (wrappers all expose the wrapped store as ``.inner``)."""
    while hasattr(store, "inner"):
        store = store.inner
    return store


def _bucket_path(url: str, scheme: str) -> tuple[str, str]:
    """Split restic's ``scheme:bucket:/path`` (or ``scheme:bucket/path``)
    into (bucket, path)."""
    rest = url[len(scheme) + 1:]
    if ":" in rest:
        bucket, _, path = rest.partition(":")
    else:
        bucket, _, path = rest.partition("/")
    if not bucket:
        raise ValueError(f"{scheme} URL {url!r} has no bucket")
    return bucket, path.lstrip("/")


def _b2_store(url: str, env: dict) -> ObjectStore:
    """Backblaze B2 via its S3-compatible endpoint (restic's b2: URL,
    B2_ACCOUNT_ID/B2_ACCOUNT_KEY env family — mover.go:331-334). B2's
    S3 endpoint embeds the bucket's region, so it must be given:
    B2_S3_ENDPOINT explicitly, or derived from B2_REGION."""
    from volsync_tpu.objstore.s3 import S3ObjectStore

    account = env.get("B2_ACCOUNT_ID", "")
    key = env.get("B2_ACCOUNT_KEY", "")
    if not account or not key:
        raise ValueError(
            "b2: repository needs B2_ACCOUNT_ID and B2_ACCOUNT_KEY in "
            "the repository Secret (restic/mover.go:331-334 passthrough); "
            "use the bucket's S3-compatible application key")
    endpoint = env.get("B2_S3_ENDPOINT")
    region = env.get("B2_REGION")
    if not endpoint and region:
        endpoint = f"https://s3.{region}.backblazeb2.com"
    if not endpoint:
        raise ValueError(
            "b2: repository needs B2_S3_ENDPOINT (e.g. "
            "https://s3.us-west-004.backblazeb2.com) or B2_REGION in "
            "the repository Secret — B2's S3-compatible endpoint is "
            "region-scoped")
    if not region:
        # B2 validates the SigV4 credential-scope region against the
        # endpoint, so it must match — derive it from the documented
        # hostname shape rather than defaulting to a wrong value.
        import re as _re

        m = _re.search(r"//s3\.([a-z0-9-]+)\.backblazeb2\.com", endpoint)
        if not m:
            raise ValueError(
                f"cannot derive the signing region from B2_S3_ENDPOINT="
                f"{endpoint!r}; set B2_REGION in the repository Secret")
        region = m.group(1)
    bucket, path = _bucket_path(url, "b2")
    return S3ObjectStore(endpoint, bucket, path, access_key=account,
                         secret_key=key, region=region)


def _gs_store(url: str, env: dict) -> ObjectStore:
    """Google Cloud Storage via the S3-interoperability XML API with
    HMAC keys (restic's gs: URL). Service-account JSON auth
    (GOOGLE_APPLICATION_CREDENTIALS) needs RS256 signing, which the
    stdlib cannot do — refuse with guidance instead of misconfiguring."""
    from volsync_tpu.objstore.s3 import S3ObjectStore

    access = env.get("GS_ACCESS_KEY_ID") or env.get("AWS_ACCESS_KEY_ID", "")
    secret = (env.get("GS_SECRET_ACCESS_KEY")
              or env.get("AWS_SECRET_ACCESS_KEY", ""))
    if not access or not secret:
        hint = ""
        if env.get("GOOGLE_APPLICATION_CREDENTIALS") or \
                env.get("GOOGLE_PROJECT_ID"):
            hint = (" — service-account JSON auth is not supported "
                    "(needs RS256); create HMAC interoperability keys "
                    "for the bucket and set GS_ACCESS_KEY_ID/"
                    "GS_SECRET_ACCESS_KEY")
        raise ValueError(
            "gs: repository needs GS_ACCESS_KEY_ID and "
            f"GS_SECRET_ACCESS_KEY in the repository Secret{hint}")
    endpoint = env.get("GS_S3_ENDPOINT", "https://storage.googleapis.com")
    bucket, path = _bucket_path(url, "gs")
    return S3ObjectStore(endpoint, bucket, path, access_key=access,
                         secret_key=secret, region="auto")
