"""Deterministic, seeded fault-injection ObjectStore wrapper.

"Optimized Disaster Recovery for Distributed Storage Systems"
(PAPERS.md) motivates verifying metadata/index consistency *under*
failure, not only on the happy path. ``FaultStore`` wraps any
ObjectStore and injects faults according to a seeded ``FaultSchedule``:

- ``transient``   — a retryable error (connection-reset analogue); for
                    writes, ``landed=1`` means the bytes reached the
                    store BEFORE the error (the S3 PUT-committed /
                    connection-died ambiguity).
- ``throttle``    — a retryable 429/Slow-Down analogue.
- ``latency``     — a latency spike (``ms=`` per hit).
- ``partial_put`` — a TORN write: the store receives a truncated
                    object, then the error raises. Retry must
                    OVERWRITE, not skip-if-exists.
- ``truncated_read`` — the connection drops mid-body (http.client
                    raises IncompleteRead in real life); retryable.
- ``crash``       — process death at operation N: a NON-retryable
                    error, and the store goes dead (every later call
                    fails too — in-flight worker threads cannot
                    quietly finish work the "dead" process started).
- ``hang``        — the call blocks (``ms=`` per hit, default 60 s)
                    past any caller-side deadline and THEN fails
                    retryable — a stuck TCP connection that a NAT
                    eventually reaps. The way to exercise
                    ``DeadlineExceeded`` paths in chaos schedules.
- ``partition``   — the store becomes unreachable for a DURATION
                    (``ms=`` per hit, default 5 s) and then heals:
                    every op inside the window fails retryable without
                    reaching the store. Distinct from ``crash``'s
                    sticky death — a replica that loses the network
                    while its siblings keep writing comes back; the
                    fleet drill's mid-outage failover rides this.
                    While partitioned, other specs' counters do not
                    advance (those ops never arrived at the store).
- ``vanish``      — a landed object LATER disappears: the triggering
                    op completes normally, then every subsequent
                    ``get``/``get_range``/``size`` of that key raises
                    ``NoSuchKey``, ``exists`` says False, and listings
                    omit it — the lost-shard / lost-replica fault
                    class (an object a bucket audit can no longer
                    find). Distinct from ``crash``'s sticky death
                    (only the KEY dies, the store lives) and from
                    ``delete`` (no client ever asked). A later PUT of
                    the key resurrects it — which is exactly what the
                    erasure-coding heal arms must be able to do.
- ``bitflip``     — SILENT corruption: a ``get``/``get_range`` payload
                    comes back with ``nbytes=`` byte positions XORed
                    (default 1) and NO exception raised — the bit-rot /
                    wrong-bytes fault class every loud kind above
                    misses. Corrupted positions and masks are a pure
                    hash of ``(seed, key, nth-occurrence)`` so the same
                    seed rots the same bytes on every run. Only read
                    ops match (the spec's ``at=N`` counter counts reads
                    only); the stored object itself is untouched.

Determinism: probability rolls are a pure hash of
``(seed, spec, op, key, nth-occurrence-of(op,key))`` — independent of
thread interleaving, so the same seed over the same multiset of
operations injects the same faults even under the concurrent upload
pool. ``at=N`` (fire at the Nth matching op) counts arrivals under a
lock and is deterministic for serial op sequences — what the
crash-at-op-N recovery scenarios use. Every injection is recorded in
``FaultStore.injected`` for replay assertions.

Arming: construct directly (tests), or set ``VOLSYNC_FAULT_SEED`` (+
optional ``VOLSYNC_FAULT_SPEC``) and open stores through
``open_store()`` / ``maybe_wrap()`` — the CLI and bench.py
(``--faults SEED``) ride that path.

Spec strings (``parse_spec``): semicolon-separated entries
``kind:key=value,...`` e.g. ::

    transient:p=0.05,op=put;latency:p=0.1,ms=2;crash:at=40,op=put,prefix=data/

``op`` accepts a pipe-separated list (``op=put|delete``) so one
crash-at-op-N counter can span every write boundary of a multi-op
protocol, e.g. the two-phase prune's mark/sweep steps.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from volsync_tpu import envflags
from volsync_tpu.analysis import lockcheck
from volsync_tpu.objstore.store import NoSuchKey
from volsync_tpu.obs import record_trigger
from volsync_tpu.resilience import ThrottleError, TransientError


class FaultInjected(TransientError):
    """A scheduled transient fault (retryable by classification)."""


class InjectedThrottle(ThrottleError):
    """A scheduled throttle response (retryable)."""


class InjectedCrash(RuntimeError):
    """Scheduled process death — NOT retryable (plain RuntimeError, so
    resilience.classify says fatal) and sticky: the store is dead."""


class InjectedHang(TransientError):
    """A scheduled hang: the call consumed the caller's patience before
    failing (retryable — but a deadline-aware policy has usually
    already expired by the time this surfaces)."""


class InjectedPartition(TransientError):
    """The store is inside a scheduled partition window: unreachable
    now, healed once the window elapses (retryable — a policy that
    keeps trying past the window succeeds)."""


class _Vanished(Exception):
    """Internal signal: the key is in the vanished set — surfaced to
    callers as NoSuchKey (or False from exists), never raised out."""


#: default blocked time for a ``hang`` spec that carries no ``ms=``
_HANG_DEFAULT_S = 60.0
#: default outage length for a ``partition`` spec that carries no ``ms=``
_PARTITION_DEFAULT_S = 5.0

_KINDS = ("transient", "throttle", "latency", "partial_put",
          "truncated_read", "crash", "hang", "partition", "bitflip",
          "vanish")
#: ops that mutate the store — the ones ``landed`` applies to
_WRITE_OPS = ("put", "put_if_absent", "delete")
#: ops returning a payload — the only ones ``bitflip`` can corrupt
_PAYLOAD_OPS = ("get", "get_range")
#: ops a vanished key answers "no such object" to (writes resurrect)
_VANISH_OPS = ("get", "get_range", "size", "exists")


@dataclass(frozen=True)
class FaultSpec:
    """One line of a fault schedule."""

    kind: str                  # one of _KINDS
    p: float = 0.0             # probability per matching op
    at: Optional[int] = None   # fire at the Nth matching op (1-based)
    op: str = "*"              # op filter: "*", one name, or "a|b|c"
    key_prefix: str = ""       # key startswith filter
    landed: bool = False       # write ops: inner op completes first
    latency: float = 0.0       # seconds, for kind="latency"
    nbytes: int = 1            # byte positions flipped, for kind="bitflip"

    def matches(self, op: str, key: str) -> bool:
        # ``op`` accepts a pipe-separated list ("put|delete") so one
        # crash counter can span every write stage of a multi-op
        # protocol (the two-phase prune's chaos schedules need
        # crash-at-op-N across its put AND delete boundaries).
        if self.kind == "bitflip" and op not in _PAYLOAD_OPS:
            # silent corruption only exists on payload-returning ops;
            # keeping non-reads out of ``matches`` keeps the spec's
            # at=N counter a pure read counter
            return False
        if self.op != "*" and op not in self.op.split("|"):
            return False
        return key.startswith(self.key_prefix)


def parse_spec(text: str) -> list[FaultSpec]:
    """Parse the VOLSYNC_FAULT_SPEC string format (module docstring)."""
    specs: list[FaultSpec] = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        kind, _, rest = entry.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(known: {', '.join(_KINDS)})")
        kwargs: dict = {}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            k, _, v = pair.partition("=")
            k = k.strip()
            v = v.strip()
            if k == "p":
                kwargs["p"] = float(v)
            elif k == "at":
                kwargs["at"] = int(v)
            elif k == "op":
                kwargs["op"] = v
            elif k == "prefix":
                kwargs["key_prefix"] = v
            elif k == "landed":
                kwargs["landed"] = v not in ("", "0", "false", "no")
            elif k == "ms":
                kwargs["latency"] = float(v) / 1000.0
            elif k == "nbytes":
                kwargs["nbytes"] = int(v)
            else:
                raise ValueError(f"unknown fault spec field {k!r}")
        specs.append(FaultSpec(kind=kind, **kwargs))
    return specs


def default_specs() -> list[FaultSpec]:
    """The transient-heavy profile a bare VOLSYNC_FAULT_SEED arms."""
    return [
        FaultSpec(kind="transient", p=0.05),
        FaultSpec(kind="latency", p=0.05, latency=0.002),
    ]


@dataclass
class FaultSchedule:
    """Seeded decision function over (op, key) arrivals."""

    seed: int
    specs: list = field(default_factory=default_specs)

    def roll(self, spec_idx: int, op: str, key: str, n: int) -> float:
        """Uniform [0,1) as a pure function of identity — thread-
        interleaving-independent determinism."""
        h = hashlib.blake2b(
            f"{self.seed}:{spec_idx}:{op}:{key}:{n}".encode(),
            digest_size=8).digest()
        return int.from_bytes(h, "big") / float(1 << 64)


class FaultStore:
    """ObjectStore wrapper applying a FaultSchedule (module docstring).

    With an all-zero schedule the wrapper is TRANSPARENT — the
    cross-backend contract test runs every backend through it to pin
    that down.
    """

    def __init__(self, inner, schedule: Optional[FaultSchedule] = None,
                 *, seed: int = 0,
                 sleep_fn=time.sleep,
                 clock=time.monotonic):
        self.inner = inner
        self.schedule = (schedule if schedule is not None
                         else FaultSchedule(seed=seed))
        self.injected: list[tuple[int, str, str, str]] = []
        self.crashed = False
        self._sleep = sleep_fn
        # partition windows are judged by this clock (injectable so
        # tests heal a partition without wall-clock waits)
        self._clock = clock
        self._partition_until = 0.0
        self._lock = lockcheck.make_lock("objstore.faults")
        # keys currently "lost" by a vanish fault (sticky until a
        # write of that key lands again)
        self._vanished: set[str] = set()
        self._op_count = 0
        # per-spec matching-op counters (for at=N) and per-(op,key)
        # occurrence counters (for the pure-hash rolls)
        self._spec_hits = [0] * len(self.schedule.specs)
        self._occurrence: dict[tuple[str, str], int] = {}

    # -- decision core ----------------------------------------------------

    def _decide(self, op: str, key: str) -> tuple[list[FaultSpec], int, int]:
        """All specs firing on this arrival (with the arrival's op index
        and per-(op,key) occurrence number), recorded — except
        ``bitflip``, which is recorded by ``_apply`` only when a
        corrupted payload actually reached the caller (a louder spec on
        the same arrival masks it). Raises InjectedCrash immediately
        when the store is already dead."""
        with self._lock:
            if self.crashed:
                raise InjectedCrash(
                    f"store is dead (earlier injected crash); {op} "
                    f"{key!r} refused")
            if self._clock() < self._partition_until:
                # inside an open partition window: the op never reaches
                # the store, and no spec counter advances for it
                raise InjectedPartition(
                    f"store partitioned; {op} {key!r} unreachable for "
                    f"{self._partition_until - self._clock():.3f}s more")
            if key in self._vanished and op in _VANISH_OPS:
                # the object is "lost": reads answer absence without
                # advancing any spec counter (they never reached a
                # real object) — writes fall through and resurrect
                raise _Vanished(key)
            self._op_count += 1
            opix = self._op_count
            n = self._occurrence.get((op, key), 0) + 1
            self._occurrence[(op, key)] = n
            fired: list[FaultSpec] = []
            for i, spec in enumerate(self.schedule.specs):
                if not spec.matches(op, key):
                    continue
                self._spec_hits[i] += 1
                hit = (self._spec_hits[i] == spec.at if spec.at is not None
                       else self.schedule.roll(i, op, key, n) < spec.p)
                if hit:
                    fired.append(spec)
                    if spec.kind not in ("bitflip", "vanish"):
                        # bitflip/vanish record in _apply, only once
                        # the op actually succeeded (a louder spec on
                        # the same arrival masks them)
                        self.injected.append((opix, op, key, spec.kind))
            if any(s.kind == "crash" for s in fired):
                self.crashed = True
        return fired, opix, n

    def _corrupt(self, data: bytes, key: str, n: int,
                 nbytes: int) -> bytes:
        """Deterministically XOR ``nbytes`` byte positions of ``data``.
        Positions and masks are a pure hash of (seed, key, nth) — the
        same seed rots the same bytes on every run — and every mask has
        its low bit set so a flipped byte always differs."""
        if not data:
            return data
        out = bytearray(data)
        for i in range(max(1, nbytes)):
            h = hashlib.blake2b(
                # schedule is set once in __init__ and never reassigned
                f"{self.schedule.seed}:bitflip:{key}:{n}:{i}".encode(),  # lint: ignore[VL402]
                digest_size=8).digest()
            pos = int.from_bytes(h[:6], "big") % len(out)
            out[pos] ^= h[6] | 0x01
        return bytes(out)

    def _apply(self, op: str, key: str, execute, *,
               torn_execute=None):
        """Run one op under the schedule. ``execute()`` performs the
        real operation; ``torn_execute()`` (writes only) performs the
        truncated form for partial_put."""
        try:
            fired, opix, n = self._decide(op, key)
        except _Vanished:
            record_trigger("fault", op=op, key=key, kinds=["vanish"])
            if op == "exists":
                return False
            raise NoSuchKey(f"{key} (vanished by fault injection)")
        if fired:
            # flight-recorder annotation, outside self._lock (_decide
            # released it) so the dump can never nest under it
            record_trigger("fault", op=op, key=key,
                           kinds=[s.kind for s in fired])
        for spec in fired:
            if spec.kind == "latency" and spec.latency > 0:
                self._sleep(spec.latency)
        crash = next((s for s in fired if s.kind == "crash"), None)
        part = next((s for s in fired if s.kind == "partition"), None)
        err = next((s for s in fired
                    if s.kind in ("transient", "throttle", "partial_put",
                                  "truncated_read", "hang")), None)
        if part is not None:
            duration = (part.latency if part.latency > 0
                        else _PARTITION_DEFAULT_S)
            with self._lock:
                self._partition_until = max(self._partition_until,
                                            self._clock() + duration)
            raise InjectedPartition(
                f"injected partition at {op} {key!r} "
                f"(unreachable {duration:.3f}s)")
        if crash is not None:
            if crash.landed and op in _WRITE_OPS:
                execute()
            raise InjectedCrash(f"injected crash at {op} {key!r}")
        if err is None:
            result = execute()
            if op in _WRITE_OPS:
                with self._lock:
                    # a landed write replaces (or truly removes) the
                    # object: the key stops being "lost"
                    self._vanished.discard(key)
            if any(s.kind == "vanish" for s in fired):
                with self._lock:
                    self._vanished.add(key)
                self.injected.append((opix, op, key, "vanish"))
            flips = [s for s in fired if s.kind == "bitflip"]
            if flips:
                # silent wrong-bytes: the op SUCCEEDS and the caller
                # receives a corrupted payload — one corruption per
                # arrival (widest nbytes wins when several specs fire),
                # recorded only now that it actually reached a caller
                result = self._corrupt(result, key, n,
                                       max(s.nbytes for s in flips))
                self.injected.append((opix, op, key, "bitflip"))
            return result
        if err.kind == "hang":
            # Block past the caller's deadline, then surface as a drop
            # (the op never reached the store — nothing lands).
            self._sleep(err.latency if err.latency > 0
                        else _HANG_DEFAULT_S)
            raise InjectedHang(f"injected hang at {op} {key!r}")
        if err.kind == "partial_put" and torn_execute is not None:
            torn_execute()
            raise FaultInjected(f"injected torn write at {op} {key!r}")
        if err.kind == "throttle":
            raise InjectedThrottle(f"injected throttle at {op} {key!r}")
        if err.kind == "truncated_read":
            raise FaultInjected(f"injected truncated read at {op} {key!r}")
        # transient
        if err.landed and op in _WRITE_OPS:
            execute()
        raise FaultInjected(f"injected transient error at {op} {key!r}")

    # -- ObjectStore protocol ---------------------------------------------

    def put(self, key: str, data) -> None:
        # PutBody-aware: iovec part lists pass through untouched (the
        # zero-copy seal path); the torn form truncates at the logical
        # half-length without materializing one blob.
        from volsync_tpu.objstore.store import body_len, body_parts

        half = max(0, body_len(data) // 2)

        def torn():
            out: list = []
            left = half
            for p in body_parts(data):
                if left <= 0:
                    break
                if len(p) <= left:
                    out.append(p)
                    left -= len(p)
                else:
                    out.append(memoryview(p)[:left])
                    left = 0
            self.inner.put(key, out)

        self._apply("put", key, lambda: self.inner.put(key, data),
                    torn_execute=torn)

    def put_if_absent(self, key: str, data) -> bool:
        return self._apply("put_if_absent", key,
                           lambda: self.inner.put_if_absent(key, data))

    def get(self, key: str) -> bytes:
        return self._apply("get", key, lambda: self.inner.get(key))

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        return self._apply("get_range", key,
                           lambda: self.inner.get_range(key, offset,
                                                        length))

    def exists(self, key: str) -> bool:
        return self._apply("exists", key, lambda: self.inner.exists(key))

    def delete(self, key: str) -> None:
        self._apply("delete", key, lambda: self.inner.delete(key))

    def size(self, key: str) -> int:
        return self._apply("size", key, lambda: self.inner.size(key))

    def list(self, prefix: str = "") -> Iterator[str]:
        # materialized so the fault decision covers the whole listing,
        # not just the first page pull; vanished keys are omitted (a
        # lost object stops appearing in bucket listings too)
        keys = self._apply("list", prefix,
                           lambda: list(self.inner.list(prefix)))
        with self._lock:
            gone = set(self._vanished)
        return iter([k for k in keys if k not in gone])

    # file transfer rides the byte path so the schedule applies to it
    # (bounded memory is irrelevant at chaos-test scale)
    def put_file(self, key: str, src) -> None:
        from pathlib import Path

        self.put(key, Path(src).read_bytes())

    def get_file(self, key: str, dst) -> int:
        import os
        from pathlib import Path

        data = self.get(key)
        dst = Path(dst)
        tmp = dst.parent / f".volsync.tmp.{os.getpid()}.{dst.name}"
        tmp.write_bytes(data)
        tmp.replace(dst)
        return len(data)


def maybe_wrap(store, *, seed: Optional[int] = None,
               spec: Optional[str] = None):
    """Wrap ``store`` in a FaultStore when armed (explicitly or via
    VOLSYNC_FAULT_SEED / VOLSYNC_FAULT_SPEC); otherwise return it
    unchanged. The arming path tests, bench.py --faults, and the CLI
    all share."""
    if seed is None:
        seed = envflags.fault_seed()
    if seed is None:
        return store
    if spec is None:
        spec = envflags.fault_spec()
    specs = parse_spec(spec) if spec else default_specs()
    return FaultStore(store, FaultSchedule(seed=seed, specs=specs))
