"""OpenStack Swift client (Keystone v3 / v1 auth, stdlib-only).

The reference's restic mover passes the Swift credential families
straight through to its engine (controllers/mover/restic/mover.go:
331-363; repository URLs of the form ``swift:container:/path``). This
is the wire-correct equivalent over Swift's object API:

- auth: Keystone v3 password auth (``POST /v3/auth/tokens``, token from
  the ``X-Subject-Token`` header, storage URL from the service
  catalog's object-store endpoint, filtered by OS_REGION_NAME), legacy
  v1 auth (``ST_AUTH``/``ST_USER``/``ST_KEY``), or a pre-authenticated
  ``OS_STORAGE_URL``/``OS_AUTH_TOKEN`` pair — the same three families
  restic accepts;
- objects: PUT / conditional PUT (``If-None-Match: *``) / GET /
  Range-GET / HEAD / DELETE and container LIST with marker pagination;
- a 401 mid-run re-authenticates once and retries (token expiry).

The auth request/response shapes are shared with the in-process
verifying fake (objstore/fakeswift.py), so an auth-protocol bug cannot
hide — the same pattern as the Azure SharedKey and S3 SigV4 pairs.
"""

from __future__ import annotations

import http.client
import json
import threading
from typing import Iterator, Optional
from urllib.parse import quote, urlsplit

from volsync_tpu.analysis import lockcheck
from volsync_tpu.objstore.store import NoSuchKey, _check_key
from volsync_tpu.resilience import RetryPolicy

_SAFE = "-_.~/"


class SwiftError(RuntimeError):
    def __init__(self, status: int, body: bytes = b""):
        super().__init__(f"HTTP {status}: {body[:200]!r}")
        self.status = status


def keystone_v3_payload(username: str, password: str, project: str,
                        user_domain: str, project_domain: str) -> dict:
    """The Keystone v3 password-auth body — one builder shared with the
    fake so request shape and verification can never drift."""
    return {
        "auth": {
            "identity": {
                "methods": ["password"],
                "password": {
                    "user": {
                        "name": username,
                        "domain": {"name": user_domain},
                        "password": password,
                    }
                },
            },
            "scope": {
                "project": {
                    "name": project,
                    "domain": {"name": project_domain},
                }
            },
        }
    }


def catalog_object_store_url(catalog: list, region: str) -> Optional[str]:
    """Pick the public object-store endpoint from a Keystone v3 service
    catalog, honoring OS_REGION_NAME when set (restic's swift backend
    resolves its storage URL the same way)."""
    for svc in catalog:
        if svc.get("type") != "object-store":
            continue
        for ep in svc.get("endpoints", []):
            if ep.get("interface", "public") != "public":
                continue
            if region and ep.get("region") not in (region, None):
                continue
            url = ep.get("url")
            if url:
                return url
    return None


class _HttpPool:
    """One keep-alive connection per (thread, netloc)."""

    def __init__(self):
        self._local = threading.local()

    def conn(self, scheme: str, netloc: str) -> http.client.HTTPConnection:
        cur = getattr(self._local, "conn", None)
        if cur is None or getattr(self._local, "netloc", None) != netloc:
            c = (http.client.HTTPSConnection if scheme == "https"
                 else http.client.HTTPConnection)
            cur = self._local.conn = c(netloc, timeout=60)
            self._local.netloc = netloc
        return cur

    def reset(self):
        self._local.conn = None


class SwiftObjectStore:
    """ObjectStore over one Swift container + key prefix."""

    def __init__(self, container: str, prefix: str = "", *,
                 auth_url: str = "", username: str = "", password: str = "",
                 project: str = "", user_domain: str = "Default",
                 project_domain: str = "Default", region: str = "",
                 v1_auth_url: str = "", v1_user: str = "", v1_key: str = "",
                 storage_url: str = "", auth_token: str = ""):
        self.container = container
        self.prefix = prefix.strip("/")
        self.auth_url = auth_url.rstrip("/")
        self.username = username
        self.password = password
        self.project = project
        self.user_domain = user_domain
        self.project_domain = project_domain
        self.region = region
        self.v1_auth_url = v1_auth_url
        self.v1_user = v1_user
        self.v1_key = v1_key
        self._pool = _HttpPool()
        self._auth_lock = lockcheck.make_lock("objstore.swift.auth")
        # Transport-level policy: one reconnect on a stale keep-alive
        # socket (the old did_reconn budget); op-level retry layers on
        # in ResilientStore via open_store().
        self._transport_policy = RetryPolicy.from_env(
            "objstore.swift.transport", max_attempts=2, deadline=None,
            base_delay=0.02, max_delay=0.25)
        # Pre-authenticated pair (OS_STORAGE_URL/OS_AUTH_TOKEN) skips
        # the auth round trip entirely; an empty token forces auth on
        # first use.
        self._storage_url = storage_url.rstrip("/")
        self._token = auth_token

    #: Keystone credential families restic accepts but this backend's
    #: built-in v3 client does not implement. Named explicitly in the
    #: from_url error so an operator whose Secret uses application
    #: credentials is not told "OS_USERNAME missing".
    UNSUPPORTED_AUTH_KEYS = (
        "OS_APPLICATION_CREDENTIAL_ID",
        "OS_APPLICATION_CREDENTIAL_NAME",
        "OS_APPLICATION_CREDENTIAL_SECRET",
        "OS_USER_ID",
        "OS_TENANT_ID",
        "OS_PROJECT_ID",
        "OS_USER_DOMAIN_ID",
        "OS_PROJECT_DOMAIN_ID",
        "OS_TRUST_ID",
    )

    @classmethod
    def from_url(cls, url: str, env: dict) -> "SwiftObjectStore":
        """``swift:container:/path`` (restic's URL form) with the OS_* /
        ST_* env families (restic/mover.go:331-363 passthrough).
        ``swift-temp:`` is accepted as an alias of ``swift:`` — it is a
        volsync-tpu convenience for temp-auth deployments, NOT a restic
        location scheme."""
        scheme = "swift-temp" if url.startswith("swift-temp:") else "swift"
        rest = url[len(scheme) + 1:]
        container, _, prefix = rest.partition(":")
        container = container.strip("/")
        if not container:
            raise ValueError(f"swift URL {url!r} has no container")
        storage_url = env.get("OS_STORAGE_URL", "")
        token = env.get("OS_AUTH_TOKEN", "")
        auth_url = env.get("OS_AUTH_URL", "")
        v1_auth = env.get("ST_AUTH", "")
        if not (storage_url and token) and not auth_url and not v1_auth:
            raise ValueError(
                "swift: repository needs credentials in the repository "
                "Secret: either OS_AUTH_URL + OS_USERNAME + OS_PASSWORD "
                "+ OS_PROJECT_NAME (Keystone v3), ST_AUTH + ST_USER + "
                "ST_KEY (v1 auth), or a pre-authenticated OS_STORAGE_URL "
                "+ OS_AUTH_TOKEN pair (restic/mover.go:331-363 "
                "passthrough)")
        if auth_url and not (storage_url and token):
            missing = [k for k in ("OS_USERNAME", "OS_PASSWORD",
                                   "OS_PROJECT_NAME")
                       if not env.get(k, "")]
            if missing:
                unsupported = [k for k in cls.UNSUPPORTED_AUTH_KEYS
                               if env.get(k, "")]
                if unsupported:
                    raise ValueError(
                        "swift: the repository Secret uses Keystone "
                        "credential keys this backend does not support: "
                        f"{', '.join(unsupported)}. Only v3 "
                        "username/password auth (OS_AUTH_URL + OS_USERNAME "
                        "+ OS_PASSWORD + OS_PROJECT_NAME), v1 auth "
                        "(ST_AUTH + ST_USER + ST_KEY), or a "
                        "pre-authenticated OS_STORAGE_URL + OS_AUTH_TOKEN "
                        "pair are implemented — application credentials, "
                        "id-based scoping, and trusts are not (see "
                        "docs/usage/restic.md)")
                raise ValueError(
                    f"swift: OS_AUTH_URL is set but {', '.join(missing)} "
                    f"{'is' if len(missing) == 1 else 'are'} missing "
                    "from the repository Secret")
        if v1_auth and not (storage_url and token) and not auth_url:
            missing = [k for k in ("ST_USER", "ST_KEY")
                       if not env.get(k, "")]
            if missing:
                raise ValueError(
                    f"swift: ST_AUTH is set but {', '.join(missing)} "
                    f"{'is' if len(missing) == 1 else 'are'} missing "
                    "from the repository Secret")
        return cls(
            container, prefix.lstrip("/"),
            auth_url=auth_url,
            username=env.get("OS_USERNAME", ""),
            password=env.get("OS_PASSWORD", ""),
            project=env.get("OS_PROJECT_NAME",
                            env.get("OS_TENANT_NAME", "")),
            user_domain=env.get("OS_USER_DOMAIN_NAME", "Default"),
            project_domain=env.get("OS_PROJECT_DOMAIN_NAME", "Default"),
            region=env.get("OS_REGION_NAME", ""),
            v1_auth_url=v1_auth,
            v1_user=env.get("ST_USER", ""),
            v1_key=env.get("ST_KEY", ""),
            storage_url=storage_url,
            auth_token=token,
        )

    # -- auth ---------------------------------------------------------------

    def _authenticate(self) -> None:
        """(Re)acquire token + storage URL via whichever family is
        configured. Called under _auth_lock."""
        if self.auth_url:
            self._auth_keystone_v3()
        elif self.v1_auth_url:
            self._auth_v1()
        else:
            raise SwiftError(401, b"static OS_AUTH_TOKEN rejected and no "
                                  b"auth family configured to refresh it")

    def _auth_keystone_v3(self) -> None:
        u = urlsplit(self.auth_url)
        conn = self._pool.conn(u.scheme or "http", u.netloc)
        body = json.dumps(keystone_v3_payload(
            self.username, self.password, self.project,
            self.user_domain, self.project_domain)).encode()
        path = (u.path.rstrip("/") or "") + "/auth/tokens"
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status not in (200, 201):
            raise SwiftError(resp.status, data)
        token = resp.getheader("X-Subject-Token", "")
        if not token:
            raise SwiftError(resp.status, b"no X-Subject-Token in reply")
        catalog = json.loads(data).get("token", {}).get("catalog", [])
        storage = catalog_object_store_url(catalog, self.region)
        if not storage:
            raise SwiftError(
                500, b"no public object-store endpoint in the Keystone "
                     b"catalog" + (f" for region {self.region!r}"
                                   .encode() if self.region else b""))
        self._token = token
        self._storage_url = storage.rstrip("/")

    def _auth_v1(self) -> None:
        u = urlsplit(self.v1_auth_url)
        conn = self._pool.conn(u.scheme or "http", u.netloc)
        conn.request("GET", u.path or "/", headers={
            "X-Auth-User": self.v1_user, "X-Auth-Key": self.v1_key})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status not in (200, 204):
            raise SwiftError(resp.status, data)
        token = resp.getheader("X-Auth-Token", "")
        storage = resp.getheader("X-Storage-Url", "")
        if not token or not storage:
            raise SwiftError(resp.status,
                             b"v1 auth reply missing token/storage URL")
        self._token = token
        self._storage_url = storage.rstrip("/")

    # -- request core -------------------------------------------------------

    def _obj_path(self, base_path: str, key: str = "") -> str:
        parts = [base_path.rstrip("/"), quote(self.container, safe=_SAFE)]
        full = "/".join(p for p in (self.prefix, key) if p)
        if full:
            parts.append(quote(full, safe=_SAFE))
        return "/".join(parts)

    def _request(self, method: str, key: str = "", *, query: str = "",
                 body: bytes = b"", headers: Optional[dict] = None,
                 container_only: bool = False) -> tuple[int, bytes, dict]:
        # Two independent one-shot budgets for the transient failures a
        # long-idle store hits TOGETHER (stale keep-alive socket AND
        # expired token — e.g. an hourly backup with a 30-min token):
        # the transport policy allows one connection rebuild per probe,
        # and the outer loop allows one re-auth per logical request.
        def one_attempt() -> tuple[int, bytes, dict, str]:
            # reviewed: the auth HTTP round-trip runs under
            # objstore.swift.auth ON PURPOSE — it serializes re-auth so
            # N worker threads hitting an expired token produce one
            # Keystone request instead of a stampede; workers that lose
            # the race block briefly and reuse the fresh token.
            # lint: ignore[VL101]
            with self._auth_lock:
                if not self._token or not self._storage_url:
                    self._authenticate()
                token, storage = self._token, self._storage_url
            u = urlsplit(storage)
            conn = self._pool.conn(u.scheme or "http", u.netloc)
            path = (u.path.rstrip("/") + "/"
                    + quote(self.container, safe=_SAFE)
                    if container_only else self._obj_path(u.path, key))
            hdrs = dict(headers or {})
            hdrs["X-Auth-Token"] = token
            try:
                conn.request(method, path + (f"?{query}" if query else ""),
                             body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                # stale keep-alive: drop it so the retry dials fresh
                self._pool.reset()
                raise
            return resp.status, data, dict(resp.getheaders()), token

        did_reauth = False
        while True:
            status, data, hdrs, token = self._transport_policy.call(
                one_attempt)
            if status == 401 and not did_reauth:
                # expired token: re-auth once and retry (restic's swift
                # library does the same transparently)
                did_reauth = True
                with self._auth_lock:
                    if self._token == token:
                        self._token = ""
                continue
            return status, data, hdrs

    # -- ObjectStore protocol ----------------------------------------------

    def put(self, key: str, data) -> None:
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        st, body, _ = self._request("PUT", key, body=body_bytes(data))
        if st not in (200, 201):
            raise SwiftError(st, body)

    def put_if_absent(self, key: str, data) -> bool:
        from volsync_tpu.objstore.store import body_bytes

        _check_key(key)
        st, body, _ = self._request("PUT", key, body=body_bytes(data),
                                    headers={"If-None-Match": "*"})
        if st in (200, 201):
            return True
        if st == 412:  # precondition failed: object exists
            return False
        raise SwiftError(st, body)

    def get(self, key: str) -> bytes:
        _check_key(key)
        st, body, _ = self._request("GET", key)
        if st == 404:
            raise NoSuchKey(key)
        if st != 200:
            raise SwiftError(st, body)
        return body

    def get_range(self, key: str, offset: int, length: int) -> bytes:
        _check_key(key)
        if length <= 0:
            return b""
        st, body, _ = self._request(
            "GET", key,
            headers={"Range": f"bytes={offset}-{offset + length - 1}"})
        if st == 404:
            raise NoSuchKey(key)
        if st == 200:
            # proxy/middlebox ignored the Range header and sent the
            # whole object: slice locally (same recovery as the S3
            # backend)
            return body[offset:offset + length]
        if st != 206:
            raise SwiftError(st, body)
        return body

    def exists(self, key: str) -> bool:
        _check_key(key)
        st, _, _ = self._request("HEAD", key)
        if st in (200, 204):
            return True
        if st == 404:
            return False
        raise SwiftError(st)

    def size(self, key: str) -> int:
        _check_key(key)
        st, _, hdrs = self._request("HEAD", key)
        if st == 404:
            raise NoSuchKey(key)
        if st not in (200, 204):
            raise SwiftError(st)
        return int(hdrs.get("Content-Length", "0"))

    def delete(self, key: str) -> None:
        _check_key(key)
        st, body, _ = self._request("DELETE", key)
        if st not in (200, 204, 404):
            raise SwiftError(st, body)

    def list(self, prefix: str = "") -> Iterator[str]:
        # Always keep the "/" after a store prefix (the S3 backend's
        # form): joining without it makes list("") match sibling
        # containers of the prefix ("repo" bleeding "repo-other/...")
        # and mis-strip their keys by prefix-length+1.
        full = f"{self.prefix}/{prefix}" if self.prefix else prefix
        strip = len(self.prefix) + 1 if self.prefix else 0
        marker = ""
        while True:
            qs = "format=plain"
            if full:
                qs += f"&prefix={quote(full, safe='')}"
            if marker:
                qs += f"&marker={quote(marker, safe='')}"
            st, body, _ = self._request("GET", query=qs,
                                        container_only=True)
            if st == 204 or (st == 200 and not body.strip()):
                return
            if st != 200:
                raise SwiftError(st, body)
            names = body.decode("utf-8").splitlines()
            if not names:
                return
            for name in names:
                yield name[strip:]
            marker = names[-1]
