"""One home for boolean env-knob parsing.

Every operational toggle (VOLSYNC_DEVICE_VERIFY, VOLSYNC_SPARSE,
VOLSYNC_BATCH_SEGMENTS, ...) parses through here so the falsy-token
set cannot drift between copies — "off" disabling one knob but
enabling another is exactly the bug class this prevents.
"""

from __future__ import annotations

import os

_FALSY = ("", "0", "false", "no", "off")


def env_bool(name: str, default: bool = False) -> bool:
    """True/False from the environment; unset -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY
