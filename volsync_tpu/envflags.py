"""One home for env-knob parsing.

Every operational toggle (VOLSYNC_DEVICE_VERIFY, VOLSYNC_SPARSE,
VOLSYNC_BATCH_SEGMENTS, ...) parses through here so the falsy-token
set cannot drift between copies — "off" disabling one knob but
enabling another is exactly the bug class this prevents. The backup
pipeline's depth/worker knobs (VOLSYNC_TPU_PIPELINE and friends) live
here too, as the single catalogue of operator-facing tunables.
"""

from __future__ import annotations

import os
from typing import Optional

_FALSY = ("", "0", "false", "no", "off")


def env_bool(name: str, default: bool = False) -> bool:
    """True/False from the environment; unset -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY


def env_int(name: str, default: int, minimum: int = 0) -> int:
    """Integer knob; unset/unparsable -> ``default``, floored at
    ``minimum`` (a malformed operator value degrades to the default
    instead of crashing the mover mid-sync)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return max(minimum, int(raw.strip()))
    except ValueError:
        return default


def env_float(name: str, default: float, minimum: float = 0.0) -> float:
    """Float knob with the same degrade-to-default contract as
    env_int."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return max(minimum, float(raw.strip()))
    except ValueError:
        return default


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw string knob; empty string counts as unset (an operator
    clearing a knob with ``VAR=`` means "off", never "the empty
    path")."""
    raw = os.environ.get(name)
    if not raw:
        return default
    return raw


# -- backup data-plane pipeline knobs (repo/repository.py, engine/chunker.py)

def pipeline_enabled() -> bool:
    """Master switch for the pipelined backup data plane.
    ``VOLSYNC_TPU_PIPELINE=0`` falls back to the fully serial path."""
    return env_bool("VOLSYNC_TPU_PIPELINE", True)


def seal_workers() -> int:
    """Worker threads for async pack sealing (zstd+AES are pure CPU and
    release the GIL inside zstd)."""
    return env_int("VOLSYNC_TPU_SEAL_WORKERS", 2, minimum=1)


def seal_queue_limit() -> int:
    """Max blobs queued for sealing per repository before add_blob
    blocks — the backpressure bound on raw bytes held by the seal
    stage."""
    return env_int("VOLSYNC_TPU_SEAL_QUEUE", 16, minimum=1)


def upload_window() -> int:
    """Max sealed packs in flight to the object store per repository."""
    return env_int("VOLSYNC_TPU_UPLOAD_WINDOW", 4, minimum=1)


def upload_retries() -> int:
    """Retries (with exponential backoff) per failed pack upload before
    the error surfaces on the caller."""
    return env_int("VOLSYNC_TPU_UPLOAD_RETRIES", 2, minimum=0)


def readahead_segments() -> int:
    """Segments prefetched ahead of the device stage by stream_chunks'
    read-ahead thread; 0 disables the thread (inline reads)."""
    if not pipeline_enabled():
        return 0
    return env_int("VOLSYNC_TPU_READAHEAD", 2, minimum=0)


# -- cross-stream segment microbatching knobs (ops/batcher.py) -----------

def batch_segments_override() -> Optional[bool]:
    """VOLSYNC_BATCH_SEGMENTS tri-state: None when unset (callers fall
    back to the backend-aware default), else the forced bool."""
    if os.environ.get("VOLSYNC_BATCH_SEGMENTS") is None:
        return None
    return env_bool("VOLSYNC_BATCH_SEGMENTS")


def batch_max() -> int:
    """Max segments coalesced into one batched device dispatch."""
    return env_int("VOLSYNC_BATCH_MAX", 16, minimum=1)


def batch_window_ms() -> float:
    """How long (ms) the first segment of a batch waits for
    companions."""
    return env_float("VOLSYNC_BATCH_WINDOW_MS", 2.0, minimum=0.0)


def batch_pipeline_depth() -> int:
    """Batched dispatches in flight per microbatcher (ops/batcher.py
    and the gRPC server's per-process batcher share this knob)."""
    return env_int("VOLSYNC_BATCH_PIPELINE", 2, minimum=1)


# -- device kernel knobs (ops/) ------------------------------------------

def root_unroll() -> int:
    """SHA-256 root-loop unroll factor (ops/segment.py). Read at TRACE
    time and not part of any jit cache key — profiling runs must set it
    before the first compile of a shape. Clamped >= 1: U=0 would make
    the loop body a no-op that never advances n (device hang)."""
    return env_int("VOLSYNC_ROOT_UNROLL", 4, minimum=1)


def no_pallas() -> bool:
    """VOLSYNC_NO_PALLAS=1 forces the XLA scan everywhere — the
    operational kill-switch for toolchains without Mosaic support."""
    return env_bool("VOLSYNC_NO_PALLAS")


def donate_device_inputs() -> Optional[bool]:
    """VOLSYNC_DONATE tri-state: None when unset — callers fall back to
    the backend-aware default (donate staged segment buffers into the
    batched chunk-hash dispatch on TPU, where XLA reuses the donated
    HBM; skip on CPU, where donation is ignored with a warning) — else
    the forced bool."""
    if os.environ.get("VOLSYNC_DONATE") is None:
        return None
    return env_bool("VOLSYNC_DONATE")


# -- engine worker knobs (engine/backup.py, engine/restore.py) -----------

def backup_workers() -> int:
    """Concurrent per-file hashing workers for TreeBackup."""
    return env_int("VOLSYNC_BACKUP_WORKERS", 4, minimum=1)


def restore_workers() -> int:
    """Concurrent per-file restore workers for TreeRestore."""
    return env_int("VOLSYNC_RESTORE_WORKERS", 4, minimum=1)


# -- restore data plane (engine/restorepipe.py, repo/packcache.py) -------

def restore_pipeline_enabled() -> bool:
    """Master switch for the pipelined restore data plane
    (pack-granular fetches + device-batched verify).
    ``VOLSYNC_RESTORE_PIPELINE=0`` falls back to the serial per-blob
    path — the byte-identity golden oracle."""
    return env_bool("VOLSYNC_RESTORE_PIPELINE", True)


def restore_cache_mb() -> int:
    """VOLSYNC_RESTORE_CACHE_MB: byte budget (MiB) of the shared
    PackCache LRU in front of the object store. Concurrent restores
    sharing one cache fetch each pack once (single-flight) and evict
    oldest-first past this budget."""
    return env_int("VOLSYNC_RESTORE_CACHE_MB", 256, minimum=1)


def restore_fetchers() -> int:
    """VOLSYNC_RESTORE_FETCHERS: worker threads in the restore
    pipeline's async pack-fetch pool (store GETs overlap decode,
    device verify, and file writes)."""
    return env_int("VOLSYNC_RESTORE_FETCHERS", 4, minimum=1)


def restore_fetch_window() -> int:
    """VOLSYNC_RESTORE_FETCH_WINDOW: max pack fetches submitted ahead
    of the consuming verify/write stage — the backpressure bound on
    fetched-but-unwritten pack bytes (window x PACK_TARGET)."""
    return env_int("VOLSYNC_RESTORE_FETCH_WINDOW", 8, minimum=1)


# -- metadata plane (repo/shardedindex.py) -------------------------------

def index_shards() -> int:
    """Shard count for the repository blob index (rounded up to a power
    of two by the index). Each shard has its own lock, so concurrent
    writers contend on ~1/S of the keyspace; 1 degenerates to the
    single-lock layout."""
    return env_int("VOLSYNC_INDEX_SHARDS", 16, minimum=1)


def index_prefilter() -> bool:
    """VOLSYNC_INDEX_PREFILTER=0 disables the blocked-bloom cold-miss
    prefilter in front of the index shards (first-backup workloads are
    nearly all misses; the filter answers "definitely absent" without a
    probe)."""
    return env_bool("VOLSYNC_INDEX_PREFILTER", True)


# -- multi-tenant service plane (service/admission.py, scheduler.py) -----

def svc_max_streams() -> int:
    """Global cap on concurrently admitted ChunkHash streams; the
    stream that would exceed it is shed at admission with
    RESOURCE_EXHAUSTED (never wedged mid-stream)."""
    return env_int("VOLSYNC_SVC_MAX_STREAMS", 64, minimum=1)


def svc_tenant_streams() -> int:
    """Default per-tenant concurrent-stream cap (a TenantConfig
    max_streams overrides it per tenant)."""
    return env_int("VOLSYNC_SVC_TENANT_STREAMS", 16, minimum=1)


def svc_max_queued() -> int:
    """Global cap on segments queued in the service scheduler; new
    streams are shed at admission while the backlog is at the cap."""
    return env_int("VOLSYNC_SVC_MAX_QUEUED", 256, minimum=1)


def svc_tenant_queued() -> int:
    """Default per-tenant bound on scheduler-queued segments — the
    credit pool behind the per-stream backpressure pause (a
    TenantConfig max_queued overrides it per tenant)."""
    return env_int("VOLSYNC_SVC_TENANT_QUEUED", 32, minimum=1)


def svc_stream_credits() -> int:
    """Segments' worth of request bytes one stream may buffer in the
    server beyond the segment in flight before the handler stops
    reading (gRPC flow control then pauses the sender)."""
    return env_int("VOLSYNC_SVC_STREAM_CREDITS", 2, minimum=1)


def svc_retry_after_ms() -> float:
    """Base retry-after hint (milliseconds) stamped on quota sheds;
    breaker sheds carry the breaker's remaining cooldown instead."""
    return env_float("VOLSYNC_SVC_RETRY_AFTER_MS", 100.0, minimum=1.0)


def svc_quantum() -> int:
    """Deficit-round-robin quantum in bytes credited to each backlogged
    tenant per scheduler round (multiplied by the tenant weight)."""
    return env_int("VOLSYNC_SVC_QUANTUM", 256 * 1024, minimum=1)


def svc_dispatch_window() -> int:
    """Max scheduler-dispatched segments outstanding in the
    microbatcher at once; 0 derives it from the batcher geometry
    (max_batch * pipeline_depth)."""
    return env_int("VOLSYNC_SVC_DISPATCH_WINDOW", 0, minimum=0)


def svc_drain_seconds() -> float:
    """How long stop() waits for in-flight streams to finish before
    aborting the stragglers with UNAVAILABLE."""
    return env_float("VOLSYNC_SVC_DRAIN_S", 10.0, minimum=0.0)


def svc_tenants_spec() -> Optional[str]:
    """VOLSYNC_SVC_TENANTS: per-tenant quota/weight spec, e.g.
    ``gold:weight=4,streams=8,queued=64;bronze:weight=1`` (see
    service/tenants.py parse rules); None = all tenants on defaults."""
    return env_str("VOLSYNC_SVC_TENANTS")


def svc_breaker_backend() -> Optional[str]:
    """VOLSYNC_SVC_BREAKER_BACKEND: name of the resilience circuit
    breaker the admission controller watches — while that breaker is
    open, new streams shed at admission with the remaining cooldown as
    the retry-after hint. None = no breaker wired."""
    return env_str("VOLSYNC_SVC_BREAKER_BACKEND")


def svc_deadline_spec() -> Optional[str]:
    """VOLSYNC_SVC_DEADLINES: deadline-class map for the segment
    scheduler, e.g. ``interactive=0.5,standard=5,background=none`` (see
    scheduler.parse_deadline_classes); None = built-in defaults."""
    return env_str("VOLSYNC_SVC_DEADLINES")


# -- fleet replica plane (service/fleet.py, service/gc.py) ---------------

def fleet_beat_seconds() -> float:
    """VOLSYNC_FLEET_BEAT_S: interval between a replica's heartbeat
    stamps into the shared object store (``fleet/<replica-id>``). The
    stamp carries headroom + backlog, so the beat is also how fast the
    router's routing picture refreshes."""
    return env_float("VOLSYNC_FLEET_BEAT_S", 2.0, minimum=0.1)


def fleet_ttl_seconds() -> float:
    """VOLSYNC_FLEET_TTL_S: heartbeat-stamp TTL — a replica whose stamp
    is older than this is presumed dead: the router stops routing to it
    and ``volsync repair`` may clear the stale stamp. Keep it a few
    beats wide so one slow put does not declare a live replica dead."""
    return env_float("VOLSYNC_FLEET_TTL_S", 10.0, minimum=0.5)


def gc_interval_seconds() -> float:
    """VOLSYNC_GC_INTERVAL_S: pause between continuous-GC prune cycles
    (service/gc.py). Each cycle is the two-phase mark-then-sweep prune;
    the interval bounds how much garbage accumulates between cycles."""
    return env_float("VOLSYNC_GC_INTERVAL_S", 60.0, minimum=0.1)


# -- silent-corruption defense (repo/scrub.py, repo/repository.py) -------

def pack_copies() -> int:
    """VOLSYNC_PACK_COPIES: replicas written for every sealed pack.
    1 (the default) keeps the classic single-copy layout; 2 additionally
    writes each pack to ``mirror/<pack-id>`` through the same resilient
    upload path, giving the scrub and restore read-repair a healthy body
    to heal from. Values above 2 clamp to 2 (one mirror prefix)."""
    return min(env_int("VOLSYNC_PACK_COPIES", 1, minimum=1), 2)


def scrub_interval_seconds() -> float:
    """VOLSYNC_SCRUB_INTERVAL_S: pause between continuous-scrub cycles
    (repo/scrub.py). Each cycle verifies a bounded slice of packs
    on-device, so the interval trades detection latency for read load
    on the store."""
    return env_float("VOLSYNC_SCRUB_INTERVAL_S", 60.0, minimum=0.1)


def scrub_packs_per_cycle() -> int:
    """VOLSYNC_SCRUB_PACKS: packs verified per scrub cycle, walked
    round-robin so every pack is eventually visited. 0 (the default)
    scrubs the whole repository each cycle — right for tests and the
    one-shot ``volsync scrub`` verb; fleets set a budget."""
    return env_int("VOLSYNC_SCRUB_PACKS", 0, minimum=0)


def scrub_read_repair_enabled() -> bool:
    """VOLSYNC_SCRUB_READ_REPAIR: when a pipelined restore's device
    verify catches a corrupt blob, re-fetch the owning pack's mirror,
    heal the primary (verify-then-replace) and keep restoring instead
    of raising IntegrityError immediately. Default on; restores of
    single-copy repositories are unaffected (no mirror -> classic
    failure path)."""
    return env_bool("VOLSYNC_SCRUB_READ_REPAIR", True)


def device_verify_enabled() -> bool:
    """VOLSYNC_DEVICE_VERIFY: check(read_data=True) verifies blob
    payloads with the batched on-device hash path (packs cross the wire
    once, ~64 MiB fused verify dispatches) instead of serial host-side
    hashing. Default on since the scrub rides the same kernels; set 0
    to force the pure-host reference path."""
    return env_bool("VOLSYNC_DEVICE_VERIFY", True)


# -- erasure coding + online repack (repo/erasure.py, repo/repack.py) ----

def ec_scheme() -> Optional[tuple]:
    """VOLSYNC_EC_SCHEME: ``k+m`` (e.g. ``4+2``) arms Reed-Solomon
    striping — sealed packs are written as k data + m parity shards
    under ``ec/<pack-id>/<shard-idx>`` instead of primary+mirror, so any
    m shard losses reconstruct at (k+m)/k storage. None (the default)
    keeps the classic layout; malformed or out-of-range specs degrade
    to None (a typo'd scheme must not silently change the durability
    story — the pack_copies mirror fallback still applies)."""
    raw = env_str("VOLSYNC_EC_SCHEME")
    if raw is None:
        return None
    parts = raw.strip().split("+")
    if len(parts) != 2:
        return None
    try:
        k, m = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if not (2 <= k <= 16 and 1 <= m <= 8):
        return None
    return (k, m)


def repack_dead_ratio() -> float:
    """VOLSYNC_REPACK_DEAD_RATIO: fraction of a pack's entries that must
    be dead (unreferenced by the index) before RepackService rewrites
    its live blobs into a fresh erasure-coded stripe. Clamped to
    [0.05, 1.0]: 0 would repack every pack every cycle."""
    v = env_float("VOLSYNC_REPACK_DEAD_RATIO", 0.3, minimum=0.05)
    return min(v, 1.0)


def repack_interval_seconds() -> float:
    """VOLSYNC_REPACK_INTERVAL_S: pause between continuous-repack cycles
    (repo/repack.py). Each cycle is one bounded pick-rewrite-retire pass
    under the shared prune lock rules."""
    return env_float("VOLSYNC_REPACK_INTERVAL_S", 60.0, minimum=0.1)


def repack_packs_per_cycle() -> int:
    """VOLSYNC_REPACK_PACKS: packs rewritten per repack cycle. 0 (the
    default) repacks every eligible pack each cycle — right for tests
    and the one-shot ``volsync repack`` verb; fleets set a budget."""
    return env_int("VOLSYNC_REPACK_PACKS", 0, minimum=0)


# -- observability (obs/tracing.py) --------------------------------------

def trace_dir() -> Optional[str]:
    """VOLSYNC_TRACE_DIR: where device_trace writes JAX profiler traces;
    None (the default) disables tracing."""
    return env_str("VOLSYNC_TRACE_DIR")


def trace_sample() -> float:
    """VOLSYNC_TRACE_SAMPLE: fraction of new root traces whose spans are
    recorded into the flight recorder (1.0 = every trace, 0 = flight
    recorder off; span totals + the stage histogram always record)."""
    return env_float("VOLSYNC_TRACE_SAMPLE", 1.0, minimum=0.0)


def trace_ring_size() -> int:
    """VOLSYNC_TRACE_RING: span events retained in the in-process
    flight-recorder ring buffer (oldest evicted first)."""
    return env_int("VOLSYNC_TRACE_RING", 4096, minimum=16)


def trace_dump_dir() -> Optional[str]:
    """VOLSYNC_TRACE_DUMP: directory where trigger events (shed,
    breaker-open, injected fault, deadline) auto-dump annotated
    Chrome-trace JSON files; None (the default) disables auto-dumps
    (the ring still records)."""
    return env_str("VOLSYNC_TRACE_DUMP")


def trace_trigger_interval() -> float:
    """VOLSYNC_TRACE_TRIGGER_INTERVAL_S: minimum seconds between
    auto-dumps for the SAME trigger reason, so a shed storm can't
    fill the dump dir."""
    return env_float("VOLSYNC_TRACE_TRIGGER_INTERVAL_S", 30.0, minimum=0.0)


# -- native accelerator (io/native.py) -----------------------------------

def no_native() -> bool:
    """VOLSYNC_NO_NATIVE=1 skips the native volio accelerator."""
    return env_bool("VOLSYNC_NO_NATIVE")


def volio_so() -> Optional[str]:
    """Path to a prebuilt libvolio.so (container images ship one)."""
    return env_str("VOLSYNC_VOLIO_SO")


def native_cache_dir() -> Optional[str]:
    """Build cache dir for the self-compiled native library."""
    return env_str("VOLSYNC_NATIVE_CACHE")


# -- repository store locking (repo/repository.py) -----------------------

def lock_stale_seconds() -> float:
    """VOLSYNC_LOCK_STALE_S: age after which another holder's repository
    lock object counts as a crashed process and is removed (default 30
    minutes — restic's staleness horizon). Operators shorten it when a
    known-dead holder would otherwise stall exclusive maintenance; the
    ``volsync_repo_lock_age_seconds`` gauge makes the wait visible."""
    return env_float("VOLSYNC_LOCK_STALE_S", 30.0 * 60.0, minimum=1.0)


def prune_grace_seconds() -> Optional[float]:
    """VOLSYNC_PRUNE_GRACE_S: grace a two-phase prune grants marked
    (pending-delete) victim packs before the sweep may delete them.
    Unset (the default) means "use the lock-staleness horizon", which
    guarantees any writer that could still dedup against a victim pack
    either shows a live lock (blocking the sweep) or has crashed. ``0``
    selects the classic stop-the-world prune: exclusive lock, victims
    swept in the same call."""
    raw = env_str("VOLSYNC_PRUNE_GRACE_S")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw.strip()))
    except ValueError:
        return None


# -- supervised accelerator sessions (cluster/sessions.py) ----------------

def session_ttl_seconds() -> float:
    """VOLSYNC_SESSION_TTL_S: hard lease TTL — a session whose keepalive
    has not succeeded for this long is recycled no matter what (the
    8-hour wedge of rounds 4/5 becomes a bounded outage)."""
    return env_float("VOLSYNC_SESSION_TTL_S", 900.0, minimum=1.0)


def session_keepalive_seconds() -> float:
    """VOLSYNC_SESSION_KEEPALIVE_S: interval between keepalive beats."""
    return env_float("VOLSYNC_SESSION_KEEPALIVE_S", 30.0, minimum=0.1)


def session_keepalive_failures() -> int:
    """VOLSYNC_SESSION_KEEPALIVE_FAILS: consecutive keepalive failures
    before the supervisor force-recycles the session."""
    return env_int("VOLSYNC_SESSION_KEEPALIVE_FAILS", 3, minimum=1)


def session_probe_timeout() -> float:
    """VOLSYNC_SESSION_PROBE_TIMEOUT_S: verify-probe budget; a probe
    that exceeds it counts as a wedged backend and triggers a recycle."""
    return env_float("VOLSYNC_SESSION_PROBE_TIMEOUT_S", 300.0, minimum=1.0)


def session_job_deadline() -> float:
    """VOLSYNC_SESSION_JOB_DEADLINE_S: per-job hard deadline in the
    serialized bench queue — a job is killed at this wall-clock bound,
    never allowed to hold the single-tenant device open-endedly."""
    return env_float("VOLSYNC_SESSION_JOB_DEADLINE_S", 1800.0, minimum=1.0)


def session_id() -> Optional[str]:
    """VOLSYNC_SESSION_ID: stamped into a job's environment by the
    session queue so bench provenance can carry the supervised-session
    identity; None when the process runs outside a session."""
    return env_str("VOLSYNC_SESSION_ID")


def session_epoch() -> int:
    """VOLSYNC_SESSION_EPOCH: the fencing epoch stamped alongside
    VOLSYNC_SESSION_ID (0 when unset)."""
    return env_int("VOLSYNC_SESSION_EPOCH", 0)


def session_backend() -> Optional[str]:
    """VOLSYNC_SESSION_BACKEND: backend name stamped alongside
    VOLSYNC_SESSION_ID."""
    return env_str("VOLSYNC_SESSION_BACKEND")


def session_status_path() -> Optional[str]:
    """VOLSYNC_SESSION_STATUS: file where the supervisor mirrors its
    state for observers (``volsync session status``); None = no mirror."""
    return env_str("VOLSYNC_SESSION_STATUS")


# -- sync-protocol planner knobs (engine/protoplan.py, syncstats.py) ------

def sync_protocol() -> str:
    """VOLSYNC_SYNC_PROTO: per-call override of the adaptive protocol
    planner — ``auto`` (cost model decides), ``full`` (whole-file copy),
    ``delta`` (rsync-style signature exchange), ``cdc`` (content-defined
    chunking + dedup). Unknown values degrade to ``auto`` (a typo'd
    override must not wedge a sync into a nonexistent protocol)."""
    raw = (env_str("VOLSYNC_SYNC_PROTO") or "auto").strip().lower()
    return raw if raw in ("auto", "full", "delta", "cdc") else "auto"


def plan_ewma_alpha() -> float:
    """VOLSYNC_PLAN_EWMA: smoothing factor for the SyncStatsBook's
    exponentially weighted moving averages (change rate, dedup ratio,
    link bandwidth/latency). Clamped to (0, 1]: 1.0 = last sample only."""
    v = env_float("VOLSYNC_PLAN_EWMA", 0.3, minimum=0.0)
    return min(max(v, 0.01), 1.0)


def delta_batch_files() -> int:
    """VOLSYNC_DELTA_BATCH: how many files the rsync source coalesces
    into one batched signature round trip + one device delta-scan
    dispatch ladder (engine/deltasync.delta_scan_batch); 1 = the serial
    per-file path."""
    return env_int("VOLSYNC_DELTA_BATCH", 32, minimum=1)


def plan_full_blob_cap() -> int:
    """VOLSYNC_PLAN_FULL_CAP: largest file (bytes) the planner may store
    as a single whole-file blob on the CDC side's FULL_COPY path; larger
    files always chunk (a monolithic blob past the segment bucket
    ceiling would blow pack sizing and device call shapes)."""
    return env_int("VOLSYNC_PLAN_FULL_CAP", 8 * 1024 * 1024, minimum=4096)


# -- resilience layer knobs (resilience.py) ------------------------------

def retry_attempts() -> int:
    """Total tries per resilient call (1 = no retry)."""
    return env_int("VOLSYNC_RETRY_ATTEMPTS", 4, minimum=1)


def retry_base_delay() -> float:
    """Backoff floor in seconds (VOLSYNC_RETRY_BASE_MS, milliseconds)."""
    return env_float("VOLSYNC_RETRY_BASE_MS", 50.0, minimum=1.0) / 1000.0


def retry_max_delay() -> float:
    """Backoff cap in seconds (VOLSYNC_RETRY_MAX_MS, milliseconds)."""
    return env_float("VOLSYNC_RETRY_MAX_MS", 5000.0, minimum=1.0) / 1000.0


def retry_deadline() -> Optional[float]:
    """Overall per-operation deadline in seconds
    (VOLSYNC_RETRY_DEADLINE_S); unset/0 = no deadline."""
    v = env_float("VOLSYNC_RETRY_DEADLINE_S", 0.0, minimum=0.0)
    return v or None


def breaker_threshold() -> int:
    """Consecutive retryable failures before a backend's circuit
    breaker opens."""
    return env_int("VOLSYNC_BREAKER_THRESHOLD", 5, minimum=1)


def breaker_reset_seconds() -> float:
    """Cooldown before an open breaker admits the half-open probe."""
    return env_float("VOLSYNC_BREAKER_RESET_S", 30.0, minimum=0.1)


def store_resilience_enabled() -> bool:
    """VOLSYNC_STORE_RESILIENCE=0 opts open_store() out of wrapping
    network backends in the shared retry/breaker layer."""
    return env_bool("VOLSYNC_STORE_RESILIENCE", True)


# -- deterministic fault injection (objstore/faultstore.py) ---------------

def fault_seed() -> Optional[int]:
    """VOLSYNC_FAULT_SEED arms the deterministic fault-injection store
    wrapper for stores opened via open_store(); None = disarmed."""
    raw = env_str("VOLSYNC_FAULT_SEED")
    if raw is None:
        return None
    try:
        return int(raw.strip())
    except ValueError:
        # Never disarm silently: a typo'd seed would let a "chaos" run
        # report a clean pass while injecting nothing.
        raise ValueError(
            f"VOLSYNC_FAULT_SEED={raw!r} is not an integer; fix or "
            "unset it (refusing to run with fault injection silently "
            "disarmed)") from None


def fault_spec() -> Optional[str]:
    """VOLSYNC_FAULT_SPEC: fault-schedule spec string (see
    objstore/faultstore.py parse_spec); None with a seed set means the
    default transient-heavy profile."""
    return env_str("VOLSYNC_FAULT_SPEC")


# -- debug/verification toggles (analysis/lockcheck.py) ------------------

def lockcheck_enabled() -> bool:
    """VOLSYNC_TPU_LOCKCHECK=1 swaps the data-plane locks for
    instrumented wrappers that record the per-thread lock-acquisition
    graph, fail fast on lock-order cycles (potential deadlock), and
    back the assert_held guards on pipeline shared state. Debug/test
    only — never on by default (every acquire pays a bookkeeping
    step)."""
    return env_bool("VOLSYNC_TPU_LOCKCHECK")
