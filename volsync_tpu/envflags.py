"""One home for env-knob parsing.

Every operational toggle (VOLSYNC_DEVICE_VERIFY, VOLSYNC_SPARSE,
VOLSYNC_BATCH_SEGMENTS, ...) parses through here so the falsy-token
set cannot drift between copies — "off" disabling one knob but
enabling another is exactly the bug class this prevents. The backup
pipeline's depth/worker knobs (VOLSYNC_TPU_PIPELINE and friends) live
here too, as the single catalogue of operator-facing tunables.
"""

from __future__ import annotations

import os

_FALSY = ("", "0", "false", "no", "off")


def env_bool(name: str, default: bool = False) -> bool:
    """True/False from the environment; unset -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY


def env_int(name: str, default: int, minimum: int = 0) -> int:
    """Integer knob; unset/unparsable -> ``default``, floored at
    ``minimum`` (a malformed operator value degrades to the default
    instead of crashing the mover mid-sync)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return max(minimum, int(raw.strip()))
    except ValueError:
        return default


# -- backup data-plane pipeline knobs (repo/repository.py, engine/chunker.py)

def pipeline_enabled() -> bool:
    """Master switch for the pipelined backup data plane.
    ``VOLSYNC_TPU_PIPELINE=0`` falls back to the fully serial path."""
    return env_bool("VOLSYNC_TPU_PIPELINE", True)


def seal_workers() -> int:
    """Worker threads for async pack sealing (zstd+AES are pure CPU and
    release the GIL inside zstd)."""
    return env_int("VOLSYNC_TPU_SEAL_WORKERS", 2, minimum=1)


def seal_queue_limit() -> int:
    """Max blobs queued for sealing per repository before add_blob
    blocks — the backpressure bound on raw bytes held by the seal
    stage."""
    return env_int("VOLSYNC_TPU_SEAL_QUEUE", 16, minimum=1)


def upload_window() -> int:
    """Max sealed packs in flight to the object store per repository."""
    return env_int("VOLSYNC_TPU_UPLOAD_WINDOW", 4, minimum=1)


def upload_retries() -> int:
    """Retries (with exponential backoff) per failed pack upload before
    the error surfaces on the caller."""
    return env_int("VOLSYNC_TPU_UPLOAD_RETRIES", 2, minimum=0)


def readahead_segments() -> int:
    """Segments prefetched ahead of the device stage by stream_chunks'
    read-ahead thread; 0 disables the thread (inline reads)."""
    if not pipeline_enabled():
        return 0
    return env_int("VOLSYNC_TPU_READAHEAD", 2, minimum=0)
