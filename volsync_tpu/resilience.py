"""Unified resilience layer: retry policy, circuit breakers, resilient
object-store wrapper.

"Reexamining Paradigms of End-to-End Data Movement" (PAPERS.md) argues
that transfer stacks need failure semantics designed as a LAYER, not
re-invented per call site. Before this module the reproduction had a
scatter of ad-hoc loops (a one-shot reconnect in ``objstore/s3.py``, a
hand-rolled exponential sleep in the pack-upload worker, bespoke
backoff in the lock refresh and the mirror-lease re-stamp). They all
route through here now, and lint rule VL105 (analysis/rules.py) keeps
it that way: a ``time.sleep`` inside an except handler or retry loop
anywhere else in the tree is a finding.

Three pieces:

- **Error classification** — ``classify(exc)`` maps an exception to
  retryable/fatal. Transient transport failures (ConnectionError,
  http.client exceptions, timeouts, gRPC UNAVAILABLE-class codes) and
  HTTP statuses 408/429/5xx are retryable; everything else — including
  NoSuchKey, auth failures and 4xx — is fatal. Backends can also raise
  ``TransientError``/``ThrottleError`` to opt a failure in explicitly.
- **RetryPolicy** — attempts bound, exponential backoff with
  DECORRELATED jitter (AWS architecture-blog variant: each sleep is
  drawn from ``[base, prev*3]`` capped — contenders desynchronize
  instead of re-colliding in lock-step), an overall deadline, and a
  per-call timeout hint threaded to callables that accept one. Every
  attempt increments ``volsync_retry_attempts_total{site,outcome}``
  and backoff waits are visible as ``resilience.backoff`` spans.
- **CircuitBreaker** — classic closed -> open -> half-open per backend,
  envflags-tunable (VOLSYNC_BREAKER_THRESHOLD / _RESET_S). While open,
  calls fail fast with ``CircuitOpen`` (retryable by classification:
  the caller's policy waits out the cooldown instead of hammering a
  dead endpoint). State is exported as
  ``volsync_breaker_state{backend}`` and transitions as a counter.

``ResilientStore`` composes both over any ObjectStore — the layer the
chaos soak (tests/test_chaos.py) drives against seeded fault schedules
(objstore/faultstore.py).
"""

from __future__ import annotations

import http.client
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from volsync_tpu import envflags
from volsync_tpu.analysis import lockcheck
from volsync_tpu.metrics import GLOBAL as GLOBAL_METRICS
from volsync_tpu.obs import record_trigger, span

log = logging.getLogger("volsync_tpu.resilience")

#: HTTP statuses worth retrying: request-timeout, throttle, and the
#: transient 5xx family. 501/505 are permanent and excluded on purpose.
RETRYABLE_HTTP = frozenset({408, 429, 500, 502, 503, 504})

#: gRPC status-code NAMES worth retrying (names, not the enum, so this
#: module never imports grpc). UNAUTHENTICATED/NOT_FOUND etc. are fatal.
RETRYABLE_GRPC = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED",
                            "RESOURCE_EXHAUSTED", "ABORTED"})


class TransientError(RuntimeError):
    """Base for failures a backend knows to be retryable (fault
    injection raises these too)."""


class ThrottleError(TransientError):
    """Server-side throttle (429/503 Slow Down analogue)."""


class CircuitOpen(TransientError):
    """The backend's breaker is open; fail fast instead of calling."""

    def __init__(self, backend: str, remaining: float):
        super().__init__(
            f"circuit breaker for {backend!r} is open "
            f"({remaining:.1f}s until half-open probe)")
        self.backend = backend
        self.remaining = remaining


class DeadlineExceeded(RuntimeError):
    """The policy's overall deadline expired; carries the last error."""

    def __init__(self, site: str, elapsed: float, last: Exception):
        super().__init__(
            f"{site}: deadline exceeded after {elapsed:.1f}s: {last}")
        self.last = last


def classify(exc: BaseException) -> bool:
    """True when ``exc`` is worth retrying.

    Duck-typed on purpose: backend error classes (S3Error, SwiftError,
    AzureError) carry ``.status``; grpc.RpcError carries ``.code()``.
    Classifying by shape keeps this module free of backend imports (the
    backends import *us*).
    """
    if isinstance(exc, TransientError):
        return True
    # NoSuchKey is a KeyError; any lookup miss is a fact, not a fault.
    if isinstance(exc, (KeyError, ValueError, TypeError)):
        return False
    status = getattr(exc, "status", None)
    if isinstance(status, int):
        return status in RETRYABLE_HTTP
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            name = getattr(code(), "name", None)
        except Exception:  # noqa: BLE001 — a broken .code() is unclassifiable
            name = None
        if isinstance(name, str):
            return name in RETRYABLE_GRPC
    if isinstance(exc, (http.client.HTTPException, ConnectionError,
                        TimeoutError, InterruptedError)):
        return True
    # Remaining OSErrors: transport-level (reset sockets, EPIPE under a
    # NAT timeout...). FileNotFoundError/PermissionError etc. are
    # subclasses handled above only if they match; treat explicit
    # filesystem misses as fatal, the rest of OSError as transient.
    if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError)):
        return False
    return isinstance(exc, OSError)


def decorrelated_jitter(prev: float, base: float, cap: float,
                        rng: Optional[random.Random] = None) -> float:
    """Next backoff sleep (AWS decorrelated-jitter):
    ``min(cap, uniform(base, prev * 3))``. Two contenders started in
    lock-step (same cron tick on two hosts) desynchronize instead of
    re-colliding every round — the randomized-contender semantics the
    repository lock always had, now shared."""
    r = rng if rng is not None else random
    return min(cap, r.uniform(base, max(base, prev * 3)))


@dataclass(frozen=True)
class Attempt:
    """One attempt handed out by RetryPolicy.attempts()."""

    number: int        # 1-based
    elapsed: float     # seconds since the first attempt started
    timeout: Optional[float]  # per-call timeout hint (policy.call_timeout)


@dataclass
class RetryPolicy:
    """Classified retry with decorrelated-jitter backoff and deadlines.

    ``site`` labels metrics/log lines. ``max_attempts`` counts total
    tries (1 = no retry). ``deadline`` bounds the WHOLE operation: no
    new attempt starts once it has passed (a transfer stack that
    retries past its sync interval just converts one failure into two).
    ``call_timeout`` is a hint threaded to each attempt for callables
    that take a ``timeout=`` kwarg. ``retryable``/``fatal`` extend the
    default classifier; ``classify_fn`` replaces it. ``sleep_fn``/
    ``rng`` are injection points so tests and the deterministic fault
    harness can run without wall-clock sleeps.
    """

    site: str = "default"
    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 5.0
    deadline: Optional[float] = None       # overall seconds budget
    call_timeout: Optional[float] = None   # per-attempt hint
    retryable: tuple = ()
    fatal: tuple = ()
    classify_fn: Optional[Callable[[BaseException], bool]] = None
    sleep_fn: Callable[[float], None] = time.sleep
    rng: Optional[random.Random] = None
    breaker: Optional["CircuitBreaker"] = None
    #: attempts observed by the last call() — tests/metrics introspection
    last_attempts: int = field(default=0, compare=False)

    @classmethod
    def from_env(cls, site: str, **overrides) -> "RetryPolicy":
        """Policy with the envflags-tunable defaults
        (VOLSYNC_RETRY_ATTEMPTS / _BASE_MS / _MAX_MS / _DEADLINE_S)."""
        base = dict(
            max_attempts=envflags.retry_attempts(),
            base_delay=envflags.retry_base_delay(),
            max_delay=envflags.retry_max_delay(),
            deadline=envflags.retry_deadline(),
        )
        base.update(overrides)
        return cls(site=site, **base)

    def is_retryable(self, exc: BaseException) -> bool:
        if self.fatal and isinstance(exc, self.fatal):
            return False
        if self.retryable and isinstance(exc, self.retryable):
            return True
        return (self.classify_fn or classify)(exc)

    def backoffs(self) -> Iterator[float]:
        """The (unbounded) jittered backoff sequence — callers that own
        their loop (lock contention) draw from this instead of
        re-deriving jitter math."""
        prev = self.base_delay
        while True:
            prev = decorrelated_jitter(prev, self.base_delay,
                                       self.max_delay, self.rng)
            yield prev

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy.

        Retries only classified-retryable failures, sleeps the jittered
        backoff between attempts (as a ``resilience.backoff`` span),
        never starts an attempt past the deadline, and consults/feeds
        the breaker when one is attached. The breaker being open counts
        as a (retryable) failed attempt — the backoff waits out part of
        the cooldown.
        """
        t0 = time.monotonic()
        delays = self.backoffs()
        last: Optional[BaseException] = None
        for attempt in range(1, max(1, self.max_attempts) + 1):
            self.last_attempts = attempt
            try:
                if self.breaker is not None:
                    self.breaker.before_call()
                result = fn(*args, **kwargs)
            except BaseException as exc:
                if (self.breaker is not None
                        and not isinstance(exc, CircuitOpen)):
                    self.breaker.record_failure(exc)
                retryable = self.is_retryable(exc)
                exhausted = (retryable
                             and attempt >= max(1, self.max_attempts))
                _retry_counter(self.site,
                               "exhausted" if exhausted
                               else "retried" if retryable
                               else "fatal").inc()
                if not retryable or exhausted:
                    raise
                last = exc
                delay = next(delays)
                elapsed = time.monotonic() - t0
                if (self.deadline is not None
                        and elapsed + delay > self.deadline):
                    record_trigger("deadline", site=self.site,
                                   attempt=attempt, elapsed_s=round(elapsed, 4))
                    raise DeadlineExceeded(self.site, elapsed, exc) from exc
                log.debug("%s: attempt %d/%d failed (%s); backing off "
                          "%.3fs", self.site, attempt, self.max_attempts,
                          exc, delay)
                with span("resilience.backoff"):
                    self.sleep_fn(delay)
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            _retry_counter(self.site, "ok").inc()
            return result
        raise AssertionError(f"unreachable: {last}")  # pragma: no cover


def _retry_counter(site: str, outcome: str):
    return GLOBAL_METRICS.retry_attempts.labels(site=site, outcome=outcome)


# -- circuit breaker --------------------------------------------------------

_STATE_CODE = {"closed": 0, "open": 1, "half-open": 2}


class CircuitBreaker:
    """closed -> open -> half-open per backend.

    ``threshold`` consecutive retryable failures open the circuit;
    while open, ``before_call`` raises CircuitOpen without touching the
    backend. After ``reset_seconds`` ONE probe call is let through
    (half-open): success closes the circuit, failure re-opens it for
    another cooldown. Fatal (non-retryable) errors never count toward
    the trip threshold — a NoSuchKey storm is the caller's bug, not an
    outage — but a fatal probe failure still releases the probe slot
    and restarts the cooldown (it proved nothing about health, and
    keeping the slot would wedge the breaker half-open forever).
    """

    def __init__(self, backend: str, *, threshold: Optional[int] = None,
                 reset_seconds: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.backend = backend
        self.threshold = (envflags.breaker_threshold() if threshold is None
                          else max(1, threshold))
        self.reset_seconds = (envflags.breaker_reset_seconds()
                              if reset_seconds is None else reset_seconds)
        self._clock = clock
        self._lock = lockcheck.make_lock(f"resilience.breaker.{backend}")
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._gauge = GLOBAL_METRICS.breaker_state.labels(backend=backend)
        self._gauge.set(0)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def open_remaining(self) -> float:
        """Seconds left in the open-state cooldown; 0.0 when the
        breaker is closed, half-open, or already due for its probe.
        Load-shedding callers (service/admission.py) use this as the
        retry-after hint — shedding at admission instead of discovering
        the open breaker mid-stream as a timeout."""
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0,
                       self._opened_at + self.reset_seconds - self._clock())

    def _transition(self, state: str):
        # caller holds self._lock
        if state == self._state:
            return
        self._state = state
        self._gauge.set(_STATE_CODE[state])
        GLOBAL_METRICS.breaker_transitions.labels(
            backend=self.backend, to=state).inc()
        if state == "open":
            # flight-recorder annotation; obs takes only its own lock,
            # never this breaker's, so nesting under self._lock is safe
            record_trigger("breaker_open", backend=self.backend)
        log.info("breaker %s -> %s", self.backend, state)

    def before_call(self):
        """Gate one call. Raises CircuitOpen while cooling down; in
        half-open, admits exactly one probe and shunts the rest."""
        with self._lock:
            if self._state == "closed":
                return
            remaining = self._opened_at + self.reset_seconds - self._clock()
            if self._state == "open":
                if remaining > 0:
                    raise CircuitOpen(self.backend, remaining)
                self._transition("half-open")
            if self._probing:  # half-open, probe slot taken
                raise CircuitOpen(self.backend, max(remaining, 0.0))
            self._probing = True

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._probing = False
            self._transition("closed")

    def record_failure(self, exc: BaseException):
        retryable = classify(exc)
        with self._lock:
            # The probe slot must be released on ANY failure, fatal or
            # not — a probe that dies on NoSuchKey would otherwise wedge
            # the breaker half-open with the slot taken forever, failing
            # every future call with CircuitOpen.
            self._probing = False
            if self._state == "half-open":
                self._opened_at = self._clock()
                self._transition("open")
                return
            if not retryable:
                return  # fatal errors say nothing about backend health
            self._failures += 1
            if self._failures >= self.threshold:
                self._opened_at = self._clock()
                self._transition("open")


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = lockcheck.make_lock("resilience.breakers")


def breaker_for(backend: str) -> CircuitBreaker:
    """Process-wide breaker per backend name (all S3 stores pointed at
    one endpoint share its health signal)."""
    with _breakers_lock:
        br = _breakers.get(backend)
        if br is None:
            br = _breakers[backend] = CircuitBreaker(backend)
        return br


def reset_breakers():
    """Drop all shared breakers (tests)."""
    with _breakers_lock:
        _breakers.clear()


# -- measured link statistics ----------------------------------------------

#: Payload size below which a store op is treated as a latency probe
#: rather than a bandwidth sample: tiny transfers are dominated by the
#: per-request round trip, so their wall time estimates link latency,
#: while large transfers estimate sustained bytes/second.
_LINK_SMALL_BYTES = 16 * 1024

_link_lock = lockcheck.make_lock("resilience.link")
_link_totals = {"small_ops": 0, "small_seconds": 0.0,
                "large_ops": 0, "large_bytes": 0, "large_seconds": 0.0}


def _observe_link(nbytes: int, seconds: float) -> None:
    """Fold one successful store attempt into the cumulative link
    totals (only arithmetic under the lock)."""
    with _link_lock:
        if nbytes < _LINK_SMALL_BYTES:
            _link_totals["small_ops"] += 1
            _link_totals["small_seconds"] += seconds
        else:
            _link_totals["large_ops"] += 1
            _link_totals["large_bytes"] += nbytes
            _link_totals["large_seconds"] += seconds


def link_totals() -> dict:
    """Cumulative timings of successful byte-moving ResilientStore
    attempts. The protocol planner's SyncStatsBook
    (engine/syncstats.py) diffs successive snapshots into EWMA
    bandwidth/latency estimates; returning cumulative totals keeps any
    number of independent books consistent."""
    with _link_lock:
        return dict(_link_totals)


def reset_link_totals() -> None:
    """Zero the cumulative link totals (tests)."""
    with _link_lock:
        for k in _link_totals:
            _link_totals[k] = type(_link_totals[k])()


def _payload_bytes(op: str, args: tuple, kwargs: dict, result) -> int:
    if op == "put":
        data = args[1] if len(args) > 1 else kwargs.get("data", b"")
        if isinstance(data, (list, tuple)):  # iovec PutBody
            return sum(len(p) for p in data)
        return len(data)
    return len(result) if isinstance(result, (bytes, bytearray)) else 0


# -- resilient object-store wrapper ----------------------------------------

#: Store methods wrapped with retry (all idempotent: puts are
#: whole-object and content-addressed or last-writer-wins, gets/lists
#: are reads). put_if_absent is NOT here: re-sending it after an
#: ambiguous failure can observe its own first attempt (see
#: objstore/s3.py put_if_absent docstring) — one attempt, caller
#: interprets False as "exists".
_RETRIED_OPS = ("put", "get", "get_range", "exists", "delete", "size",
                "put_file", "get_file")

#: Ops that are single-attempt BY DESIGN: retrying them needs an
#: argued-safe policy at the call site, never the blanket wrap.
#: ``put_if_absent`` is the fence/marker primitive — a blind replay
#: after an ambiguous failure could observe its own first attempt and
#: misreport "lost"; Repository._claim_marker documents the safe retry.
#: The VL601 analyzer (analysis/faultflow.py) exempts these sites the
#: way VL505 sanctions copy sites.
SINGLE_ATTEMPT_OPS = frozenset({"put_if_absent"})


class ResilientStore:
    """Any ObjectStore, wrapped in the shared retry policy + breaker.

    ``list`` is special: the iterator is materialized per attempt so a
    mid-pagination failure retries the WHOLE listing instead of
    resuming a broken continuation token.
    """

    def __init__(self, inner, *, policy: Optional[RetryPolicy] = None,
                 backend: str = "store",
                 breaker: Optional[CircuitBreaker] = None):
        self.inner = inner
        if policy is None:
            policy = RetryPolicy.from_env(f"objstore.{backend}")
        if policy.breaker is None:
            policy.breaker = (breaker if breaker is not None
                              else breaker_for(backend))
        self.policy = policy

    def __getattr__(self, name):  # passthrough for extras (stats, etc.)
        return getattr(self.inner, name)

    def put_if_absent(self, key: str, data: bytes) -> bool:
        return self.inner.put_if_absent(key, data)

    def list(self, prefix: str = ""):
        return iter(self.policy.call(
            lambda: list(self.inner.list(prefix))))


#: Byte-moving ops whose successful attempts feed the measured link
#: totals above. put_file/get_file are excluded: sizing them would cost
#: an extra stat per call on a path that already reports transfer totals
#: through the pipeline's own accounting.
_TIMED_OPS = ("put", "get", "get_range")


def _make_op(op: str):
    if op in _TIMED_OPS:
        def method(self, *args, **kwargs):
            inner = getattr(self.inner, op)

            def timed(*a, **kw):
                t0 = time.perf_counter()
                out = inner(*a, **kw)
                _observe_link(_payload_bytes(op, a, kw, out),
                              time.perf_counter() - t0)
                return out

            return self.policy.call(timed, *args, **kwargs)
    else:
        def method(self, *args, **kwargs):
            return self.policy.call(getattr(self.inner, op), *args, **kwargs)

    method.__name__ = op
    return method


for _op in _RETRIED_OPS:
    setattr(ResilientStore, _op, _make_op(_op))
del _op
