"""Repo-specific correctness layer: static analysis + runtime checks.

Two halves:

* ``volsync_tpu.analysis.engine`` / ``rules`` / ``iprules`` — an AST
  lint pass (``python -m volsync_tpu.analysis``, also ``volsync
  lint``) enforcing the invariants the code states but Python can't:
  env knobs parse only through envflags.py, optional heavy deps stay
  behind their shims, no silent exception swallowing, tracer-unsafe
  host ops stay out of jit'd kernels, data-plane locks route through
  lockcheck (VL001-VL005, per file); plus the interprocedural family
  over the project call graph (``callgraph``/``dataflow``): no
  blocking I/O under a lockcheck lock, thread/executor lifecycle,
  exception-path resource leaks, tracer taint through helper calls
  (VL101-VL104); plus a shape/dtype abstract interpreter over the same
  call graph (``shapes``/``absdomain``): statically incompatible
  shapes, implicit dtype promotion out of uint32 hash arithmetic,
  ``lax.scan`` carry drift, ``vmap`` axis arity, and mesh axis names
  vs ``parallel/mesh.py`` (VL201-VL205), with interprocedural shape
  summaries; plus a static concurrency analyzer over the lock regions
  (``lockflow``): lock-order cycles, guarded-field races,
  check-then-act windows, unsynchronized publication (VL401-VL404);
  plus a buffer-provenance and device-boundary analyzer (``bufflow``):
  implicit device->host syncs, per-item dispatch loops, unledgered
  pooled-buffer copies, use-after-donate, copy-ledger sanction drift
  (VL501-VL505) — the zero-copy data plane's laws, proven statically;
  plus a fault-path analyzer (``faultflow``): unprotected network
  effects, retry stacking over ``ResilientStore``, exception-taxonomy
  drift against ``classify()``, fence-before-publish dominance, and
  declared crash-ordering laws (VL601-VL605) — the retry/fencing/
  crash-ordering contracts of ``resilience.py`` and the repository
  two-phase protocols, proven statically.
  SARIF/JSON output (full source spans) and a content-hash
  incremental cache live in ``sarif``/``cache``; ``--select`` /
  ``--ignore`` stage rule families by code prefix.

* ``volsync_tpu.analysis.lockcheck`` — a debug-flag
  (``VOLSYNC_TPU_LOCKCHECK=1``) runtime detector that records the
  lock-acquisition graph per thread, fails fast on lock-order cycles
  (potential deadlock), and backs held-lock assertions on the pipeline
  stages' shared state.
"""

from volsync_tpu.analysis.engine import (
    Finding,
    LintResult,
    apply_baseline,
    load_baseline,
    run_lint,
    run_project,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "run_lint",
    "run_project",
    "load_baseline",
    "apply_baseline",
    "write_baseline",
]
