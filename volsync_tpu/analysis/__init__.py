"""Repo-specific correctness layer: static analysis + runtime checks.

Two halves:

* ``volsync_tpu.analysis.engine`` / ``rules`` — an AST lint pass
  (``python -m volsync_tpu.analysis``, also ``volsync lint``) enforcing
  the invariants the code states but Python can't: env knobs parse only
  through envflags.py, optional heavy deps stay behind their shims,
  no silent exception swallowing, tracer-unsafe host ops stay out of
  jit'd kernels, data-plane locks route through lockcheck.

* ``volsync_tpu.analysis.lockcheck`` — a debug-flag
  (``VOLSYNC_TPU_LOCKCHECK=1``) runtime detector that records the
  lock-acquisition graph per thread, fails fast on lock-order cycles
  (potential deadlock), and backs held-lock assertions on the pipeline
  stages' shared state.
"""

from volsync_tpu.analysis.engine import (
    Finding,
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

__all__ = [
    "Finding",
    "run_lint",
    "load_baseline",
    "apply_baseline",
    "write_baseline",
]
