"""Incremental lint cache keyed on file content hashes.

The cache stores, per analyzed file: the sha256 of its bytes, its
direct project-internal import dependencies (relpaths), and its
findings. On a warm run:

* nothing changed -> every finding is served from the cache and ZERO
  files are re-analyzed (no parsing at all);
* some files changed (or disappeared) -> the dirty set is the changed
  files plus their transitive REVERSE dependency closure — callers can
  hold interprocedural findings about callees, so editing a module
  must re-analyze everyone who (transitively) imports it. Everything
  else keeps its cached findings.

The cache self-invalidates when the analyzer version or the rule set
changes (``rules_signature``), so a rule edit can never serve stale
verdicts. The file is JSON, safe to delete at any time.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Optional

CACHE_VERSION = 1
# bump when rule logic changes in a way that should bust caches even
# though rule codes stayed the same
ANALYZER_REVISION = 5  # 5: VL6xx fault-path family + "fx" facts


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def rules_signature(rules: list, project_rules: list) -> str:
    ids = sorted(r.code for r in rules) + sorted(
        r.code for r in project_rules)
    blob = json.dumps({"rev": ANALYZER_REVISION, "rules": ids})
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def load_cache(path: Path, signature: str) -> Optional[dict]:
    """{relpath: {"hash", "deps", "findings"}} — None when absent,
    unreadable, or written by a different analyzer/rule set."""
    try:
        raw = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    if (raw.get("version") != CACHE_VERSION
            or raw.get("rules_sig") != signature):
        return None
    files = raw.get("files")
    return files if isinstance(files, dict) else None


def save_cache(path: Path, signature: str, files: dict) -> None:
    payload = {
        "comment": ("volsync lint incremental cache — content-hash "
                    "keyed, safe to delete"),
        "version": CACHE_VERSION,
        "rules_sig": signature,
        "files": files,
    }
    try:
        Path(path).write_text(json.dumps(payload, sort_keys=True) + "\n",
                              encoding="utf-8")
    except OSError:
        pass  # narrow: a read-only checkout simply skips caching


def dirty_closure(changed: set[str], removed: set[str],
                  deps: dict[str, set[str]]) -> set[str]:
    """changed/removed files plus everyone who transitively imports
    them, per the CURRENT dependency graph."""
    rdeps: dict[str, set[str]] = {}
    for src, targets in deps.items():
        for t in targets:
            rdeps.setdefault(t, set()).add(src)
    dirty = set(changed)
    work = list(changed | removed)
    while work:
        cur = work.pop()
        for dependent in rdeps.get(cur, ()):
            if dependent not in dirty:
                dirty.add(dependent)
                work.append(dependent)
    return dirty
