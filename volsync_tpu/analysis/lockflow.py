"""Static lock-order analysis (VL401) over lockcheck-named locks.

The runtime detector (``analysis/lockcheck.py``) records the
acquisition orders that tests actually *execute*; this module proves
the orders that the code can *reach*.  It extracts per-function
lock-acquisition summaries with the same region machinery as VL101
(``with``-regions plus bare ``acquire()``…``release()`` tail spans),
propagates held-lock sets interprocedurally through the call graph,
and builds the global acquisition-order graph: an edge ``a -> b``
means some code path acquires ``b`` while holding ``a``.  Any cycle in
that graph is a potential deadlock no test has to interleave for.

Naming follows lockcheck: locks are identified by their construction
NAME (a lock class, not an instance).  Striped locks built from
f-strings — ``make_lock(f"repo.index.shard{i}")`` — canonicalise to
their literal prefix plus ``*`` (``repo.index.shard*``), so the static
graph speaks in wildcards that runtime-observed names match by prefix
(see :func:`name_matches`); that is what makes the runtime-edge ⊆
static-graph cross-check in tests/test_analysis_locks.py well-typed.
Unnamed locks stay distinct per construction site rather than unifying
into one bogus graph node.

The per-index model (regions, held sets, acquisition edges) is also
the substrate for the guarded-field rules in ``analysis/guards.py``,
and per-function summaries are cached as the "locks" fact kind so warm
``--cache`` runs skip this pass entirely.
"""

from __future__ import annotations

import ast
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Iterator, Optional

from volsync_tpu.analysis.callgraph import (
    ModuleInfo,
    ProjectIndex,
    attr_chain,
)
from volsync_tpu.analysis.engine import Finding, finding_at
from volsync_tpu.analysis.iprules import (
    _LOCK_CTORS,
    _ScopeMaps,
    _walk_skip_defs,
)
from volsync_tpu.analysis.rules import _const_str


def lock_ctor_name(call: ast.Call, relpath: str) -> Optional[str]:
    """Lock NAME for a make_lock/make_rlock call: the literal string,
    an f-string's literal prefix + ``*`` (one wildcard lock class per
    construction site), or a site-unique placeholder when unnamed."""
    chain = attr_chain(call.func)
    if not chain or chain[-1] not in _LOCK_CTORS:
        return None
    if call.args:
        arg = call.args[0]
        lit = _const_str(arg)
        if lit is not None:
            return lit
        if isinstance(arg, ast.JoinedStr):
            prefix = ""
            for part in arg.values:
                if (isinstance(part, ast.Constant)
                        and isinstance(part.value, str)):
                    prefix += part.value
                else:
                    break
            return prefix + "*"
    return f"<unnamed:{relpath}:{call.lineno}>"


def _ctor_name_in(value: ast.AST, relpath: str) -> Optional[str]:
    """Lock name for an assignment RHS: a direct ctor call, or a lock
    stripe — a list/comprehension of ctor calls (all one name class)."""
    if isinstance(value, ast.Call):
        return lock_ctor_name(value, relpath)
    if isinstance(value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return _ctor_name_in(value.elt, relpath)
    if isinstance(value, (ast.List, ast.Tuple)) and value.elts:
        names = {_ctor_name_in(e, relpath) for e in value.elts}
        names.discard(None)
        if len(names) == 1:
            return names.pop()
    return None


#: Raw stdlib lock constructors. Code outside the lockcheck-
#: instrumented data plane guards state with plain threading locks;
#: the analyzer must see those as locks too, or every correctly
#: guarded access behind one reads as unguarded (false VL402/VL404).
_RAW_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: Sentinel returned while the binding target (which names the lock)
#: isn't known yet.
_RAW = "<raw>"


def _raw_ctor_name(value: ast.AST, cls_qual: Optional[str],
                   module_locks: dict, class_locks: dict) -> Optional[str]:
    """``threading.Lock()``/``RLock()``/``Condition()`` as a lock
    binding. These have no lockcheck name, so the binding gets a
    synthetic static-only ``raw:<owner>.<attr>`` name (never observed
    at runtime, so the runtime-⊆-static check is unaffected).
    ``Condition(existing_lock)`` ALIASES the wrapped lock's name:
    ``with self._cond:`` acquires the same underlying lock."""
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func)
    if not chain or chain[-1] not in _RAW_LOCK_CTORS:
        return None
    if len(chain) >= 2 and chain[-2] != "threading":
        return None
    if chain[-1] == "Condition" and value.args:
        arg = value.args[0]
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self" and cls_qual):
            wrapped = class_locks.get(cls_qual, {}).get(arg.attr)
            if wrapped is not None:
                return wrapped
        elif isinstance(arg, ast.Name):
            wrapped = module_locks.get(arg.id)
            if wrapped is not None:
                return wrapped
    return _RAW


def lock_bindings(
        mod: ModuleInfo) -> tuple[dict[str, str], dict[str, dict[str, str]]]:
    """(module_locks {var: name}, class_locks {class_qual: {attr:
    name}}) — like iprules._lock_bindings but wildcard-aware for
    f-string names and striped-lock lists."""
    module_locks: dict[str, str] = {}
    class_locks: dict[str, dict[str, str]] = {}

    def walk(body: list, cls_qual: Optional[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk(node.body, f"{_qual_prefix(node)}")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(node.body, cls_qual)
            else:
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    name = _ctor_name_in(sub.value, mod.relpath)
                    if name is None:
                        name = _raw_ctor_name(sub.value, cls_qual,
                                              module_locks, class_locks)
                    if name is None:
                        continue
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            module_locks[t.id] = (
                                f"raw:{mod.name}.{t.id}"
                                if name is _RAW else name)
                        elif (isinstance(t, ast.Attribute)
                              and isinstance(t.value, ast.Name)
                              and t.value.id == "self" and cls_qual):
                            class_locks.setdefault(
                                cls_qual, {})[t.attr] = (
                                f"raw:{cls_qual}.{t.attr}"
                                if name is _RAW else name)
                walk([s for s in ast.iter_child_nodes(node)
                      if isinstance(s, ast.stmt)], cls_qual)

    prefixes: dict[int, str] = {}

    def _qual_prefix(node: ast.ClassDef) -> str:
        return prefixes[id(node)]

    # precompute class qualnames the same way _ScopeMaps does, so the
    # keys line up with ProjectIndex.classes
    def name_walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            nprefix = prefix
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nprefix = f"{prefix}.{child.name}"
            elif isinstance(child, ast.ClassDef):
                nprefix = f"{prefix}.{child.name}"
                prefixes[id(child)] = nprefix
            name_walk(child, nprefix)

    name_walk(mod.ctx.tree, mod.name)
    walk(mod.ctx.tree.body, None)
    return module_locks, class_locks


@dataclass
class Region:
    """One lock-held span: a ``with``-region or a bare acquire tail."""
    lock: str
    relpath: str
    func: str  # qualname of enclosing function, or module name
    cls: Optional[str]  # lexical class qualname, if inside a method
    header: ast.AST  # the With / acquire-Expr statement
    body: list = field(default_factory=list)


@dataclass
class LockEdge:
    """``src`` held while ``dst`` is acquired, first derivation wins.

    ``chain`` is the call path as function qualnames: the holder
    function first, then each hop down to the function that directly
    acquires ``dst``.  ``node``/``relpath``/``lineno`` locate the
    statement *inside the src region* that starts the path (the nested
    acquisition itself, or the call that reaches one)."""
    src: str
    dst: str
    relpath: str
    lineno: int
    node: ast.AST
    chain: tuple


class LockModel:
    """Whole-program lock facts for one ProjectIndex."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.maps: dict[str, _ScopeMaps] = {}
        self.module_locks: dict[str, dict[str, str]] = {}  # relpath -> bind
        self.class_locks: dict[str, dict[str, str]] = {}  # class_qual -> bind
        self.regions: list[Region] = []
        # id(With|Expr stmt) -> ordered locks it acquires (With items)
        self._acq_stmts: dict[int, list[str]] = {}
        # func qual -> {lock: (relpath, lineno)} direct acquisitions
        self.direct: dict[str, dict[str, tuple]] = {}
        # func qual -> {lock: (chain, relpath, lineno)} transitive
        self.may: dict[str, dict[str, tuple]] = {}
        self.edges: dict[tuple, LockEdge] = {}
        # (class qualname, field) -> possible class qualnames: inferred
        # from ``self.f = ClassName(...)`` sites, so calls through
        # typed fields (``self._index.insert()``) resolve even though
        # the callgraph proper has no receiver types
        self.field_types: dict[tuple, set] = {}
        self._widened: dict[str, set] = {}
        # attr-typed call resolution: id(Call) -> callee qualnames,
        # plus the flat caller->callees edges for reachability closures
        self._attr_callees: dict[int, set] = {}
        self.extra_calls: dict[str, set] = {}
        self._extra_callers: dict[str, set] = {}
        self._fnqual: dict[int, str] = {
            id(fi.node): qual for qual, fi in index.functions.items()}
        self._build()

    # -- construction -------------------------------------------------------

    def _build(self) -> None:
        for relpath in sorted(self.index.by_relpath):
            mod = self.index.by_relpath[relpath]
            mlocks, clocks = lock_bindings(mod)
            self.module_locks[relpath] = mlocks
            self.class_locks.update(clocks)
        self._collect_field_types()
        self._resolve_attr_calls()
        for relpath in sorted(self.index.by_relpath):
            self._collect_regions(self.index.by_relpath[relpath])
        self._close_may()
        self._collect_edges()

    def _collect_field_types(self) -> None:
        for cq in sorted(self.index.classes):
            ci = self.index.classes[cq]
            mod = self.index.modules.get(ci.module)
            if mod is None:
                continue
            for fi in ci.methods.values():
                params = self._param_types(fi, mod)
                for sub in ast.walk(fi.node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    classes: set = set()
                    if isinstance(sub.value, ast.Call):
                        target_cls = self._class_of_ctor(sub.value, mod)
                        if target_cls is not None:
                            classes.add(target_cls)
                    elif (isinstance(sub.value, ast.Name)
                          and sub.value.id in params):
                        classes |= params[sub.value.id]
                    if not classes:
                        continue
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            self.field_types.setdefault(
                                (cq, t.attr), set()).update(classes)

    def _param_types(self, fi, mod) -> dict[str, set]:
        """{param name: widened class quals} from parameter
        annotations that resolve to project classes — so a field
        assigned FROM a parameter (``self.store = store`` with
        ``store: ObjectStore``) gets a type instead of a blind spot.
        The widening makes this a may-analysis: any implementation
        could arrive at runtime, so all of them are candidates."""
        out: dict[str, set] = {}
        a = fi.node.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            if arg.annotation is None:
                continue
            cls = self._annotation_class(arg.annotation, mod)
            if cls is not None:
                out[arg.arg] = self._widen_type(cls)
        return out

    def _annotation_class(self, expr: ast.AST, mod) -> Optional[str]:
        if isinstance(expr, ast.Subscript):
            chain = attr_chain(expr.value)
            if chain and chain[-1] == "Optional":
                return self._annotation_class(expr.slice, mod)
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            try:
                parsed = ast.parse(expr.value, mode="eval").body
            except SyntaxError:
                return None
            return self._annotation_class(parsed, mod)
        chain = attr_chain(expr)
        return self._resolve_class_chain(chain, mod) if chain else None

    def _widen_type(self, cls_qual: str) -> set:
        """A declared type widened to its possible concrete classes.
        A ``Protocol`` widens structurally — every class defining ALL
        of the protocol's declared (public) methods implements it; a
        nominal class widens to itself plus its subclasses."""
        cached = self._widened.get(cls_qual)
        if cached is not None:
            return cached
        ci = self.index.classes.get(cls_qual)
        out = {cls_qual}
        if ci is not None:
            if any((attr_chain(b) or ["?"])[-1] == "Protocol"
                   for b in ci.base_exprs):
                wanted = {m for m in ci.methods if not m.startswith("_")}
                if wanted:
                    for dq in sorted(self.index.classes):
                        di = self.index.classes[dq]
                        if dq != cls_qual and wanted <= set(di.methods):
                            out.add(dq)
            else:
                for dq in sorted(self.index.classes):
                    if cls_qual in self._ancestors(dq):
                        out.add(dq)
        self._widened[cls_qual] = out
        return out

    def _ancestors(self, cls_qual: str) -> set:
        seen: set = set()
        queue = deque([cls_qual])
        while queue:
            q = queue.popleft()
            if q is None or q in seen:
                continue
            seen.add(q)
            ci = self.index.classes.get(q)
            if ci:
                queue.extend(ci.bases)
        seen.discard(cls_qual)
        return seen

    def _class_of_ctor(self, call: ast.Call, mod) -> Optional[str]:
        chain = attr_chain(call.func)
        return self._resolve_class_chain(chain, mod) if chain else None

    def _resolve_class_chain(self, chain: list, mod) -> Optional[str]:
        if len(chain) == 1 and chain[0] in mod.classes:
            return mod.classes[chain[0]].qualname
        dotted = None
        if chain[0] in mod.aliases:
            dotted = ".".join([mod.aliases[chain[0]]] + chain[1:])
        elif len(chain) > 1:
            dotted = ".".join(chain)
        if dotted is None:
            return None
        resolved = self.index.resolve_dotted(dotted)
        if resolved is None:
            return None
        if resolved in self.index.classes:
            return resolved
        if resolved.endswith(".__init__"):
            cq = resolved[:-len(".__init__")]
            if cq in self.index.classes:
                return cq
        return None

    def _field_classes(self, cls_qual: Optional[str], attr: str) -> set:
        """Field types for ``self.<attr>``, walking the base chain."""
        seen: set = set()
        out: set = set()
        queue = deque([cls_qual] if cls_qual else [])
        while queue:
            q = queue.popleft()
            if q is None or q in seen:
                continue
            seen.add(q)
            out |= self.field_types.get((q, attr), set())
            ci = self.index.classes.get(q)
            if ci:
                queue.extend(ci.bases)
        return out

    def _resolve_attr_calls(self) -> None:
        """Second-chance resolution for ``self.<field>.<method>()``
        calls the callgraph left unresolved."""
        for qual in sorted(self.index.functions):
            fi = self.index.functions[qual]
            for node in _walk_skip_defs(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                site = self.index.site_by_node.get(id(node))
                if site is not None and site.callee is not None:
                    continue
                chain = attr_chain(node.func)
                if (not chain or len(chain) != 3
                        or chain[0] != "self" or fi.cls is None):
                    continue
                targets: set = set()
                for tcq in sorted(self._field_classes(fi.cls, chain[1])):
                    ci = self.index.classes.get(tcq)
                    m = (self.index._method_on_class(ci, chain[2])
                         if ci else None)
                    if m:
                        targets.add(m)
                if not targets:
                    continue
                self._attr_callees[id(node)] = targets
                self.extra_calls.setdefault(qual, set()).update(targets)
                for t in targets:
                    self._extra_callers.setdefault(t, set()).add(qual)

    def resolve_self_lock(self, cls_qual: Optional[str],
                          attr: str) -> Optional[str]:
        """``self.<attr>`` -> lock name, walking ALL base classes
        breadth-first (inherited locks guard subclass code too)."""
        seen: set[str] = set()
        queue = deque([cls_qual] if cls_qual else [])
        while queue:
            q = queue.popleft()
            if q is None or q in seen:
                continue
            seen.add(q)
            name = self.class_locks.get(q, {}).get(attr)
            if name:
                return name
            ci = self.index.classes.get(q)
            if ci:
                queue.extend(ci.bases)
        return None

    def _context_lock(self, expr: ast.AST, relpath: str,
                      cls_qual: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Subscript):  # striped: self._locks[s]
            expr = expr.value
        if isinstance(expr, ast.Name):
            return self.module_locks[relpath].get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return self.resolve_self_lock(cls_qual, expr.attr)
        return None

    def _func_of(self, maps: _ScopeMaps, node: ast.AST,
                 mod: ModuleInfo) -> str:
        fn = maps.encl_fn.get(id(node))
        while fn is not None and id(fn) not in self._fnqual:
            fn = maps.encl_fn.get(id(fn))
        return self._fnqual[id(fn)] if fn is not None else mod.name

    def _collect_regions(self, mod: ModuleInfo) -> None:
        maps = _ScopeMaps(mod)
        self.maps[mod.relpath] = maps
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cq = maps.encl_cls.get(id(node))
                locks = [lk for item in node.items
                         if (lk := self._context_lock(
                             item.context_expr, mod.relpath, cq))]
                if not locks:
                    continue
                self._acq_stmts[id(node)] = locks
                func = self._func_of(maps, node, mod)
                for lk in locks:
                    self.regions.append(Region(
                        lk, mod.relpath, func, cq, node, node.body))
                    self.direct.setdefault(func, {}).setdefault(
                        lk, (mod.relpath, node.lineno))
                # ``with a, b:`` acquires in item order: a -> b
                for held, nxt in zip(locks, locks[1:]):
                    self._add_edge(held, nxt, mod.relpath, node.lineno,
                                   node, (func,))
            elif isinstance(node, ast.Expr):
                self._collect_bare_region(node, mod, maps)

    def _collect_bare_region(self, node: ast.Expr, mod: ModuleInfo,
                             maps: _ScopeMaps) -> None:
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"):
            return
        base = attr_chain(call.func.value)
        if base is None:
            return
        cq = maps.encl_cls.get(id(node))
        lock = None
        if len(base) == 1:
            lock = self.module_locks[mod.relpath].get(base[0])
        elif base[0] == "self" and len(base) == 2:
            lock = self.resolve_self_lock(cq, base[1])
        if not lock:
            return
        block = maps.block_of(node)
        if block is None:
            return
        tail: list = []
        for stmt in block[block.index(node) + 1:]:
            tail.append(stmt)
            if any(isinstance(s, ast.Call)
                   and isinstance(s.func, ast.Attribute)
                   and s.func.attr == "release"
                   and attr_chain(s.func.value) == base
                   for s in ast.walk(stmt)):
                break
        func = self._func_of(maps, node, mod)
        self._acq_stmts[id(node)] = [lock]
        self.regions.append(Region(lock, mod.relpath, func, cq, node, tail))
        self.direct.setdefault(func, {}).setdefault(
            lock, (mod.relpath, node.lineno))

    def _close_may(self) -> None:
        """Transitive may-acquire: if f calls g and g may acquire L,
        then f may acquire L.  First (shortest-first, deterministic)
        derivation wins, so chains stay minimal and stable."""
        for qual in sorted(self.direct):
            for lk in sorted(self.direct[qual]):
                relpath, lineno = self.direct[qual][lk]
                self.may.setdefault(qual, {})[lk] = ((qual,), relpath, lineno)
        work = deque(sorted(self.may))
        while work:
            callee = work.popleft()
            facts = self.may.get(callee, {})
            for caller in self._callers_of(callee):
                cur = self.may.setdefault(caller, {})
                changed = False
                for lk in sorted(facts):
                    if lk in cur:
                        continue
                    chain, relpath, lineno = facts[lk]
                    cur[lk] = ((caller,) + chain, relpath, lineno)
                    changed = True
                if changed:
                    work.append(caller)

    def _callers_of(self, callee: str) -> Iterator[str]:
        for site in self.index.callers.get(callee, ()):  # type: ignore
            yield site.caller
        yield from sorted(self._extra_callers.get(callee, ()))

    def _add_edge(self, src: str, dst: str, relpath: str, lineno: int,
                  node: ast.AST, chain: tuple) -> None:
        self.edges.setdefault(
            (src, dst), LockEdge(src, dst, relpath, lineno, node, chain))

    def _collect_edges(self) -> None:
        for region in self.regions:
            for stmt in region.body:
                for node in self._iter_live(stmt):
                    locks = self._acq_stmts.get(id(node))
                    if locks is not None:
                        for lk in locks:
                            self._add_edge(region.lock, lk, region.relpath,
                                           node.lineno, node, (region.func,))
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    site = self.index.site_by_node.get(id(node))
                    callees = set(self._attr_callees.get(id(node), ()))
                    if site is not None and site.callee is not None:
                        callees.add(site.callee)
                    for callee in sorted(callees):
                        for lk in sorted(self.may.get(callee, ())):
                            chain, _, _ = self.may[callee][lk]
                            self._add_edge(region.lock, lk, region.relpath,
                                           node.lineno, node,
                                           (region.func,) + chain)

    @staticmethod
    def _iter_live(stmt: ast.AST) -> Iterator[ast.AST]:
        """The statement and everything under it that runs while the
        region is held — nested def/lambda bodies execute later, on
        their own call sites, so they are skipped."""
        yield stmt
        yield from _walk_skip_defs(stmt)

    # -- held-lock query (used by guards.py) --------------------------------

    def held_map(self, relpath: str) -> dict[int, frozenset]:
        """id(node) -> set of lock names held at that node, for every
        node inside some region body of this module."""
        held: dict[int, set] = {}
        for region in self.regions:
            if region.relpath != relpath:
                continue
            for stmt in region.body:
                for node in self._iter_live(stmt):
                    held.setdefault(id(node), set()).add(region.lock)
        return {k: frozenset(v) for k, v in held.items()}


_MODELS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def model_for(index: ProjectIndex) -> LockModel:
    model = _MODELS.get(index)
    if model is None:
        model = LockModel(index)
        _MODELS[index] = model
    return model


# -- rendering ---------------------------------------------------------------


def fn_label(index: ProjectIndex, qual: str) -> str:
    """Human hop label: ``Repository.flush()`` / ``helper()`` /
    ``module:pkg.mod`` for module-level code."""
    fi = index.functions.get(qual)
    if fi is None:
        return f"module:{qual}"
    name = fi.node.name
    if fi.cls:
        return f"{fi.cls.rsplit('.', 1)[-1]}.{name}()"
    return f"{name}()"


def _hop_text(index: ProjectIndex, edge: LockEdge) -> str:
    return " -> ".join(f"`{fn_label(index, q)}`" for q in edge.chain)


# -- VL401 rule --------------------------------------------------------------


class LockOrderRule:
    """VL401 — cycle in the static lock-acquisition-order graph."""

    code = "VL401"
    name = "lock-order-cycle"
    severity = "error"
    description = ("two lock classes are acquired in both orders on "
                   "some pair of static paths — a potential deadlock "
                   "no test has to interleave for")

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        model = model_for(index)
        adj: dict[str, list] = {}
        for a, b in model.edges:
            if a != b:
                adj.setdefault(a, []).append(b)
        for a in adj:
            adj[a].sort()
        reported: set[frozenset] = set()
        for a, b in sorted(model.edges):
            if a == b:
                continue  # same-name nesting: hazardous only across
                # instances; kept in the graph, judged by the runtime
                # detector which can tell instances apart
            path = self._bfs_path(adj, b, a)
            if path is None:
                continue
            nodes = frozenset(path)
            if nodes in reported:
                continue
            reported.add(nodes)
            cycle = [a] + path  # a -> b -> ... -> a
            hops = []
            for s, d in zip(cycle, cycle[1:]):
                e = model.edges[(s, d)]
                hops.append(f"'{s}'->'{d}' via {_hop_text(index, e)} "
                            f"({e.relpath}:{e.lineno})")
            head = model.edges[(a, b)]
            yield finding_at(
                head.relpath, head.node, self.code,
                f"lock-order cycle {' -> '.join(repr(n) for n in cycle)}: "
                + "; ".join(hops)
                + " — pick one global acquisition order",
                severity=self.severity)

    @staticmethod
    def _bfs_path(adj: dict, start: str, goal: str) -> Optional[list]:
        """Shortest path start..goal over ``adj`` (inclusive), or
        None.  Deterministic: neighbours are pre-sorted."""
        if start == goal:
            return [start]
        prev: dict[str, str] = {}
        queue = deque([start])
        seen = {start}
        while queue:
            cur = queue.popleft()
            for nxt in adj.get(cur, ()):  # sorted
                if nxt in seen:
                    continue
                seen.add(nxt)
                prev[nxt] = cur
                if nxt == goal:
                    out = [goal]
                    while out[-1] != start:
                        out.append(prev[out[-1]])
                    return out[::-1]
                queue.append(nxt)
        return None


# -- cache fact kind ---------------------------------------------------------


def summaries_for(index: ProjectIndex) -> dict[str, dict]:
    """Per-file lock facts — the cached "locks" fact kind.  A file's
    summary changes iff its acquisition sites or the edges rooted in
    it change, so the cache layer can replay clean files verbatim."""
    model = model_for(index)
    out: dict[str, dict] = {}

    def slot(relpath: str) -> dict:
        return out.setdefault(relpath, {"acquires": {}, "edges": []})

    for qual in sorted(model.direct):
        fi = index.functions.get(qual)
        mod = index.modules.get(qual) if fi is None else None
        relpath = fi.relpath if fi else (mod.relpath if mod else None)
        if relpath is None:
            continue
        slot(relpath)["acquires"][qual] = sorted(
            [lk, lineno] for lk, (_, lineno) in model.direct[qual].items())
    for (a, b) in sorted(model.edges):
        e = model.edges[(a, b)]
        slot(e.relpath)["edges"].append([a, b, e.lineno, list(e.chain)])
    return out


# -- graph export ------------------------------------------------------------


def graph_json(index: ProjectIndex) -> dict:
    """The static acquisition graph as plain JSON for the debug
    toolbox: nodes are lock names, edges carry hop chains."""
    model = model_for(index)
    nodes = sorted({n for e in model.edges for n in e})
    edges = [{"from": a, "to": b,
              "site": f"{e.relpath}:{e.lineno}",
              "via": [fn_label(index, q) for q in e.chain]}
             for (a, b), e in sorted(model.edges.items())]
    return {"nodes": nodes, "edges": edges}


def dump_for_paths(paths) -> dict:
    """Build the acquisition graph for a path set from scratch —
    the ``volsync lint --dump-lock-graph`` entry point."""
    from volsync_tpu.analysis.callgraph import build_index
    from volsync_tpu.analysis.engine import (
        FileContext,
        iter_py_files,
        relativize,
    )

    contexts = []
    for path in iter_py_files(paths):
        relpath = relativize(path)
        try:
            source = path.read_bytes().decode("utf-8")
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError, ValueError):
            continue  # the lint run proper reports parse errors
        contexts.append(FileContext(path, relpath, source, tree))
    return graph_json(build_index(contexts))


def static_edges(index: ProjectIndex) -> set:
    """The raw ``(src, dst)`` edge name set (wildcards included)."""
    return set(model_for(index).edges)


def name_matches(static_name: str, runtime_name: str) -> bool:
    """Does a static lock name (possibly a ``prefix*`` wildcard from
    an f-string construction site) cover a runtime-observed name?"""
    if static_name.endswith("*"):
        return runtime_name.startswith(static_name[:-1])
    return static_name == runtime_name


def edge_covered(edges: set, runtime_edge: tuple) -> bool:
    """Is a runtime-observed ``(src, dst)`` acquisition edge covered
    by some static edge, matching wildcard names by prefix?"""
    ra, rb = runtime_edge
    return any(name_matches(a, ra) and name_matches(b, rb)
               for a, b in edges)
