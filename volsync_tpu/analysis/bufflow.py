"""Buffer-provenance and device-boundary dataflow analysis (VL5xx).

The zero-copy data plane moves payload bytes as pooled buffers
(engine/bufpool.py) and memoryviews; the copy ledger
(obs/copyledger.py) accounts for the sanctioned host copies that
remain, and the donation twins (ops/segment.py) hand staged device
rows to XLA for reuse.  VL106 guards that contract syntactically; this
module proves it semantically: an abstract provenance lattice per
value —

* ``pooled``  — a buffer from a BufferPool ``acquire()``;
* ``mview``   — a memoryview/slice over a pooled buffer;
* ``device``  — the result of a ``jnp.*``/``lax.*``/jitted call
  (including the donated-argument jit twins);
* ``host``    — materialized host bytes (``np.asarray`` fetch,
  ``bytes``, ``.tobytes``);
* ``unknown`` — everything else (never produces a finding);

propagated through per-function summaries (returns / donated params /
param materializations) over the callgraph, each fact carrying a hop
chain back to its origin.  Five rules ride the model:

* **VL501** implicit device→host sync in a hot scope (``float``/
  ``int``/``bool``/``.item()``/``np.asarray`` on a device value in
  engine/, ops/ or repo/).  A function that ledgers a sanctioned copy
  (``record_copy(site, n)`` with ``site`` in ``SANCTIONED_SITES``) is
  an explicit staging site and is exempt — that is where the batched
  fetch is *supposed* to happen.
* **VL502** device dispatch inside a per-item Python loop: a ``jnp``/
  ``lax``/jit-twin call whose operand derives from the loop variable —
  the anti-pattern the batched kernels exist to kill.
* **VL503** semantic copy: a materialization (``bytes(x)``,
  ``x.tobytes()``, ``b"".join``) whose operand has pooled/mview
  provenance — locally or via a parameter — is a finding unless the
  statement (or an adjacent sibling within ``_SANCTION_SPAN`` lines)
  ledgers it with a sanctioned ``record_copy`` site.
* **VL504** use-after-donate: a variable passed to a donated-argument
  jit twin (directly, through a helper whose summary donates the
  parameter, or through a conditional ``donated if cond else normal``
  twin binding — the maybe-donating hop that bypasses the donating
  twin on one path) and then read again.
* **VL505** ledger⊆sanction drift: every ``record_copy`` call site
  must name a literal site in ``SANCTIONED_SITES``, and every
  sanctioned site must have at least one call site.

``SANCTIONED_SITES`` is resolved from the AST of ``obs/copyledger.py``
in the linted tree (never hardcoded), falling back to the installed
module's file when the tree under analysis does not include one; VL505
stays silent without a ledger module in the index.  Per-function facts
are cached as the ``"buf"`` fact kind so warm ``--cache`` runs skip
this pass entirely, and ``volsync lint --dump-provenance`` exports the
node/hop-edge JSON for offline diffing (docs/development.md).
"""

from __future__ import annotations

import ast
import weakref
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

from volsync_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleInfo,
    ProjectIndex,
    attr_chain,
)
from volsync_tpu.analysis.engine import Finding, finding_at
from volsync_tpu.analysis.iprules import _ScopeMaps, _walk_skip_defs
from volsync_tpu.analysis.rules import _const_str

# -- provenance lattice ------------------------------------------------------

POOLED = "pooled"
MVIEW = "mview"
DEVICE = "device"
HOST = "host"
UNKNOWN = "unknown"

#: join order: a pooled verdict must survive merging with anything
#: weaker, and any concrete tag beats the symbolic param:<i> tags.
_RANK = {POOLED: 5, MVIEW: 4, DEVICE: 3, HOST: 2, UNKNOWN: 0}


@dataclass(frozen=True)
class Prov:
    """One abstract value: lattice tag + hop chain back to the origin
    (human-readable strings, origin first).  Symbolic tags
    ``param:<i>`` / ``paramview:<i>`` stand for "the i-th parameter of
    the function under analysis" until call-site provenance arrives."""

    tag: str
    chain: tuple = ()


UNK = Prov(UNKNOWN)


def _rank(p: Prov) -> int:
    return _RANK.get(p.tag, 1)  # symbolic tags rank above UNKNOWN


def join(a: Prov, b: Prov) -> Prov:
    return a if _rank(a) >= _rank(b) else b


def _param_of(p: Prov) -> Optional[tuple]:
    """(index, is_view) for a symbolic parameter tag, else None."""
    if p.tag.startswith("param:"):
        return int(p.tag.split(":")[1]), False
    if p.tag.startswith("paramview:"):
        return int(p.tag.split(":")[1]), True
    return None


def _hops(chain) -> str:
    return " -> ".join(chain)


# -- sanctioned-site resolution ---------------------------------------------

#: a materialization counts as ledgered when the record_copy sits on
#: the same statement or an adjacent sibling within this many lines
_SANCTION_SPAN = 3

_LEDGER_SUFFIX = "obs/copyledger.py"


def _literal_sites(value: ast.AST) -> dict[str, ast.AST]:
    """{site: element node} from a frozenset({...})/set/list/tuple of
    string constants (the SANCTIONED_SITES shape)."""
    if isinstance(value, ast.Call) and value.args:
        value = value.args[0]
    out: dict[str, ast.AST] = {}
    if isinstance(value, (ast.Set, ast.List, ast.Tuple)):
        for e in value.elts:
            s = _const_str(e)
            if s is not None:
                out[s] = e
    return out


def _sites_from_tree(tree: ast.AST) -> Optional[dict[str, ast.AST]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SANCTIONED_SITES":
                    return _literal_sites(node.value)
    return None


def ledger_module(index: ProjectIndex) -> Optional[ModuleInfo]:
    for rp in sorted(index.by_relpath):
        if rp == _LEDGER_SUFFIX or rp.endswith("/" + _LEDGER_SUFFIX):
            return index.by_relpath[rp]
    return None


_installed_cache: dict[str, frozenset] = {}


def installed_sanctioned_sites() -> frozenset:
    """SANCTIONED_SITES parsed from the installed copyledger file — the
    fallback used when the linted tree has no obs/copyledger.py (and by
    the per-file VL106 rule, which has no project index)."""
    path = Path(__file__).resolve().parent.parent / "obs" / "copyledger.py"
    key = str(path)
    if key not in _installed_cache:
        try:
            sites = _sites_from_tree(ast.parse(path.read_text(
                encoding="utf-8")))
        except (OSError, SyntaxError, ValueError):
            sites = None
        _installed_cache[key] = frozenset(sites or ())
    return _installed_cache[key]


def _is_record_copy(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain[-1] == "record_copy"


def _record_site(call: ast.Call) -> Optional[str]:
    """Literal site name of a record_copy call, else None."""
    arg = call.args[0] if call.args else next(
        (kw.value for kw in call.keywords if kw.arg == "site"), None)
    return _const_str(arg) if arg is not None else None


def statement_sanctioned(stmt: ast.stmt, block: Optional[list],
                         sites: frozenset) -> Optional[str]:
    """Site name when ``stmt`` is ledgered: itself or an adjacent
    sibling statement within ``_SANCTION_SPAN`` lines carries a
    ``record_copy`` with a literal sanctioned site.  Shared by VL503
    and the per-file VL106 rule, so their verdicts can never drift."""
    candidates = [stmt]
    if block is not None and stmt in block:
        i = block.index(stmt)
        for sib in block[max(0, i - 1): i + 2]:
            if sib is not stmt and abs(
                    sib.lineno - stmt.lineno) <= _SANCTION_SPAN:
                candidates.append(sib)
    for cand in candidates:
        for node in ast.walk(cand):
            if isinstance(node, ast.Call) and _is_record_copy(node):
                site = _record_site(node)
                if site is not None and site in sites:
                    return site
    return None


_COMPOUND_STMTS = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
                   ast.AsyncWith, ast.Try, ast.FunctionDef,
                   ast.AsyncFunctionDef, ast.ClassDef)


def _child_blocks(stmt: ast.stmt) -> Iterator[list]:
    for name in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, name, None)
        if blk:
            yield blk
    for h in getattr(stmt, "handlers", ()):
        yield h.body


def sanctioned_lines(tree: ast.Module,
                     sites: Optional[frozenset] = None) -> set:
    """1-based line numbers covered by statements whose copies are
    ledgered (``statement_sanctioned``).  The per-file bridge VL106
    consults: a syntactic copy on one of these lines is semantically
    sanctioned, so the blanket same-line suppressions that merely
    restated a ``record_copy`` can go away."""
    if sites is None:
        sites = installed_sanctioned_sites()
    out: set = set()
    if not sites:
        return out

    def visit_block(stmts: list) -> None:
        for s in stmts:
            if not isinstance(s, _COMPOUND_STMTS) and \
                    statement_sanctioned(s, stmts, sites) is not None:
                end = getattr(s, "end_lineno", None) or s.lineno
                out.update(range(s.lineno, end + 1))
            for blk in _child_blocks(s):
                visit_block(blk)

    visit_block(tree.body)
    return out


# -- device / pool / twin classification ------------------------------------

def _expand_chain(chain: list, mod: ModuleInfo) -> str:
    """Dotted name with the leading alias expanded: with ``import
    jax.numpy as jnp``, ["jnp", "asarray"] -> "jax.numpy.asarray"."""
    head = mod.aliases.get(chain[0], chain[0])
    return ".".join([head] + chain[1:])


def _is_device_call(call: ast.Call, mod: ModuleInfo) -> bool:
    """Any jax-API call — produces a device-provenance value."""
    chain = attr_chain(call.func)
    if not chain:
        return False
    dotted = _expand_chain(chain, mod)
    return dotted == "jax" or dotted.startswith("jax.")


def _is_dispatch_chain(chain: list, mod: ModuleInfo) -> bool:
    """jnp./lax./pallas chains only — the VL502 notion of a *dispatch*
    (jax.jit / jax.block_until_ready are not per-item dispatches)."""
    dotted = _expand_chain(chain, mod)
    return dotted.startswith(("jax.numpy.", "jax.lax.",
                              "jax.experimental.pallas"))


def _is_pool_acquire(call: ast.Call) -> bool:
    """``bufpool.GLOBAL.acquire(n)`` / ``<pool>.acquire(n)`` where the
    receiver chain names the pool module or its GLOBAL singleton."""
    chain = attr_chain(call.func)
    return (bool(chain) and chain[-1] == "acquire"
            and any(c in ("bufpool", "GLOBAL") for c in chain[:-1]))


def _is_host_fetch(call: ast.Call, mod: ModuleInfo) -> bool:
    """np.asarray/np.array — device→host when the operand is device."""
    chain = attr_chain(call.func)
    if not chain:
        return False
    return _expand_chain(chain, mod) in ("numpy.asarray", "numpy.array")


_JIT_NAMES = ("jax.jit", "jax.pjit")


def _twin_donates(value: ast.AST, mod: ModuleInfo) -> Optional[tuple]:
    """Donated positional indices for a jit application RHS/decorator:
    ``jax.jit(impl, donate_argnums=...)`` or
    ``functools.partial(jax.jit, ..., donate_argnums=...)(impl)`` /
    the same partial used as a decorator.  ``()`` = jitted, donates
    nothing; None = not a jit application at all."""
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func)
    if chain and _expand_chain(chain, mod) in _JIT_NAMES:
        return _donate_kw(value)
    if isinstance(value.func, ast.Call):  # partial(jax.jit, ...)(impl)
        inner = value.func
        ichain = attr_chain(inner.func)
        if (ichain and ichain[-1] == "partial" and inner.args
                and (achain := attr_chain(inner.args[0]))
                and _expand_chain(achain, mod) in _JIT_NAMES):
            return _donate_kw(inner)
    # decorator form: @functools.partial(jax.jit, ...)
    if chain and chain[-1] == "partial" and value.args:
        achain = attr_chain(value.args[0])
        if achain and _expand_chain(achain, mod) in _JIT_NAMES:
            return _donate_kw(value)
    return None


def _donate_kw(call: ast.Call) -> tuple:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


_MAT_KINDS = {"bytes": "bytes(...)", "tobytes": ".tobytes()",
              "join": 'b"".join'}


def _materialization(call: ast.Call) -> Optional[tuple]:
    """(kind label, operand expr) for bytes(x) / x.tobytes() /
    b"".join(parts) — the same shapes VL106 matches."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "tobytes":
        return _MAT_KINDS["tobytes"], f.value
    if (isinstance(f, ast.Name) and f.id == "bytes" and len(call.args) == 1
            and not call.keywords
            and not isinstance(call.args[0], ast.Constant)):
        return _MAT_KINDS["bytes"], call.args[0]
    if (isinstance(f, ast.Attribute) and f.attr == "join"
            and isinstance(f.value, ast.Constant)
            and isinstance(f.value.value, bytes) and call.args):
        return _MAT_KINDS["join"], call.args[0]
    return None


def _const_iterable(it: ast.AST) -> bool:
    """True for an iterable that is a literal constant sequence —
    ``(1, 2, 4, 8, 16)`` or ``range(16)`` — i.e. a bounded structural
    unroll (the log-depth doubling kernels), not a per-data-item loop."""
    if isinstance(it, (ast.Tuple, ast.List)):
        return bool(it.elts) and all(
            isinstance(e, ast.Constant) for e in it.elts)
    if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and it.args):
        return all(isinstance(a, ast.Constant) for a in it.args)
    return False


_SYNC_BUILTINS = {"float", "int", "bool"}

#: VL501 hot scopes — the zero-copy data plane proper
_HOT_PARTS = ("engine", "ops", "repo")


# -- per-function facts ------------------------------------------------------

@dataclass
class FnSummary:
    """What a caller needs to know about a function."""

    returns: Prov = UNK
    ret_param: Optional[int] = None  # returns param i (or a view of it)
    ret_view: bool = False
    donates: dict = field(default_factory=dict)  # param idx -> hop chain
    sanctions: list = field(default_factory=list)  # [(site, lineno)]


@dataclass
class _Pending:
    """A fact about a symbolic parameter, resolved after the param-
    provenance fixpoint: a materialization of param ``idx`` (VL503) at
    ``node`` in function ``qual``."""

    qual: str
    idx: int
    node: ast.AST
    relpath: str
    desc: str  # local hop text, e.g. "bytes(...) at a/b.py:12"


class BufModel:
    """Whole-program buffer-provenance facts for one ProjectIndex."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.maps: dict[str, _ScopeMaps] = {}
        self.sites: dict[str, ast.AST] = {}  # sanctioned site -> elt node
        self.ledger: Optional[ModuleInfo] = None
        self.site_set: frozenset = frozenset()
        # jit twins: dotted qualname -> donated positional indices
        self.twins: dict[str, tuple] = {}
        self.record_sites: dict[str, list] = {}  # site -> [(relpath, line)]
        self.nonliteral: list = []  # (relpath, Call) record_copy sites
        self.summaries: dict[str, FnSummary] = {}
        self._in_progress: set = set()
        self.findings: list[Finding] = []
        self._pending: list[_Pending] = []
        # (callee qual, param idx) -> list of contributions:
        #   ("const", Prov) | ("param", caller qual, caller idx, hop)
        self._flows: dict[tuple, list] = {}
        self.param_prov: dict[tuple, Prov] = {}
        self._build()

    # -- construction -------------------------------------------------------

    def _build(self) -> None:
        self.ledger = ledger_module(self.index)
        if self.ledger is not None:
            self.sites = _sites_from_tree(self.ledger.ctx.tree) or {}
            self.site_set = frozenset(self.sites)
        else:
            self.site_set = installed_sanctioned_sites()
        for rp in sorted(self.index.by_relpath):
            mod = self.index.by_relpath[rp]
            self.maps[rp] = _ScopeMaps(mod)
            self._collect_twins(mod)
        for rp in sorted(self.index.by_relpath):
            self._collect_records(self.index.by_relpath[rp])
        for qual in sorted(self.index.functions):
            self.summary_of(qual)
        # module-level code (scripts, benches) runs at import time and
        # dispatches too — analyze each module body as a param-less
        # pseudo-function so VL501/VL502/VL503 cover script paths
        for rp in sorted(self.index.by_relpath):
            mod = self.index.by_relpath[rp]
            shim = FunctionInfo(
                qualname=mod.name, module=mod.name, relpath=rp,
                node=mod.ctx.tree, cls=None, parent=None, params=[],
                kwonly=[])
            self._analyze_fn(mod.name, shim)
        self._solve_params()
        self._emit_pending()
        self._check_ledger_drift()

    def _collect_twins(self, mod: ModuleInfo) -> None:
        for node in mod.ctx.tree.body:
            if isinstance(node, ast.Assign):
                donates = _twin_donates(node.value, mod)
                if donates is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.twins[f"{mod.name}.{t.id}"] = donates
        for qual in sorted(self.index.functions):
            fi = self.index.functions[qual]
            if fi.module != mod.name:
                continue
            for dec in fi.node.decorator_list:
                chain = attr_chain(dec)
                if chain and _expand_chain(chain, mod) in _JIT_NAMES:
                    self.twins.setdefault(qual, ())
                    continue
                donates = _twin_donates(dec, mod)
                if donates is not None:
                    self.twins[qual] = donates

    def _collect_records(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Call) and _is_record_copy(node):
                site = _record_site(node)
                if site is None:
                    self.nonliteral.append((mod.relpath, node))
                else:
                    self.record_sites.setdefault(site, []).append(
                        (mod.relpath, node.lineno))

    # -- twin lookup --------------------------------------------------------

    def _twin_ref(self, expr: ast.AST, mod: ModuleInfo) -> Optional[tuple]:
        """Donate tuple when ``expr`` references a known jit twin (by
        local name, alias, or dotted attribute)."""
        chain = attr_chain(expr)
        if not chain:
            return None
        dotted = _expand_chain(chain, mod)
        if dotted in self.twins:
            return self.twins[dotted]
        q = self.index.resolve_dotted(dotted)
        if q is not None and q in self.twins:
            return self.twins[q]
        if len(chain) == 1:
            local = f"{mod.name}.{chain[0]}"
            if local in self.twins:
                return self.twins[local]
        return None

    def _twin_value(self, value: ast.AST, env_twin: dict,
                    mod: ModuleInfo) -> Optional[tuple]:
        """Donate tuple when binding ``value`` to a name yields a callable
        that (maybe) donates — e.g. ``fn = donated if flag else plain``.
        Conditional bindings union both branches: maybe-donating counts."""
        if isinstance(value, ast.IfExp):
            a = self._twin_value(value.body, env_twin, mod)
            b = self._twin_value(value.orelse, env_twin, mod)
            if a is None and b is None:
                return None
            return tuple(sorted(set(a or ()) | set(b or ())))
        if isinstance(value, ast.Name) and value.id in env_twin:
            return env_twin[value.id]
        if isinstance(value, (ast.Name, ast.Attribute)):
            return self._twin_ref(value, mod)
        return None

    # -- function analysis --------------------------------------------------

    def summary_of(self, qual: str) -> FnSummary:
        got = self.summaries.get(qual)
        if got is not None:
            return got
        if qual in self._in_progress:  # recursion: weakest assumption
            return FnSummary()
        fi = self.index.functions.get(qual)
        if fi is None:
            return FnSummary()
        self._in_progress.add(qual)
        try:
            summary = self._analyze_fn(qual, fi)
        finally:
            self._in_progress.discard(qual)
        if qual in self.twins:  # jitted: result is a device array
            summary.returns = Prov(
                DEVICE, (f"device array from jit'd {fi.node.name}() "
                         f"({fi.relpath}:{fi.node.lineno})",))
            summary.ret_param = None
        self.summaries[qual] = summary
        return summary

    def _analyze_fn(self, qual: str, fi: FunctionInfo) -> FnSummary:
        mod = self.index.modules[fi.module]
        maps = self.maps[fi.relpath]
        summary = FnSummary()
        env: dict[str, Prov] = {
            p: Prov(f"param:{i}") for i, p in enumerate(fi.params)}
        env_twin: dict[str, tuple] = {}
        hot = any(p in mod.ctx.scope_dirs() for p in _HOT_PARTS)
        # one function-level pre-scan: a sanctioned record_copy
        # ANYWHERE in the body marks the whole function as an explicit
        # staging site (the VL501 exemption), order-independent
        for node in _walk_skip_defs(fi.node):
            if isinstance(node, ast.Call) and _is_record_copy(node):
                site = _record_site(node)
                if site is not None and site in self.site_set:
                    summary.sanctions.append((site, node.lineno))
        fn_sanctioned = bool(summary.sanctions)
        # ordered linear statement record for VL504 use-after-donate
        events: list = []  # (stmt, loads, stores)
        donated: list = []  # (var, event idx, chain)

        def site_of(node: ast.AST) -> str:
            return f"{fi.relpath}:{node.lineno}"

        def eval_expr(expr: ast.AST) -> Prov:
            if isinstance(expr, ast.Name):
                return env.get(expr.id, UNK)
            if isinstance(expr, ast.Call):
                return eval_call(expr)
            if isinstance(expr, ast.Subscript):
                base = eval_expr(expr.value)
                if base.tag in (POOLED, MVIEW):
                    return Prov(MVIEW, base.chain + (
                        f"sliced at {site_of(expr)}",))
                pv = _param_of(base)
                if pv is not None:
                    return Prov(f"paramview:{pv[0]}", base.chain)
                return base
            if isinstance(expr, ast.Attribute):
                base = eval_expr(expr.value)
                return base if base.tag == DEVICE else UNK
            if isinstance(expr, ast.IfExp):
                return join(eval_expr(expr.body), eval_expr(expr.orelse))
            if isinstance(expr, ast.BinOp):
                lt, rt = eval_expr(expr.left), eval_expr(expr.right)
                if DEVICE in (lt.tag, rt.tag):
                    return lt if lt.tag == DEVICE else rt
                return UNK
            if isinstance(expr, (ast.Starred, ast.Await)):
                return eval_expr(expr.value)
            return UNK

        def eval_call(call: ast.Call) -> Prov:
            if _is_pool_acquire(call):
                return Prov(POOLED, (
                    f"pooled buffer from acquire() at {site_of(call)}",))
            chain = attr_chain(call.func)
            if chain and chain[-1] == "memoryview" and call.args:
                inner = eval_expr(call.args[0])
                if inner.tag in (POOLED, MVIEW):
                    return Prov(MVIEW, inner.chain + (
                        f"memoryview at {site_of(call)}",))
                pv = _param_of(inner)
                if pv is not None:
                    return Prov(f"paramview:{pv[0]}", inner.chain)
                return UNK
            if _is_host_fetch(call, mod):
                return Prov(HOST, (f"np.asarray at {site_of(call)}",))
            twin = (self._twin_ref(call.func, mod)
                    if not isinstance(call.func, ast.Call) else None)
            if twin is None and isinstance(call.func, ast.Name):
                twin = env_twin.get(call.func.id)
            if twin is not None:
                return Prov(DEVICE, (
                    f"device array from jit twin at {site_of(call)}",))
            if _is_device_call(call, mod):
                return Prov(DEVICE, (
                    f"device array from "
                    f"{'.'.join(attr_chain(call.func) or ['jax'])} "
                    f"at {site_of(call)}",))
            mat = _materialization(call)
            if mat is not None:
                return Prov(HOST, (f"{mat[0]} at {site_of(call)}",))
            site = self.index.site_by_node.get(id(call))
            if site is not None and site.callee is not None:
                return self._call_result(call, site.callee, eval_expr,
                                         site_of(call))
            if isinstance(call.func, ast.Attribute):
                base = eval_expr(call.func.value)
                if base.tag == DEVICE and call.func.attr not in (
                        "item", "tobytes", "tolist"):
                    return base  # device method chain (.astype, .reshape)
            return UNK

        def scan_stmt(stmt: ast.stmt) -> None:
            """Findings + summary facts for every call the statement
            owns directly (compound bodies and nested defs excluded —
            the block walk / their own analyses cover those)."""
            for root in _scan_roots(stmt):
                nodes = [root, *_walk_skip_defs(root)]
                scan_stmt_nodes(stmt, nodes)

        def scan_stmt_nodes(stmt, nodes) -> None:
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                if _is_record_copy(node):
                    continue
                self._scan_materialization(node, stmt, maps, fi, qual,
                                           eval_expr)
                if hot and not fn_sanctioned:
                    self._scan_sync(node, mod, fi, eval_expr)
                self._scan_donation(node, mod, fi, summary, env_twin,
                                    donated, len(events), eval_expr)
                self._record_flows(node, qual, eval_expr)

        def walk_block(stmts: list) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                scan_stmt(stmt)
                events.append((stmt, _loads(stmt), _stores(stmt)))
                if isinstance(stmt, ast.Assign):
                    prov = eval_expr(stmt.value)
                    twin = self._twin_value(stmt.value, env_twin, mod)
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            env[t.id] = prov
                            if twin is not None:
                                env_twin[t.id] = twin
                            else:
                                env_twin.pop(t.id, None)
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    if stmt.value is not None and isinstance(
                            stmt.target, ast.Name):
                        env[stmt.target.id] = eval_expr(stmt.value)
                elif isinstance(stmt, ast.Return) and stmt.value is not None:
                    self._fold_return(summary, eval_expr(stmt.value))
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        if isinstance(item.optional_vars, ast.Name):
                            env[item.optional_vars.id] = eval_expr(
                                item.context_expr)
                    walk_block(stmt.body)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    walk_block(stmt.body)
                    walk_block(stmt.orelse)
                elif isinstance(stmt, (ast.If, ast.While)):
                    walk_block(stmt.body)
                    walk_block(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    walk_block(stmt.body)
                    for h in stmt.handlers:
                        walk_block(h.body)
                    walk_block(stmt.orelse)
                    walk_block(stmt.finalbody)

        walk_block(fi.node.body)
        self._check_use_after_donate(events, donated, fi)
        self._check_loop_dispatch(fi, mod, env_twin)
        return summary

    # -- statement scanners -------------------------------------------------

    def _scan_materialization(self, call, stmt, maps, fi, qual,
                              eval_expr) -> None:
        mat = _materialization(call)
        if mat is None:
            return
        kind, operand = mat
        prov = eval_expr(operand)
        pv = _param_of(prov)
        if prov.tag not in (POOLED, MVIEW) and pv is None:
            return
        block = maps.block_of(stmt) if stmt is not None else None
        if statement_sanctioned(stmt, block, self.site_set) is not None:
            return  # ledgered copy — the sanctioned kind
        desc = f"{kind} at {fi.relpath}:{call.lineno}"
        if pv is not None:
            self._pending.append(_Pending(qual, pv[0], call, fi.relpath,
                                          desc))
            return
        self.findings.append(finding_at(
            fi.relpath, call, "VL503",
            f"{kind} materializes a {prov.tag}-provenance buffer with "
            f"no sanctioned record_copy on the statement "
            f"[{_hops(prov.chain + (desc,))}] — ledger it "
            f"(record_copy(site, n), site in SANCTIONED_SITES) or keep "
            f"the view", severity="error"))

    def _scan_sync(self, call, mod, fi, eval_expr) -> None:
        f = call.func
        operand = None
        what = None
        if (isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS
                and len(call.args) == 1):
            operand, what = call.args[0], f"{f.id}()"
        elif isinstance(f, ast.Attribute) and f.attr == "item":
            operand, what = f.value, ".item()"
        elif _is_host_fetch(call, mod) and call.args:
            operand, what = call.args[0], "np.asarray()"
        if operand is None:
            return
        prov = eval_expr(operand)
        if prov.tag != DEVICE:
            return
        self.findings.append(finding_at(
            fi.relpath, call, "VL501",
            f"{what} on a device-provenance value forces an implicit "
            f"device->host sync in a hot scope "
            f"[{_hops(prov.chain)}] — batch the fetch at an explicit "
            f"staging site (a function that ledgers a sanctioned "
            f"record_copy) or keep the value on device",
            severity="error"))

    def _scan_donation(self, call, mod, fi, summary, env_twin, donated,
                       event_idx, eval_expr) -> None:
        twin = (self._twin_ref(call.func, mod)
                if not isinstance(call.func, ast.Call) else None)
        if twin is None and isinstance(call.func, ast.Name):
            twin = env_twin.get(call.func.id)
        idxs: list = []
        via = "jit twin"
        if twin:
            idxs = [i for i in twin if i < len(call.args)]
        else:
            site = self.index.site_by_node.get(id(call))
            if site is not None and site.callee is not None:
                s = self.summary_of(site.callee)
                if s.donates:
                    cfi = self.index.functions.get(site.callee)
                    offset = 1 if (cfi and cfi.cls and cfi.params
                                   and cfi.params[0] in ("self", "cls")
                                   and isinstance(call.func, ast.Attribute)
                                   ) else 0
                    idxs = [i - offset for i in s.donates
                            if 0 <= i - offset < len(call.args)]
                    via = f"helper {cfi.node.name}()" if cfi else "helper"
        for i in idxs:
            arg = call.args[i]
            hop = (f"donated to {via} at {fi.relpath}:{call.lineno}",)
            pv = _param_of(eval_expr(arg))
            if pv is not None:
                # donating a caller-supplied value: ride the summary so
                # the caller's variable is tracked across the hop
                summary.donates.setdefault(pv[0], hop)
            if isinstance(arg, ast.Name):
                donated.append((arg.id, event_idx, hop))

    def _record_flows(self, call, caller_qual, eval_expr) -> None:
        """Positional-arg provenance flowing into callee params — the
        edges the param-provenance fixpoint solves over."""
        site = self.index.site_by_node.get(id(call))
        if site is None or site.callee is None:
            return
        cfi = self.index.functions.get(site.callee)
        if cfi is None:
            return
        offset = 1 if (cfi.cls and cfi.params
                       and cfi.params[0] in ("self", "cls")
                       and isinstance(call.func, ast.Attribute)) else 0
        hop = (f"passed to {cfi.node.name}() at "
               f"{site.relpath}:{call.lineno}")
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            pidx = i + offset
            if pidx >= len(cfi.params):
                break
            prov = eval_expr(arg)
            slot = self._flows.setdefault((site.callee, pidx), [])
            pv = _param_of(prov)
            if pv is not None:
                slot.append(("param", caller_qual, pv[0], hop))
            elif prov.tag in (POOLED, MVIEW, DEVICE):
                slot.append(("const", Prov(prov.tag, prov.chain + (hop,))))

    # -- per-function post passes -------------------------------------------

    def _check_use_after_donate(self, events, donated, fi) -> None:
        for var, start, chain in donated:
            for stmt, loads, stores in events[start + 1:]:
                if var in stores and var not in loads:
                    break  # rebound before any read
                if var in loads:
                    node = next((n for n in ast.walk(stmt)
                                 if isinstance(n, ast.Name)
                                 and n.id == var), stmt)
                    self.findings.append(finding_at(
                        fi.relpath, node, "VL504",
                        f"'{var}' is read after being donated "
                        f"[{_hops(chain)}] — XLA may have reused its "
                        f"buffer; use the non-donating twin or rebuild "
                        f"the value from host data", severity="error"))
                    break
                if var in stores:
                    break

    def _trace_context(self, fi, mod) -> bool:
        """Is ``fi``'s body executed at trace time (so Python loops
        unroll into one compiled program, not per-item dispatches)?
        True for jitted functions and for closures handed to the
        ``jax.lax`` control-flow combinators (scan/while_loop bodies),
        walking up through lexically enclosing functions."""
        seen = set()
        qual = fi.qualname
        while qual is not None and qual not in seen:
            seen.add(qual)
            if qual in self.twins:
                return True
            cur = self.index.functions.get(qual)
            if cur is None or cur.parent is None:
                return False
            parent = self.index.functions.get(cur.parent)
            if parent is not None:
                for call in _walk_skip_defs(parent.node):
                    if not isinstance(call, ast.Call):
                        continue
                    chain = attr_chain(call.func)
                    if not chain or not _expand_chain(chain, mod).startswith(
                            "jax.lax."):
                        continue
                    for a in list(call.args) + [kw.value
                                                for kw in call.keywords]:
                        if isinstance(a, ast.Name) \
                                and a.id == cur.node.name:
                            return True
            qual = cur.parent
        return False

    def _check_loop_dispatch(self, fi, mod, env_twin) -> None:
        if self._trace_context(fi, mod):
            return
        for loop in _walk_skip_defs(fi.node):
            if isinstance(loop, (ast.For, ast.AsyncFor)):
                if _const_iterable(loop.iter):
                    continue  # structural unroll over a literal
                tainted = _target_names(loop.target)
                body: list = loop.body
            elif isinstance(loop, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                tainted = set()
                for gen in loop.generators:
                    if not _const_iterable(gen.iter):
                        tainted |= _target_names(gen.target)
                body = []
            else:
                continue
            if not tainted:
                continue
            exprs: list = []
            for stmt in body:
                for node in [stmt, *_walk_skip_defs(stmt)]:
                    if isinstance(node, ast.Assign) and (
                            _names_in(node.value) & tainted):
                        for t in node.targets:
                            tainted |= _target_names(t)
                    if isinstance(node, ast.Call):
                        exprs.append(node)
            if not body:  # comprehension: scan its element/conditions
                exprs = [n for n in ast.walk(loop)
                         if isinstance(n, ast.Call)]
            for call in exprs:
                chain = attr_chain(call.func)
                is_dispatch = bool(chain) and _is_dispatch_chain(chain, mod)
                if not is_dispatch:
                    twin = (self._twin_ref(call.func, mod) if chain
                            else None)
                    if twin is None and isinstance(call.func, ast.Name):
                        twin = env_twin.get(call.func.id)
                    is_dispatch = twin is not None
                if not is_dispatch:
                    continue
                args_names: set = set()
                for a in list(call.args) + [kw.value
                                            for kw in call.keywords]:
                    args_names |= _names_in(a)
                if args_names & tainted:
                    self.findings.append(finding_at(
                        fi.relpath, call, "VL502",
                        f"device dispatch inside a per-item Python loop "
                        f"(operand derives from loop variable "
                        f"{sorted(args_names & tainted)}) — batch the "
                        f"items into one padded dispatch "
                        f"(ops/segment.py batched kernels) or hoist it "
                        f"out of the loop", severity="error"))

    # -- interprocedural solving --------------------------------------------

    def _fold_return(self, summary: FnSummary, prov: Prov) -> None:
        pv = _param_of(prov)
        if pv is not None:
            summary.ret_param, summary.ret_view = pv[0], pv[1]
            return
        summary.returns = join(summary.returns, prov)

    def _call_result(self, call, callee, eval_expr, site_desc) -> Prov:
        s = self.summary_of(callee)
        if s.ret_param is not None:
            cfi = self.index.functions.get(callee)
            offset = 1 if (cfi and cfi.cls and cfi.params
                           and cfi.params[0] in ("self", "cls")
                           and isinstance(call.func, ast.Attribute)) else 0
            i = s.ret_param - offset
            if 0 <= i < len(call.args):
                arg = eval_expr(call.args[i])
                if s.ret_view and arg.tag in (POOLED, MVIEW):
                    return Prov(MVIEW, arg.chain + (
                        f"viewed by callee at {site_desc}",))
                pv = _param_of(arg)
                if s.ret_view and pv is not None:
                    return Prov(f"paramview:{pv[0]}", arg.chain)
                return arg
        if s.returns.tag != UNKNOWN:
            return Prov(s.returns.tag, s.returns.chain)
        return UNK

    def _solve_params(self) -> None:
        """Monotone fixpoint over the arg→param flow edges: concrete
        provenance seeds, symbolic edges forward it caller→callee."""
        changed = True
        while changed:
            changed = False
            for key in sorted(self._flows):
                cur = self.param_prov.get(key, UNK)
                best = cur
                for contrib in self._flows[key]:
                    if contrib[0] == "const":
                        best = join(best, contrib[1])
                    else:
                        _, src_qual, src_idx, hop = contrib
                        src = self.param_prov.get((src_qual, src_idx), UNK)
                        if src.tag in (POOLED, MVIEW, DEVICE):
                            best = join(best, Prov(
                                src.tag, src.chain + (hop,)))
                if best.tag != cur.tag:
                    self.param_prov[key] = best
                    changed = True

    def _emit_pending(self) -> None:
        for p in self._pending:
            prov = self.param_prov.get((p.qual, p.idx), UNK)
            if prov.tag not in (POOLED, MVIEW):
                continue
            self.findings.append(finding_at(
                p.relpath, p.node, "VL503",
                f"materialization of a {prov.tag}-provenance parameter "
                f"with no sanctioned record_copy on the statement "
                f"[{_hops(prov.chain + (p.desc,))}] — ledger it "
                f"(record_copy(site, n), site in SANCTIONED_SITES) or "
                f"keep the view", severity="error"))

    def _check_ledger_drift(self) -> None:
        if self.ledger is None:
            return  # no copyledger in the linted tree — VL505 is moot
        for relpath, node in sorted(self.nonliteral,
                                    key=lambda t: (t[0], t[1].lineno)):
            self.findings.append(finding_at(
                relpath, node, "VL505",
                "record_copy site is not a string literal — sites are "
                "Prometheus label values and must be auditable "
                "statically; pass a literal dotted lowercase name",
                severity="error"))
        for site in sorted(self.record_sites):
            if site in self.site_set:
                continue
            first = self._first_record_node(site)
            if first is not None:
                self.findings.append(finding_at(
                    first[0], first[1], "VL505",
                    f"record_copy site '{site}' is not in "
                    f"obs.SANCTIONED_SITES — adding a copy site is a "
                    f"reviewed change: add it to the frozenset with a "
                    f"reason", severity="error"))
        for site in sorted(self.site_set):
            if site not in self.record_sites:
                elt = self.sites.get(site)
                if elt is None:
                    continue
                self.findings.append(finding_at(
                    self.ledger.relpath, elt, "VL505",
                    f"sanctioned site '{site}' has no record_copy call "
                    f"site — the ledger entry is dead; remove it or "
                    f"restore the call", severity="error"))

    def _first_record_node(self, site: str) -> Optional[tuple]:
        for rp in sorted(self.index.by_relpath):
            mod = self.index.by_relpath[rp]
            for node in ast.walk(mod.ctx.tree):
                if (isinstance(node, ast.Call) and _is_record_copy(node)
                        and _record_site(node) == site):
                    return rp, node
        return None


def _target_names(t: ast.AST) -> set:
    out: set = set()
    for n in ast.walk(t):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _names_in(expr: ast.AST) -> set:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _scan_roots(stmt: ast.stmt) -> list:
    """The expression parts a statement owns directly.  Compound
    statements own only their headers (test / iter / context
    managers) — their bodies are separate statements the block walk
    visits on its own, so scanning the whole compound node would
    double-report every call inside it."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _loads(stmt: ast.stmt) -> set:
    out: set = set()
    for root in _scan_roots(stmt):
        for n in [root, *_walk_skip_defs(root)]:
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
    return out


def _stores(stmt: ast.stmt) -> set:
    out: set = set()
    for root in _scan_roots(stmt):
        for n in [root, *_walk_skip_defs(root)]:
            if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                      (ast.Store, ast.Del)):
                out.add(n.id)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        out |= _target_names(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for i in stmt.items:
            if i.optional_vars is not None:
                out |= _target_names(i.optional_vars)
    return out


_MODELS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def model_for(index: ProjectIndex) -> BufModel:
    model = _MODELS.get(index)
    if model is None:
        model = BufModel(index)
        _MODELS[index] = model
    return model


# -- rules -------------------------------------------------------------------


class _BufRule:
    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        for f in model_for(index).findings:
            if f.code == self.code:
                yield f


class HostSyncRule(_BufRule):
    code = "VL501"
    name = "implicit-host-sync"
    severity = "error"
    description = ("float()/int()/bool()/.item()/np.asarray() on a "
                   "device-provenance value in engine/, ops/ or repo/ "
                   "outside an explicit (ledgered) staging site")


class LoopDispatchRule(_BufRule):
    code = "VL502"
    name = "per-item-device-dispatch"
    severity = "error"
    description = ("jnp/lax/jit-twin call inside a per-item Python loop "
                   "with an operand derived from the loop variable — "
                   "batch it (the PR 6/13 kernels exist for this)")


class SemanticCopyRule(_BufRule):
    code = "VL503"
    name = "unledgered-pooled-copy"
    severity = "error"
    description = ("bytes()/.tobytes()/b\"\".join over a pooled-buffer "
                   "or memoryview-of-pooled value (tracked "
                   "interprocedurally) without a sanctioned "
                   "record_copy on the statement")


class UseAfterDonateRule(_BufRule):
    code = "VL504"
    name = "use-after-donate"
    severity = "error"
    description = ("value passed to a donated-argument jit twin "
                   "(directly, via a helper, or via a conditional twin "
                   "binding) and read again — XLA may have reused the "
                   "buffer")


class LedgerDriftRule(_BufRule):
    code = "VL505"
    name = "ledger-sanction-drift"
    severity = "error"
    description = ("record_copy site missing from SANCTIONED_SITES, "
                   "non-literal site name, or a sanctioned site with "
                   "no remaining call site")


def default_buf_rules() -> list:
    return [HostSyncRule(), LoopDispatchRule(), SemanticCopyRule(),
            UseAfterDonateRule(), LedgerDriftRule()]


# -- cache fact kind ---------------------------------------------------------


def summaries_for(index: ProjectIndex) -> dict[str, dict]:
    """Per-file buffer-provenance facts — the cached "buf" fact kind.
    A file's summary changes iff its provenance-relevant surface
    (returns, donations, sanction sites, ledger records) changes, so
    the cache layer can replay clean files verbatim."""
    model = model_for(index)
    out: dict[str, dict] = {}

    def slot(relpath: str) -> dict:
        return out.setdefault(relpath, {"prov": {}, "donates": {},
                                        "sanctions": [], "records": []})

    for qual in sorted(model.summaries):
        fi = index.functions.get(qual)
        if fi is None:
            continue
        s = model.summaries[qual]
        entry = slot(fi.relpath)
        ret = (f"param:{s.ret_param}{'(view)' if s.ret_view else ''}"
               if s.ret_param is not None else s.returns.tag)
        if ret != UNKNOWN or s.donates or s.sanctions:
            entry["prov"][qual] = ret
        if s.donates:
            entry["donates"][qual] = sorted(s.donates)
        for site, lineno in sorted(s.sanctions):
            entry["sanctions"].append([site, lineno])
    for site in sorted(model.record_sites):
        for relpath, lineno in model.record_sites[site]:
            slot(relpath)["records"].append([site, lineno])
    return out


# -- provenance export & bridge helpers --------------------------------------


def sanction_sites(index: ProjectIndex) -> dict[str, list]:
    """{site: [(relpath, lineno), ...]} of statically discovered,
    SANCTIONED record_copy call sites — the static half of the
    runtime⊆static ledger bridge (tests/test_analysis_buf.py)."""
    model = model_for(index)
    return {site: list(model.record_sites[site])
            for site in sorted(model.record_sites)
            if site in model.site_set}


def provenance_json(index: ProjectIndex) -> dict:
    """Per-site provenance facts as plain JSON for offline diffing —
    nodes are functions with non-trivial provenance surface, edges are
    the arg→param hops the fixpoint solved over."""
    model = model_for(index)
    nodes = []
    for qual in sorted(model.summaries):
        s = model.summaries[qual]
        fi = index.functions.get(qual)
        ret = (f"param:{s.ret_param}{'(view)' if s.ret_view else ''}"
               if s.ret_param is not None else s.returns.tag)
        if ret == UNKNOWN and not s.donates and not s.sanctions:
            continue
        nodes.append({
            "fn": qual, "file": fi.relpath if fi else "?",
            "returns": ret, "donates": sorted(s.donates),
            "sanctions": sorted({site for site, _ in s.sanctions})})
    edges = []
    for (callee, idx) in sorted(model._flows):
        prov = model.param_prov.get((callee, idx), UNK)
        if prov.tag == UNKNOWN:
            continue
        edges.append({"to": callee, "param": idx, "prov": prov.tag,
                      "via": list(prov.chain)})
    return {
        "sanctioned_sites": {
            site: [f"{rp}:{ln}" for rp, ln in entries]
            for site, entries in sanction_sites(index).items()},
        "nodes": nodes,
        "edges": edges,
    }


def _index_for_paths(paths) -> ProjectIndex:
    from volsync_tpu.analysis.callgraph import build_index
    from volsync_tpu.analysis.engine import (
        FileContext,
        iter_py_files,
        relativize,
    )

    contexts = []
    for path in iter_py_files(paths):
        relpath = relativize(path)
        try:
            source = path.read_bytes().decode("utf-8")
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError, ValueError):
            continue  # the lint run proper reports parse errors
        contexts.append(FileContext(path, relpath, source, tree))
    return build_index(contexts)


def dump_for_paths(paths) -> dict:
    """Build the provenance export for a path set from scratch — the
    ``volsync lint --dump-provenance`` entry point."""
    return provenance_json(_index_for_paths(paths))


def sanction_sites_for_paths(paths) -> dict[str, list]:
    """The static sanction-site map for a path set — what the tier-1
    runtime⊆static bridge test checks ``copies_by_site()`` against."""
    return sanction_sites(_index_for_paths(paths))
