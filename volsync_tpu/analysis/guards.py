"""Guarded-field race inference (VL402/VL403/VL404).

RacerD-style ownership analysis over the lock model built by
``analysis/lockflow.py``:

* **VL402 guarded-field-race** — for each ``self._field`` of a class
  that creates lockcheck locks, infer the owning lock from the
  majority of guarded accesses (guarded on ≥ 2 accesses and on more
  than half of them), then flag accesses that skip the guard while
  being reachable from a thread entry point (``threading.Thread``
  targets, ``executor.submit`` callables, gRPC ``*Servicer`` methods).
  ``__init__`` is exempt: the object is not published yet.  A
  ``lockcheck.assert_held(self._lock, ...)`` statement in a function
  body counts as holding that lock from that line on — the checked
  way to write a caller-holds-the-lock helper (runtime-enforced under
  VOLSYNC_TPU_LOCKCHECK, statically trusted here, unlike a comment).

* **VL403 check-then-act** — a field read under a lock into a local,
  the lock released, and a *dependent* write (the stale local feeds
  the written value or a branch guarding it) re-acquiring the same
  lock later in the same function: the classic lost-update / TOCTOU
  window.

* **VL404 unsynchronized-publication** — a mutable container
  (dict/list/set/deque) attribute of a class whose methods run on a
  started thread or pool, accessed with no lock held *anywhere*: the
  field crosses the thread seam with no common guard at all.  (When a
  majority guard exists this is VL402's territory instead.)

All three share one pass per ProjectIndex (memoized weakly, like
shapes.py), and the per-class field/guard statistics are exported as
part of the cached "locks" fact kind.
"""

from __future__ import annotations

import ast
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Iterator, Optional

from volsync_tpu.analysis.callgraph import (
    ProjectIndex,
    attr_chain,
)
from volsync_tpu.analysis.engine import Finding, finding_at
from volsync_tpu.analysis.iprules import _LOCK_CTORS, _dotted_for
from volsync_tpu.analysis.lockflow import fn_label, model_for

# containers whose in-place mutation is NOT atomic across threads
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}
# internally-synchronized primitives: fields holding these are not
# shared *data*, they ARE the synchronization
_SYNC_CTORS = {"Event", "Condition", "Semaphore", "BoundedSemaphore",
               "Barrier", "Thread", "Timer", "Queue", "SimpleQueue",
               "LifoQueue", "PriorityQueue"} | _LOCK_CTORS
_MUTATOR_METHODS = {"append", "appendleft", "extend", "extendleft",
                    "insert", "pop", "popleft", "popitem", "remove",
                    "discard", "clear", "update", "setdefault", "add",
                    "sort", "reverse", "rotate"}


# -- thread entry points -----------------------------------------------------


def thread_roots(index: ProjectIndex) -> dict[str, str]:
    """{function qualname: reason} for code that runs off the creating
    thread: Thread targets, executor-submitted callables, and gRPC
    servicer methods."""
    roots: dict[str, str] = {}

    def add(qual: Optional[str], reason: str) -> None:
        if qual is not None:
            roots.setdefault(qual, reason)

    for caller in sorted(index.calls):
        for site in index.calls[caller]:
            call = site.node
            chain = attr_chain(call.func)
            if not chain:
                continue
            where = f"{site.relpath}:{site.lineno}"
            if chain[-1] == "Thread":
                target = next((kw.value for kw in call.keywords
                               if kw.arg == "target"), None)
                if target is not None:
                    q = _resolve_ref(index, target, site)
                    add(q, f"Thread target at {where}")
            elif chain[-1] == "submit" and call.args:
                q = _resolve_ref(index, call.args[0], site)
                add(q, f"executor submit at {where}")
    for cq in sorted(index.classes):
        ci = index.classes[cq]
        if any(_base_name(b).endswith("Servicer") for b in ci.base_exprs):
            for fi in ci.methods.values():
                add(fi.qualname, f"gRPC handler on {cq}")
    return roots


def _base_name(expr: ast.expr) -> str:
    chain = attr_chain(expr)
    return chain[-1] if chain else ""


def _resolve_ref(index: ProjectIndex, expr: ast.expr, site) -> Optional[str]:
    """Resolve a callable *reference* (not a call): ``self._run``, a
    local function name, or a dotted module path."""
    chain = attr_chain(expr)
    if not chain:
        return None
    mod = index.by_relpath.get(site.relpath)
    caller_fi = index.functions.get(site.caller)
    if chain[0] in ("self", "cls") and len(chain) == 2:
        cq = caller_fi.cls if caller_fi else None
        ci = index.classes.get(cq) if cq else None
        return index._method_on_class(ci, chain[1]) if ci else None
    if len(chain) == 1:
        if caller_fi and chain[0] in caller_fi.nested:
            return caller_fi.nested[chain[0]]
        return mod.functions.get(chain[0]) if mod else None
    if mod is None:
        return None
    dotted = _dotted_for(mod, chain) or ".".join(chain)
    return index.resolve_dotted(dotted)


def thread_reachable(index: ProjectIndex) -> dict[str, str]:
    """Forward call-graph closure from the thread roots (including
    calls through typed fields the lock model resolved):
    {qualname: reason it runs on a foreign thread}."""
    extra = model_for(index).extra_calls
    reach = dict(thread_roots(index))
    work = deque(sorted(reach))
    while work:
        qual = work.popleft()
        callees = {site.callee for site in index.calls.get(qual, ())}
        callees |= extra.get(qual, set())
        for callee in sorted(c for c in callees if c is not None):
            if callee not in reach:
                reach[callee] = reach[qual]
                work.append(callee)
    return reach


# -- field access collection -------------------------------------------------


@dataclass
class Access:
    cls: str  # lexical class qualname
    field: str
    method: str  # method qualname ("" when unresolved)
    relpath: str
    node: ast.Attribute
    kind: str  # "read" | "write"
    held: frozenset  # lock names held at the access


class _Analysis:
    """One shared pass: accesses, inference, findings for 402/403/404."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.model = model_for(index)
        self.reach = thread_reachable(index)
        self.findings: list[tuple[str, Finding]] = []
        self._held: dict[str, dict[int, frozenset]] = {}
        # cls -> field -> [Access]; __init__ accesses excluded
        self.acc: dict[str, dict[str, list[Access]]] = {}
        # cls -> field -> (__init__ Assign node, container kind)
        self.containers: dict[str, dict[str, tuple]] = {}
        self._collect()
        self._infer_vl402_vl404()
        self._check_vl403()

    # -- plumbing -----------------------------------------------------------

    def held_at(self, relpath: str, node: ast.AST) -> frozenset:
        if relpath not in self._held:
            self._held[relpath] = self.model.held_map(relpath)
        return self._held[relpath].get(id(node), frozenset())

    def _family(self, cq: str) -> list[str]:
        """cq plus all (resolved) ancestors, breadth-first."""
        out, queue = [], deque([cq])
        seen: set[str] = set()
        while queue:
            q = queue.popleft()
            if q in seen:
                continue
            seen.add(q)
            out.append(q)
            ci = self.index.classes.get(q)
            if ci:
                queue.extend(ci.bases)
        return out

    def _is_method_name(self, cq: str, attr: str) -> bool:
        ci = self.index.classes.get(cq)
        return bool(ci and self.index._method_on_class(ci, attr))

    def _sync_fields(self, cq: str) -> set:
        """Fields of ``cq``'s family holding locks or synchronized
        primitives — excluded from data-race inference."""
        out: set = set()
        for q in self._family(cq):
            out |= set(self.model.class_locks.get(q, ()))
            init = self.index.classes.get(q, None)
            init_fi = init.methods.get("__init__") if init else None
            if init_fi is None:
                continue
            for sub in ast.walk(init_fi.node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                value = sub.value
                if not isinstance(value, ast.Call):
                    continue
                chain = attr_chain(value.func)
                if not chain or chain[-1] not in _SYNC_CTORS:
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
        return out

    # -- collection ---------------------------------------------------------

    def _collect(self) -> None:
        for cq in sorted(self.index.classes):
            ci = self.index.classes[cq]
            if not any(self.model.class_locks.get(q) or
                       self.containers.get(q)
                       for q in self._family(cq)) \
                    and not self._class_has_locks_or_threads(ci):
                continue
            sync = self._sync_fields(cq)
            self._collect_containers(cq, ci)
            for mname in sorted(ci.methods):
                fi = ci.methods[mname]
                if mname in ("__init__", "__new__", "__post_init__"):
                    continue
                maps = self.model.maps.get(fi.relpath)
                if maps is None:
                    continue
                asserted = self._asserted_locks(cq, fi)
                for node in ast.walk(fi.node):
                    if not (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        continue
                    attr = node.attr
                    if attr in sync or self._is_method_name(cq, attr):
                        continue
                    kind = self._access_kind(node, maps)
                    if kind is None:
                        continue
                    held = self.held_at(fi.relpath, node)
                    if asserted:
                        held = held | frozenset(
                            name for name, line in asserted
                            if node.lineno >= line)
                    self.acc.setdefault(cq, {}).setdefault(attr, []).append(
                        Access(cq, attr, fi.qualname, fi.relpath, node,
                               kind, held))

    def _asserted_locks(self, cq: str, fi) -> list[tuple[str, int]]:
        """``lockcheck.assert_held(self.<lockattr>, ...)`` statements
        directly in the function body: each makes its lock count as
        held from that line to the end of the function — the checked
        precondition idiom for caller-holds-the-lock helpers."""
        out: list[tuple[str, int]] = []
        for stmt in fi.node.body:
            if not (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)):
                continue
            call = stmt.value
            chain = attr_chain(call.func)
            if not chain or chain[-1] != "assert_held" or not call.args:
                continue
            arg = call.args[0]
            if (isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"):
                name = self.model.resolve_self_lock(cq, arg.attr)
                if name is not None:
                    out.append((name, stmt.lineno))
        return out

    def _class_has_locks_or_threads(self, ci) -> bool:
        """Classes with no lock anywhere in the family still matter to
        VL404 when they put work on a thread (gc/scrub services)."""
        return any(fi.qualname in self.reach for fi in ci.methods.values())

    def _collect_containers(self, cq: str, ci) -> None:
        init_fi = ci.methods.get("__init__")
        if init_fi is None:
            return
        for sub in ast.walk(init_fi.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            kind = self._container_kind(sub.value)
            if kind is None:
                continue
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    self.containers.setdefault(cq, {})[t.attr] = (sub, kind)

    @staticmethod
    def _container_kind(value: Optional[ast.AST]) -> Optional[str]:
        if isinstance(value, ast.Dict):
            return "dict"
        if isinstance(value, (ast.List, ast.ListComp)):
            return "list"
        if isinstance(value, (ast.Set, ast.SetComp)):
            return "set"
        if isinstance(value, ast.Call):
            chain = attr_chain(value.func)
            if chain and chain[-1] in _MUTABLE_CTORS:
                return chain[-1]
        return None

    def _access_kind(self, node: ast.Attribute, maps) -> Optional[str]:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return "write"
        parent = maps.parent.get(id(node))
        # self.f[k] = v / del self.f[k] — container mutation
        if (isinstance(parent, ast.Subscript) and parent.value is node
                and isinstance(parent.ctx, (ast.Store, ast.Del))):
            return "write"
        # self.f.append(x) etc — container mutation through a method
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _MUTATOR_METHODS):
            gp = maps.parent.get(id(parent))
            if isinstance(gp, ast.Call) and gp.func is parent:
                return "write"
        return "read"

    # -- VL402 + VL404 ------------------------------------------------------

    def _family_accesses(self, cq: str, field: str) -> list:
        out: list = []
        for q in self._family(cq):
            out.extend(self.acc.get(q, {}).get(field, ()))
        return out

    def _majority_lock(self, accesses: list) -> Optional[tuple]:
        """(lock, guarded, total) when one lock guards ≥ 2 accesses
        and more than half of them — the inferred owner."""
        counts: dict[str, int] = {}
        for a in accesses:
            for lk in a.held:
                counts[lk] = counts.get(lk, 0) + 1
        if not counts:
            return None
        lock = max(sorted(counts), key=lambda k: counts[k])
        guarded, total = counts[lock], len(accesses)
        if guarded >= 2 and guarded * 2 > total:
            return lock, guarded, total
        return None

    def _infer_vl402_vl404(self) -> None:
        for cq in sorted(self.acc):
            for field in sorted(self.acc[cq]):
                fam = self._family_accesses(cq, field)
                owner = self._majority_lock(fam)
                if owner is not None:
                    self._flag_vl402(cq, field, fam, owner)
        for cq in sorted(self.containers):
            for field in sorted(self.containers[cq]):
                self._flag_vl404(cq, field)

    def _flag_vl402(self, cq: str, field: str, fam: list,
                    owner: tuple) -> None:
        lock, guarded, total = owner
        cls_label = cq.rsplit(".", 1)[-1]
        for a in self.acc[cq].get(field, ()):  # own accesses only —
            # ancestor accesses get flagged under their own class
            if lock in a.held:
                continue
            reason = self.reach.get(a.method)
            if reason is None:
                continue
            self.findings.append(("VL402", finding_at(
                a.relpath, a.node, "VL402",
                f"field '{field}' of {cls_label} is guarded by "
                f"'{lock}' on {guarded}/{total} accesses but {a.kind} "
                f"here without it, on a path threads run "
                f"({reason}) — hold '{lock}' or document why this "
                f"access is safe", severity="error")))

    def _flag_vl404(self, cq: str, field: str) -> None:
        fam = self._family_accesses(cq, field)
        if len(fam) < 2 or any(a.held for a in fam):
            return  # guarded somewhere: VL402's territory
        threaded = [a for a in fam if a.method in self.reach]
        if not threaded:
            return
        node, kind = self.containers[cq][field]
        reason = self.reach[threaded[0].method]
        cls_label = cq.rsplit(".", 1)[-1]
        self.findings.append(("VL404", finding_at(
            self._relpath_of_class(cq), node, "VL404",
            f"mutable {kind} '{field}' of {cls_label} crosses a "
            f"thread seam ({reason}) with no lock on any of its "
            f"{len(fam)} accesses — all of "
            f"{sorted({fn_label(self.index, a.method) for a in fam})} "
            f"touch it unsynchronized; guard it with one lock",
            severity="warning")))

    def _relpath_of_class(self, cq: str) -> str:
        ci = self.index.classes.get(cq)
        mod = self.index.modules.get(ci.module) if ci else None
        return mod.relpath if mod else ""

    # -- VL403 --------------------------------------------------------------

    def _check_vl403(self) -> None:
        by_fn: dict[str, list] = {}
        for region in self.model.regions:
            by_fn.setdefault(region.func, []).append(region)
        for func in sorted(by_fn):
            regions = sorted(by_fn[func],
                             key=lambda r: r.header.lineno)
            if len(regions) < 2:
                continue
            live = [set(map(id, self._live_nodes(r))) for r in regions]
            for i, ri in enumerate(regions):
                taint = self._tainted_locals(ri)
                if not taint:
                    continue
                for j in range(i + 1, len(regions)):
                    rj = regions[j]
                    if rj.lock != ri.lock or id(rj.header) in live[i]:
                        continue  # different lock, or never released
                    self._flag_vl403(ri, rj, taint)

    def _live_nodes(self, region) -> Iterator[ast.AST]:
        for stmt in region.body:
            yield from self.model._iter_live(stmt)

    def _tainted_locals(self, region) -> dict[str, tuple]:
        """{local name: (field, read Attribute node)} for locals that
        snapshot a self-field inside the region."""
        taint: dict[str, tuple] = {}
        for node in self._live_nodes(region):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            for sub in ast.walk(node.value):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and isinstance(sub.ctx, ast.Load)):
                    taint[node.targets[0].id] = (sub.attr, sub)
                    break
        return taint

    def _flag_vl403(self, ri, rj, taint: dict) -> None:
        maps = self.model.maps.get(rj.relpath)
        for node in self._live_nodes(rj):
            target = None
            if isinstance(node, ast.Assign):
                target = node.targets[0] if len(node.targets) == 1 else None
            elif isinstance(node, ast.AugAssign):
                target = node.target
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            field = target.attr
            stale = [(name, n) for name, (f, n) in taint.items()
                     if f == field]
            if not stale:
                continue
            names = {name for name, _ in stale}
            if not (self._uses(node.value, names)
                    or self._branch_uses(node, maps, names)):
                continue
            name = sorted(names)[0]
            self.findings.append(("VL403", finding_at(
                rj.relpath, node, "VL403",
                f"check-then-act on field '{field}': snapshot into "
                f"'{name}' under '{ri.lock}' at line "
                f"{taint[name][1].lineno}, lock released, and this "
                f"dependent write re-acquires '{rj.lock}' — another "
                f"thread can update '{field}' in the window; widen "
                f"the critical section or re-validate under the lock",
                severity="error")))
            return  # one finding per region pair keeps the noise down

    @staticmethod
    def _uses(expr: Optional[ast.AST], names: set) -> bool:
        if expr is None:
            return False
        return any(isinstance(n, ast.Name) and n.id in names
                   and isinstance(n.ctx, ast.Load)
                   for n in ast.walk(expr))

    def _branch_uses(self, node: ast.AST, maps, names: set) -> bool:
        """Is the write guarded by an if/while whose test reads the
        stale snapshot? (the 'act' of check-then-act)"""
        if maps is None:
            return False
        for anc in maps.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(anc, (ast.If, ast.While)) \
                    and self._uses(anc.test, names):
                return True
        return False


_ANALYSES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _analysis_for(index: ProjectIndex) -> _Analysis:
    a = _ANALYSES.get(index)
    if a is None:
        a = _Analysis(index)
        _ANALYSES[index] = a
    return a


def field_summaries(index: ProjectIndex) -> dict[str, dict]:
    """Per-file guarded-field statistics for the cached "locks" fact
    kind: {relpath: {"Class.field": {"guarded": {lock: n},
    "total": n}}}."""
    a = _analysis_for(index)
    out: dict[str, dict] = {}
    for cq in sorted(a.acc):
        for field in sorted(a.acc[cq]):
            accesses = a.acc[cq][field]
            counts: dict[str, int] = {}
            for acc in accesses:
                for lk in sorted(acc.held):
                    counts[lk] = counts.get(lk, 0) + 1
            key = f"{cq.rsplit('.', 1)[-1]}.{field}"
            relpath = accesses[0].relpath
            out.setdefault(relpath, {})[key] = {
                "guarded": dict(sorted(counts.items())),
                "total": len(accesses)}
    return out


class _GuardRule:
    severity = "error"

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        for code, finding in _analysis_for(index).findings:
            if code == self.code:
                yield finding


class GuardedFieldRule(_GuardRule):
    """VL402 — majority-guarded field accessed without its lock."""

    code = "VL402"
    name = "guarded-field-race"
    description = ("a field guarded by one lock on most accesses is "
                   "read/written without it on a thread-reachable path")


class CheckThenActRule(_GuardRule):
    """VL403 — lock released between a snapshot and a dependent write."""

    code = "VL403"
    name = "check-then-act"
    description = ("guarded read, lock released, dependent write "
                   "re-acquires the lock: lost-update / TOCTOU window")


class UnsyncPublicationRule(_GuardRule):
    """VL404 — mutable container crosses the thread seam unguarded."""

    code = "VL404"
    name = "unsynchronized-publication"
    severity = "warning"
    description = ("a dict/list/set/deque attribute is handed to a "
                   "started thread or pool with no common guard")
