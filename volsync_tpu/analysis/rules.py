"""Lint rules enforcing volsync-tpu's stated-but-unenforced invariants.

Each rule is a class with ``code``/``name``/``description`` and a
``check(ctx) -> Iterator[Finding]``. Codes are stable (they appear in
baselines and suppression comments):

VL001  VOLSYNC_* env reads outside envflags.py
VL002  gated third-party imports (zstandard, cryptography) outside shim
VL003  broad except that swallows silently (no log / re-raise)
VL004  tracer-unsafe host ops inside jit'd functions (ops/ kernels)
VL005  direct threading.Lock/RLock in data-plane modules (bypasses
       lockcheck instrumentation)
VL105  ad-hoc retry: time.sleep inside an except handler or a retry
       loop (a for/while containing a try) outside resilience.py —
       route through resilience.RetryPolicy
VL106  hot-path byte copies: ``.tobytes()``, ``bytes(<buffer>)``, or a
       ``b"".join(...)`` in the zero-copy data plane (engine/, ops/,
       repo/) — the paths whose copies the ledger
       (obs/copyledger.py) accounts; sanctioned sites carry a
       reasoned ``# lint: ignore[VL106]`` next to their record_copy
VL301  span/trace names must be literal, dotted, lowercase strings at
       the call site (no f-strings/concatenation/variables) — span
       names become Prometheus label values, so dynamic names are
       unbounded metric cardinality
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from volsync_tpu.analysis.engine import FileContext, Finding, finding_at

_BROAD_EXC = {"Exception", "BaseException"}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class EnvFlagRule:
    """All VOLSYNC_* environment reads go through envflags.py — one
    falsy-token set, one catalogue of operator knobs."""

    code = "VL001"
    name = "env-flag-centralized"
    description = ("os.environ/os.getenv read of a VOLSYNC_* key outside "
                   "envflags.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.in_module("envflags.py"):
            return
        os_names: set[str] = set()
        environ_names: set[str] = set()
        getenv_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "os":
                        os_names.add(alias.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for alias in node.names:
                    if alias.name == "environ":
                        environ_names.add(alias.asname or "environ")
                    elif alias.name == "getenv":
                        getenv_names.add(alias.asname or "getenv")

        def is_environ(node: ast.AST) -> bool:
            if isinstance(node, ast.Attribute) and node.attr == "environ":
                return (isinstance(node.value, ast.Name)
                        and node.value.id in os_names)
            return isinstance(node, ast.Name) and node.id in environ_names

        def volsync_key(node: ast.AST) -> Optional[str]:
            s = _const_str(node)
            if s is not None and s.startswith("VOLSYNC"):
                return s
            return None

        for node in ast.walk(ctx.tree):
            key = None
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in ("get", "pop", "setdefault")
                        and is_environ(f.value) and node.args):
                    key = volsync_key(node.args[0])
                elif ((isinstance(f, ast.Attribute) and f.attr == "getenv"
                       and isinstance(f.value, ast.Name)
                       and f.value.id in os_names)
                      or (isinstance(f, ast.Name)
                          and f.id in getenv_names)) and node.args:
                    key = volsync_key(node.args[0])
            elif isinstance(node, ast.Subscript):
                if (isinstance(node.ctx, ast.Load)
                        and is_environ(node.value)):
                    key = volsync_key(node.slice)
            elif isinstance(node, ast.Compare):
                if (len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and is_environ(node.comparators[0])):
                    key = volsync_key(node.left)
            if key is not None:
                yield finding_at(
                    ctx.relpath, node, self.code,
                    f"read of {key!r} outside envflags.py — add/use an "
                    f"accessor in volsync_tpu/envflags.py")


class ImportGateRule:
    """Optional heavy deps import only behind their shims, so every
    other module stays importable when the dep is absent."""

    code = "VL002"
    name = "gated-imports"
    description = ("zstandard/cryptography imported outside "
                   "repo/compress.py / repo/crypto.py")

    GATES = {
        "zstandard": "repo/compress.py",
        "cryptography": "repo/crypto.py",
    }

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            roots: list[str] = []
            if isinstance(node, ast.Import):
                roots = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level == 0:  # relative imports can't be the dep
                    roots = [node.module.split(".")[0]]
            for root in roots:
                shim = self.GATES.get(root)
                if shim is None or ctx.in_module(shim):
                    continue
                yield finding_at(
                    ctx.relpath, node, self.code,
                    f"import of {root!r} outside {shim} — route through "
                    f"the shim so its absence degrades instead of "
                    f"breaking imports")


class SilentExceptRule:
    """A broad except whose body does nothing hides real failures —
    the invariant-drift class both sync-correctness papers blame."""

    code = "VL003"
    name = "silent-broad-except"
    description = ("except Exception/BaseException/bare whose body only "
                   "passes — no log, no re-raise")

    @staticmethod
    def _is_broad(type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare except
        if isinstance(type_node, ast.Name):
            return type_node.id in _BROAD_EXC
        if isinstance(type_node, ast.Attribute):
            return type_node.attr in _BROAD_EXC
        if isinstance(type_node, ast.Tuple):
            return any(SilentExceptRule._is_broad(e)
                       for e in type_node.elts)
        return False

    @staticmethod
    def _is_silent(body: list[ast.stmt]) -> bool:
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)):
                continue  # docstring / ellipsis
            return False
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._is_broad(node.type) and self._is_silent(node.body):
                yield finding_at(
                    ctx.relpath, node, self.code,
                    "broad except swallows the exception silently — "
                    "re-raise, narrow the type, or log it "
                    "(`# lint: ignore[VL003]` with a reason if "
                    "intentional)")


class TracerSafetyRule:
    """Host-side ops on traced values inside a jit'd function either
    fail at trace time or silently bake a traced value into the
    compiled graph — both are kernel bugs. Heuristic, scoped to ops/."""

    code = "VL004"
    name = "jit-tracer-safety"
    description = ("float()/int()/bool()/.item()/.tolist() or Python "
                   "branching on a traced arg inside a jit'd function")

    SCOPE_PARTS = ("ops",)

    @staticmethod
    def _is_jit_expr(node: ast.AST) -> bool:
        return ((isinstance(node, ast.Name) and node.id == "jit")
                or (isinstance(node, ast.Attribute) and node.attr == "jit"))

    @classmethod
    def _jit_static_names(
            cls, fn: ast.FunctionDef) -> Optional[set[str]]:
        """None if ``fn`` is not jit-decorated, else the set of
        static_argnames (traced args are the rest)."""
        for dec in fn.decorator_list:
            if cls._is_jit_expr(dec):
                return set()
            if not isinstance(dec, ast.Call):
                continue
            f = dec.func
            is_partial = ((isinstance(f, ast.Name) and f.id == "partial")
                          or (isinstance(f, ast.Attribute)
                              and f.attr == "partial"))
            if is_partial and dec.args and cls._is_jit_expr(dec.args[0]):
                pass
            elif cls._is_jit_expr(f):
                pass  # @jax.jit(static_argnames=...)
            else:
                continue
            statics: set[str] = set()
            for kw in dec.keywords:
                if kw.arg != "static_argnames":
                    continue
                v = kw.value
                if _const_str(v):
                    statics.add(_const_str(v))
                elif isinstance(v, (ast.Tuple, ast.List)):
                    statics.update(
                        s for s in (_const_str(e) for e in v.elts) if s)
            return statics
        return None

    @classmethod
    def _traced_uses(cls, node: ast.AST, traced: set[str]) -> set[str]:
        """Traced params used as VALUES in ``node``. Two uses are
        static even on a traced array and excluded: ``.shape/.dtype/
        .ndim`` metadata access, and ``is (not) None`` identity checks
        (the optional-traced-arg idiom all over ops/)."""
        if (isinstance(node, ast.Attribute)
                and node.attr in ("shape", "dtype", "ndim")):
            return set()
        if (isinstance(node, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops)):
            return set()
        if isinstance(node, ast.Name):
            return {node.id} & traced
        out: set[str] = set()
        for child in ast.iter_child_nodes(node):
            out |= cls._traced_uses(child, traced)
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.scope_dirs()
        if not any(p in parts for p in self.SCOPE_PARTS):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            statics = self._jit_static_names(fn)
            if statics is None:
                continue
            a = fn.args
            params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
            traced = params - statics
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Name)
                            and f.id in ("float", "int", "bool")
                            and len(node.args) == 1
                            and not isinstance(node.args[0], ast.Constant)
                            and self._traced_uses(node.args[0], traced)):
                        yield finding_at(
                            ctx.relpath, node, self.code,
                            f"{f.id}() on a traced value inside jit'd "
                            f"{fn.name}() — forces a host sync or fails "
                            f"at trace time")
                    elif (isinstance(f, ast.Attribute)
                          and f.attr in ("item", "tolist")):
                        yield finding_at(
                            ctx.relpath, node, self.code,
                            f".{f.attr}() inside jit'd {fn.name}() — "
                            f"host transfer of a traced value")
                elif isinstance(node, (ast.If, ast.While)):
                    hot = self._traced_uses(node.test, traced)
                    if hot:
                        yield finding_at(
                            ctx.relpath, node, self.code,
                            f"Python branch on traced arg(s) "
                            f"{sorted(hot)} inside jit'd {fn.name}() — "
                            f"use lax.cond/lax.select")


class DirectLockRule:
    """Data-plane modules construct locks via analysis.lockcheck so
    VOLSYNC_TPU_LOCKCHECK can instrument them; a direct
    threading.Lock() there is invisible to the detector."""

    code = "VL005"
    name = "lockcheck-routed-locks"
    description = ("direct threading.Lock/RLock construction in a "
                   "data-plane module (repo/objstore/ops/engine/obs/io)")

    SCOPE_PARTS = ("repo", "objstore", "ops", "engine", "obs", "io")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.scope_dirs()
        if not any(p in parts for p in self.SCOPE_PARTS):
            return
        lock_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "threading"):
                lock_names.update(
                    a.asname or a.name for a in node.names
                    if a.name in ("Lock", "RLock"))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if (isinstance(f, ast.Attribute) and f.attr in ("Lock", "RLock")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "threading"):
                hit = f.attr
            elif isinstance(f, ast.Name) and f.id in lock_names:
                hit = f.id
            if hit:
                yield finding_at(
                    ctx.relpath, node, self.code,
                    f"threading.{hit}() constructed directly — use "
                    f"analysis.lockcheck.make_{hit.lower()}(name) so "
                    f"VOLSYNC_TPU_LOCKCHECK can instrument it")


class AdHocRetryRule:
    """Every retry loop routes through resilience.RetryPolicy — one
    audited story for classification, backoff jitter, deadlines, and
    breaker/metrics integration. A ``time.sleep`` in an except handler
    or in a loop that wraps a try is the signature of a hand-rolled
    retry (the exact scatter PR 5 removed)."""

    code = "VL105"
    name = "adhoc-retry"
    description = ("time.sleep inside an except handler or a retry loop "
                   "(for/while containing a try) outside resilience.py")

    @staticmethod
    def _sleep_names(tree: ast.Module) -> tuple[set[str], set[str]]:
        """(module aliases of ``time``, local names bound to
        ``time.sleep``) — alias-aware, same pattern as VL001."""
        time_aliases: set[str] = set()
        sleep_names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        time_aliases.add(a.asname or "time")
            elif (isinstance(node, ast.ImportFrom)
                  and node.module == "time" and node.level == 0):
                for a in node.names:
                    if a.name == "sleep":
                        sleep_names.add(a.asname or "sleep")
        return time_aliases, sleep_names

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.in_module("resilience.py"):
            return
        time_aliases, sleep_names = self._sleep_names(ctx.tree)
        if not time_aliases and not sleep_names:
            return

        def is_sleep(call: ast.Call) -> bool:
            f = call.func
            if (isinstance(f, ast.Attribute) and f.attr == "sleep"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in time_aliases):
                return True
            return isinstance(f, ast.Name) and f.id in sleep_names

        findings: list[Finding] = []

        def visit(node: ast.AST, in_except: bool, in_retry_loop: bool):
            for child in ast.iter_child_nodes(node):
                ie, irl = in_except, in_retry_loop
                if isinstance(child, ast.ExceptHandler):
                    ie = True
                elif isinstance(child, (ast.For, ast.While)):
                    if any(isinstance(n, ast.Try)
                           for n in ast.walk(child)):
                        irl = True
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)):
                    # a nested function is a fresh context: its sleeps
                    # are judged by ITS loops/handlers, not the
                    # enclosing ones
                    ie = irl = False
                if (isinstance(child, ast.Call) and (ie or irl)
                        and is_sleep(child)):
                    where = ("an except handler" if ie
                             else "a retry loop")
                    findings.append(finding_at(
                        ctx.relpath, child, self.code,
                        f"time.sleep in {where} — hand-rolled retry; "
                        f"route through resilience.RetryPolicy "
                        f"(policy.call or policy.backoffs())"))
                visit(child, ie, irl)

        visit(ctx.tree, False, False)
        yield from findings


class HotPathCopyRule:
    """The zero-copy data plane (docs/performance.md) moves payload
    bytes as pooled buffers and memoryviews; every host copy that
    remains is sanctioned, ledgered via ``obs.record_copy``, and
    suppressed here with a reason. A NEW ``.tobytes()`` /
    ``bytes(buffer)`` / ``b"".join`` on these modules is the
    regression class PR 16 removed — flag it so the copy either goes
    away or joins the ledger explicitly."""

    code = "VL106"
    name = "hot-path-copy"
    description = (".tobytes()/bytes(<buffer>)/b\"\".join copy in a "
                   "zero-copy data-plane module (engine/, ops/, repo/)")

    SCOPE_PARTS = ("engine", "ops", "repo")

    @staticmethod
    def _is_bytes_literal(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, bytes))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.scope_dirs()
        if not any(p in parts for p in self.SCOPE_PARTS):
            return
        # consult the VL503 sanction verdict: a copy whose statement
        # (or adjacent sibling) ledgers a sanctioned record_copy is the
        # accounted-for kind — no blanket suppression needed
        from volsync_tpu.analysis.bufflow import sanctioned_lines

        ledgered = sanctioned_lines(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if node.lineno in ledgered:
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "tobytes":
                yield finding_at(
                    ctx.relpath, node, self.code,
                    ".tobytes() materializes a copy on the zero-copy "
                    "data plane — pass the buffer itself (hashing, "
                    "numpy, and the store all take memoryviews), or "
                    "sanction it: record_copy(site, n) + a reasoned "
                    "`# lint: ignore[VL106]`")
            elif (isinstance(f, ast.Name) and f.id == "bytes"
                  and len(node.args) == 1 and not node.keywords
                  and not isinstance(node.args[0], ast.Constant)):
                # bytes(<expr>) copies any buffer; bytes(1024) and
                # bytes literals are allocations, not copies, and
                # constant args are skipped above
                yield finding_at(
                    ctx.relpath, node, self.code,
                    "bytes(...) over a buffer copies it — keep the "
                    "memoryview/bytearray, or sanction the copy: "
                    "record_copy(site, n) + a reasoned "
                    "`# lint: ignore[VL106]`")
            elif (isinstance(f, ast.Attribute) and f.attr == "join"
                  and self._is_bytes_literal(f.value)):
                yield finding_at(
                    ctx.relpath, node, self.code,
                    "bytes join materializes one contiguous copy — "
                    "hand the parts list down (iovec PutBody, "
                    "seal_parts, writelines), or sanction the copy: "
                    "record_copy(site, n) + a reasoned "
                    "`# lint: ignore[VL106]`")


class SpanNameLiteralRule:
    """Span names feed Prometheus labels
    (``volsync_stage_duration_seconds{stage}``,
    ``volsync_svc_stage_seconds{stage}``) and the VL-clean flight
    recorder: a dynamic name (f-string, concatenation, variable) at a
    ``span()``/``begin_span()`` call site is unbounded label
    cardinality. Names must be literal ``component.stage`` strings —
    lowercase, dotted, ``[a-z0-9_]`` segments."""

    code = "VL301"
    name = "span-name-literal"
    description = ("span()/begin_span() call whose name is not a literal "
                   "dotted lowercase string")

    TARGETS = ("span", "begin_span")
    _NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
    #: receiver names for attribute-style calls (obs.span(...),
    #: tracing.begin_span(...)); a bare ``m.span(1)`` (re.Match.span)
    #: is NOT matched because ``m`` is not a tracing receiver
    RECEIVERS = ("obs", "tracing")

    def _is_target(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.TARGETS
        if isinstance(func, ast.Attribute) and func.attr in self.TARGETS:
            return (isinstance(func.value, ast.Name)
                    and func.value.id in self.RECEIVERS)
        return False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # the tracing module itself defines span()/begin_span() and
        # forwards caller-supplied names internally
        if ctx.in_module("obs/tracing.py"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_target(node.func):
                continue
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if name_arg is None:
                continue  # not the tracing API's shape
            literal = _const_str(name_arg)
            if literal is None:
                yield finding_at(
                    ctx.relpath, node, self.code,
                    "span name is not a string literal — dynamic names "
                    "(f-strings/concatenation/variables) are unbounded "
                    "Prometheus label cardinality; use a literal "
                    "component.stage name and carry variability in "
                    "span attributes")
            elif not self._NAME_RE.match(literal):
                yield finding_at(
                    ctx.relpath, node, self.code,
                    f"span name {literal!r} is not dotted-lowercase "
                    f"(expected e.g. 'engine.read': [a-z0-9_] segments "
                    f"joined by '.')")


def default_rules() -> list:
    return [EnvFlagRule(), ImportGateRule(), SilentExceptRule(),
            TracerSafetyRule(), DirectLockRule(), AdHocRetryRule(),
            HotPathCopyRule(), SpanNameLiteralRule()]
