"""AST lint engine: file walker, rule runner, baseline, reporting.

The engine is deliberately tiny and dependency-free (stdlib ``ast``
only): it parses each ``.py`` file once, hands the tree + source lines
to every registered rule (analysis/rules.py), and post-filters the
findings through inline suppressions and the checked-in baseline.

Output format is one finding per line, ``file:line CODE message`` —
greppable, editor-clickable, stable for the baseline diff.

Suppressions
------------
A finding on line N is suppressed when line N carries a comment
``# lint: ignore[CODE]`` (or ``# lint: ignore`` for all codes). The
suppression is part of the code under review — it shows up in diffs,
unlike a baseline entry.

Baseline
--------
``--write-baseline`` records the current findings keyed by
``path:CODE:message`` (line numbers excluded, so unrelated edits above
a grandfathered site don't churn the file) with a count per key.
Subsequent runs subtract the baseline: only NEW findings fail the run.
Baseline entries that no longer match anything are reported as stale —
the expire half of the workflow — so the file shrinks monotonically
toward empty instead of fossilizing.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator, Optional

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*ignore(?:\[([A-Z0-9, ]+)\])?")


@dataclass(frozen=True)
class Finding:
    path: str  # posix, as given/walked — what gets printed
    line: int
    code: str
    message: str
    severity: str = "warning"  # error | warning | note (SARIF levels)
    # optional source span (1-based; 0 = unknown) — SARIF region data
    col: int = 0
    end_line: int = 0
    end_col: int = 0

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.code} {self.message}"

    def baseline_key(self) -> str:
        return f"{self.path}:{self.code}:{self.message}"


def finding_at(relpath: str, node: ast.AST, code: str, message: str,
               severity: str = "warning") -> Finding:
    """Finding carrying the full source span of ``node`` (ast column
    offsets are 0-based; SARIF and editors are 1-based)."""
    end_line = getattr(node, "end_lineno", None) or 0
    end_col = getattr(node, "end_col_offset", None)
    return Finding(
        relpath, getattr(node, "lineno", 0), code, message,
        severity=severity,
        col=getattr(node, "col_offset", -1) + 1,
        end_line=end_line,
        end_col=0 if end_col is None else end_col + 1)


def _finding_from_row(relpath: str, row: list) -> Finding:
    """Rebuild a Finding from a cache row; rows written before the
    span fields existed have 4 elements."""
    line, code, msg, sev = row[0], row[1], row[2], row[3]
    col, end_line, end_col = (row[4], row[5], row[6]) if len(row) >= 7 \
        else (0, 0, 0)
    return Finding(relpath, int(line), code, msg, severity=sev,
                   col=int(col), end_line=int(end_line),
                   end_col=int(end_col))


class FileContext:
    """Everything a rule may look at for one file."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath  # posix path as reported in findings
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree

    def scope_dirs(self) -> list[str]:
        """Directory components AFTER the last ``volsync_tpu`` path
        element (all of them when absent) — what scope-limited rules
        match against, so an absolute checkout path like
        ``/root/repo/...`` can't smuggle components (``repo``!) into
        the scope decision."""
        parts = self.relpath.split("/")[:-1]
        if "volsync_tpu" in parts:
            parts = parts[len(parts) - parts[::-1].index("volsync_tpu"):]
        return parts

    def in_module(self, *suffixes: str) -> bool:
        """True when this file IS one of ``suffixes`` (posix path
        suffix match on a path-component boundary) — how rules express
        'allowed only in repo/compress.py'."""
        for suffix in suffixes:
            if self.relpath == suffix or self.relpath.endswith("/" + suffix):
                return True
        return False

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def iter_py_files(paths: Iterable[str]) -> Iterator[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            yield from sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts)
        elif p.suffix == ".py":
            yield p


def relativize(path: Path) -> str:
    """Cwd-relative posix path when ``path`` lives under the cwd, else
    the path as-is.  The single relativization policy for cache keys,
    scope decisions and dump/SARIF artifacts: an absolute
    ``/root/repo/bench.py`` must not inherit a ``repo`` scope dir, and
    dump files must not leak absolute checkout paths."""
    try:
        return path.relative_to(Path.cwd()).as_posix()
    except ValueError:
        return path.as_posix()


def _suppressed(ctx: FileContext, finding: Finding) -> bool:
    m = _SUPPRESS_RE.search(ctx.line_text(finding.line))
    if not m:
        return False
    codes = m.group(1)
    if codes is None:
        return True
    return finding.code in {c.strip() for c in codes.split(",")}


@dataclass
class LintResult:
    """What a project run produced, plus how much work it did — the
    `analyzed` list is what the incremental-cache acceptance criteria
    are stated against (warm run: empty; single edit: the file plus
    its reverse dependencies)."""

    findings: list  # list[Finding]
    errors: list  # list[str]
    analyzed: list  # relpaths (re-)analyzed this run
    total: int  # files considered


def run_lint(paths: Iterable[str],
             rules: Optional[list] = None) -> tuple[list[Finding], list[str]]:
    """Lint ``paths`` -> (findings, errors). ``errors`` are files that
    failed to read/parse — reported, and they fail the run (a syntax
    error must not read as 'clean')."""
    res = run_project(paths, rules=rules)
    return res.findings, res.errors


def _severity_of(f: Finding) -> str:
    return getattr(f, "severity", "warning") or "warning"


def run_project(paths: Iterable[str],
                rules: Optional[list] = None,
                project_rules: Optional[list] = None,
                cache_path: Optional[Path] = None) -> LintResult:
    """Project-wide lint: per-file rules plus the interprocedural
    rules (callgraph + dataflow), with optional content-hash
    incremental caching.

    With ``cache_path`` and an unchanged tree, findings are served
    entirely from the cache and no file is parsed. When files changed,
    the dirty set is the changed files plus their transitive reverse
    import dependencies; everything is re-parsed (the call graph is
    global) but findings are refreshed only for dirty files and served
    from cache for the rest.
    """
    from volsync_tpu.analysis import cache as cache_mod

    if rules is None:
        from volsync_tpu.analysis.rules import default_rules

        rules = default_rules()
    if project_rules is None:
        from volsync_tpu.analysis.iprules import default_project_rules
        from volsync_tpu.analysis.shapes import default_shape_rules

        project_rules = default_project_rules() + default_shape_rules()

    errors: list[str] = []
    blobs: list[tuple[Path, str, bytes]] = []  # (path, relpath, bytes)
    seen: set[str] = set()
    for path in iter_py_files(paths):
        relpath = relativize(path)
        if relpath in seen:
            continue
        seen.add(relpath)
        try:
            blobs.append((path, relpath, path.read_bytes()))
        except OSError as e:
            errors.append(f"{relpath}: {e}")

    signature = cache_mod.rules_signature(rules, project_rules)
    cached = (cache_mod.load_cache(cache_path, signature)
              if cache_path else None)
    hashes = {relpath: cache_mod.content_hash(data)
              for _, relpath, data in blobs}

    if cached is not None:
        changed = {rp for rp in hashes
                   if cached.get(rp, {}).get("hash") != hashes[rp]}
        removed = set(cached) - set(hashes)
        if not changed and not removed:
            findings = [
                _finding_from_row(rp, row)
                for rp, entry in cached.items()
                for row in entry.get("findings", [])]
            findings.sort(key=lambda f: (f.path, f.line, f.code))
            return LintResult(findings, errors, [], len(blobs))
    else:
        changed = set(hashes)
        removed = set()

    # parse everything: interprocedural rules need the whole project
    contexts: list[FileContext] = []
    parsed: set[str] = set()
    for path, relpath, data in blobs:
        try:
            source = data.decode("utf-8")
            tree = ast.parse(source, filename=str(path))
        except (SyntaxError, ValueError) as e:
            errors.append(f"{relpath}: {e}")
            continue
        contexts.append(FileContext(path, relpath, source, tree))
        parsed.add(relpath)

    from volsync_tpu.analysis.callgraph import build_index

    index = build_index(contexts)
    deps = index.file_deps()
    dirty = cache_mod.dirty_closure(changed & parsed, removed, deps)
    dirty &= parsed

    by_ctx = {ctx.relpath: ctx for ctx in contexts}
    fresh: dict[str, list[Finding]] = {rp: [] for rp in dirty}
    for relpath in sorted(dirty):
        ctx = by_ctx[relpath]
        for rule in rules:
            for f in rule.check(ctx):
                if not _suppressed(ctx, f):
                    fresh[relpath].append(f)
    for rule in project_rules:
        for f in rule.check_project(index):
            ctx = by_ctx.get(f.path)
            if f.path in dirty and ctx is not None:
                if not _suppressed(ctx, f):
                    fresh[f.path].append(f)

    # shape summaries ride the cache so a warm run can show them (and
    # the cache tests can assert summary-edit invalidation) without
    # re-running the interpreter; only computed when VL2xx rules ran
    shape_sum: dict = {}
    if any(str(getattr(r, "code", "")).startswith("VL2")
           for r in project_rules):
        from volsync_tpu.analysis.shapes import summaries_for

        shape_sum = summaries_for(index)

    # lock facts (acquisition sites, order edges, guarded-field stats)
    # are the VL4xx analogue of the shape summaries: cached per file so
    # a warm run replays them without rebuilding the lock model
    lock_sum: dict = {}
    if any(str(getattr(r, "code", "")).startswith("VL4")
           for r in project_rules):
        from volsync_tpu.analysis.lockflow import (
            summaries_for as lock_summaries,
        )

        lock_sum = lock_summaries(index)

    # buffer-provenance facts (per-function return provenance, donated
    # params, sanctioned/record sites) are the VL5xx analogue: cached
    # per file so a warm run skips the provenance pass entirely
    buf_sum: dict = {}
    if any(str(getattr(r, "code", "")).startswith("VL5")
           for r in project_rules):
        from volsync_tpu.analysis.bufflow import (
            summaries_for as buf_summaries,
        )

        buf_sum = buf_summaries(index)

    # fault-path facts (per-function store effects with their retry
    # layers, raise types) are the VL6xx analogue: cached per file so a
    # warm run replays VL6 findings without re-running the effect walk
    fx_sum: dict = {}
    if any(str(getattr(r, "code", "")).startswith("VL6")
           for r in project_rules):
        from volsync_tpu.analysis.faultflow import (
            summaries_for as fx_summaries,
        )

        fx_sum = fx_summaries(index)

    findings: list[Finding] = []
    new_cache: dict[str, dict] = {}
    for relpath in sorted(parsed):
        old_entry = (cached or {}).get(relpath, {})
        if relpath in dirty:
            file_findings = fresh.get(relpath, [])
            shapes_entry = shape_sum.get(relpath, {})
            locks_entry = lock_sum.get(relpath, {})
            buf_entry = buf_sum.get(relpath, {})
            fx_entry = fx_sum.get(relpath, {})
        else:
            file_findings = [_finding_from_row(relpath, row)
                             for row in old_entry.get("findings", [])]
            shapes_entry = old_entry.get("shapes",
                                         shape_sum.get(relpath, {}))
            locks_entry = old_entry.get("locks",
                                        lock_sum.get(relpath, {}))
            buf_entry = old_entry.get("buf", buf_sum.get(relpath, {}))
            fx_entry = old_entry.get("fx", fx_sum.get(relpath, {}))
        findings.extend(file_findings)
        new_cache[relpath] = {
            "hash": hashes[relpath],
            "deps": sorted(deps.get(relpath, ())),
            "findings": [[f.line, f.code, f.message, _severity_of(f),
                          f.col, f.end_line, f.end_col]
                         for f in sorted(
                             file_findings,
                             key=lambda f: (f.line, f.code, f.message))],
            "shapes": shapes_entry,
            "locks": locks_entry,
            "buf": buf_entry,
            "fx": fx_entry,
        }

    if cache_path is not None and not errors:
        cache_mod.save_cache(cache_path, signature, new_cache)

    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return LintResult(findings, errors, sorted(dirty), len(blobs))


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Path) -> dict[str, int]:
    """{baseline_key: allowed count}. Missing file -> empty baseline."""
    try:
        raw = json.loads(path.read_text(encoding="utf-8"))
    except FileNotFoundError:
        return {}
    counts = raw.get("findings", {})
    return {str(k): int(v) for k, v in counts.items()}


def write_baseline(findings: Iterable[Finding], path: Path) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    payload = {
        "comment": ("grandfathered `volsync lint` findings; regenerate "
                    "with --write-baseline, shrink it whenever you fix "
                    "one"),
        "findings": dict(sorted(counts.items())),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def apply_baseline(
        findings: list[Finding],
        baseline: dict[str, int]) -> tuple[list[Finding], int, list[str]]:
    """Split findings against the baseline.

    Returns (new_findings, suppressed_count, stale_keys): findings
    beyond a key's allowance are new; allowances nothing matched are
    stale (fixed or moved — time to regenerate the baseline).
    """
    remaining = dict(baseline)
    new: list[Finding] = []
    suppressed = 0
    for f in findings:
        k = f.baseline_key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            suppressed += 1
        else:
            new.append(f)
    stale = sorted(k for k, v in remaining.items() if v > 0)
    return new, suppressed, stale
