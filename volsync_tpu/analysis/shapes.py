"""Static shape/dtype abstract interpreter + the VL2xx rule family.

An abstract interpreter over the project call graph
(analysis/callgraph.py) that pushes the (shape, dtype, weak-type)
lattice from analysis/absdomain.py through ``jnp.*`` / ``lax.*``
calls: literal shapes from ``zeros``/``ones``/``arange``/``full``,
broadcasting and reduction semantics, ``reshape``/``concatenate``/
``dot`` arity rules, and interprocedural function summaries (a callee
is re-interpreted under its caller's abstract arguments, memoized per
argument signature, cycle-guarded — the same fixpoint discipline as
the VL10x dataflow rules).

Rules:

* **VL201** (error) shape-incompatible elementwise / dot / reshape /
  concatenate operands — reported only when every involved dim is
  concrete;
* **VL202** (warning) implicit dtype promotion that moves an unsigned
  operand off its dtype (``uint32 -> int64/float32/...``) in
  ``ops/`` / ``kernels/`` hash arithmetic, unless an explicit
  ``.astype(...)`` / ``dtype=`` cast appears on either operand;
* **VL203** (error) ``lax.scan`` / ``fori_loop`` / ``while_loop``
  carry whose inferred shape/dtype differs from its init (the
  retrace/NaN trap);
* **VL204** (error) ``vmap`` ``in_axes``/``out_axes`` arity vs the
  callee signature, and mapped-dim validity against known arg ranks;
* **VL205** (error) ``PartitionSpec`` / collective axis names checked
  against the mesh axes declared in ``parallel/mesh.py``.

Everything is conservative: Unknown or merely-symbolic values can
suppress a finding but never create one, so an unresolved helper or
an exotic construct costs recall, not precision. Findings discovered
while interpreting a callee under a caller's arguments are reported
at the caller's call site with a hop chain (``... via mix()``) and
the sink location in the message, deduplicated on the sink so a
helper shared by many kernels is reported once.
"""

from __future__ import annotations

import ast
import weakref
from dataclasses import dataclass, field
from typing import Optional

from volsync_tpu.analysis import absdomain as D
from volsync_tpu.analysis.absdomain import AbsArray, UNKNOWN_ARRAY
from volsync_tpu.analysis.callgraph import ProjectIndex, attr_chain
from volsync_tpu.analysis.engine import Finding, finding_at

_SEVERITY = {"VL201": "error", "VL202": "warning", "VL203": "error",
             "VL204": "error", "VL205": "error"}

_MAX_DEPTH = 8  # interprocedural interpretation depth
_MAX_CALLS = 20000  # per-project budget; past it calls go Unknown
_MAX_LITERAL_ITER = 128  # comprehension/range unrolling cap


# -- abstract value classes -------------------------------------------------

class _UnknownType:
    __slots__ = ()

    def __repr__(self) -> str:
        return "Unknown"


Unknown = _UnknownType()


@dataclass(frozen=True)
class PyInt:
    """Python int with an abstract value usable as a dim."""

    dim: object = None  # int | structural tuple | None


@dataclass(frozen=True)
class PyFloat:
    value: object = None


@dataclass(frozen=True)
class PyBool:
    value: object = None


@dataclass(frozen=True)
class PyStr:
    value: object = None


@dataclass(frozen=True)
class PyNoneV:
    pass


@dataclass(frozen=True)
class PyTuple:
    elts: tuple


@dataclass(frozen=True)
class PyDtype:
    name: str


@dataclass(frozen=True)
class PyModule:
    """Dotted reference not (yet) resolved to a value: an external
    module/function (``jax.numpy``, ``jax.lax.scan``) or a project
    module used as a namespace."""

    dotted: str


@dataclass(frozen=True)
class PyBuiltin:
    name: str


class PyFunc:
    """Reference to a project function, optionally with the defining
    frame's environment captured (nested defs / closures)."""

    __slots__ = ("qual", "closure")

    def __init__(self, qual: str, closure: Optional[dict] = None):
        self.qual = qual
        self.closure = closure


class PyLambda:
    __slots__ = ("node", "closure", "mod")

    def __init__(self, node: ast.Lambda, closure: dict, mod):
        self.node = node
        self.closure = closure
        self.mod = mod


class PyPartial:
    __slots__ = ("fn", "args", "kwargs")

    def __init__(self, fn, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs


class PyVmapped:
    __slots__ = ("fn", "in_axes", "out_axes", "node")

    def __init__(self, fn, in_axes, out_axes, node):
        self.fn = fn
        self.in_axes = in_axes  # int | None | tuple | Unknown
        self.out_axes = out_axes
        self.node = node


class PyWrapped:
    """shard_map/jit-style transparent wrapper: calling it interprets
    the target with Unknown-ified args (per-shard shapes must not
    leak through) purely to surface findings in the body."""

    __slots__ = ("fn",)

    def __init__(self, fn):
        self.fn = fn


@dataclass(frozen=True)
class PyAt:
    arr: AbsArray


@dataclass(frozen=True)
class PyAtIndexed:
    arr: AbsArray


@dataclass
class Rec:
    """A finding raised inside a nested (callee) frame, propagated up
    to the top-level caller for emission with a hop chain."""

    code: str
    message: str
    sink_rel: str
    sink_line: int
    chain: tuple = ()  # callee qualnames, outermost hop first


def _short(qual: str) -> str:
    return qual.rsplit(".", 1)[-1]


def _chain_str(chain) -> str:
    return " -> ".join(f"{_short(q)}()" for q in chain)


# -- value helpers ----------------------------------------------------------

def to_array(v) -> AbsArray:
    if isinstance(v, AbsArray):
        return v
    if isinstance(v, PyInt):
        return AbsArray((), "int32", weak=True)
    if isinstance(v, PyBool):
        return AbsArray((), "bool", weak=True)
    if isinstance(v, PyFloat):
        return AbsArray((), "float32", weak=True)
    if isinstance(v, PyTuple):
        return _literal_array(v, None)
    return UNKNOWN_ARRAY


def _literal_array(v: PyTuple, dtype: Optional[str]) -> AbsArray:
    """Shape/dtype of an array built from a (nested) Python list."""
    n = len(v.elts)
    inner_shapes = []
    kinds = set()
    for e in v.elts:
        if isinstance(e, PyTuple):
            a = _literal_array(e, None)
            inner_shapes.append(a.shape)
            kinds.add(a.dtype)
        elif isinstance(e, AbsArray):
            inner_shapes.append(e.shape)
            kinds.add(e.dtype)
        elif isinstance(e, PyInt):
            inner_shapes.append(())
            kinds.add("int32")
        elif isinstance(e, PyFloat):
            inner_shapes.append(())
            kinds.add("float32")
        elif isinstance(e, PyBool):
            inner_shapes.append(())
            kinds.add("bool")
        else:
            return AbsArray((n,) if n else (0,), dtype)
    first = inner_shapes[0] if inner_shapes else ()
    if any(s != first for s in inner_shapes) or first is None:
        return AbsArray((n,), dtype)
    dt = dtype if dtype else (kinds.pop() if len(kinds) == 1 else None)
    return AbsArray((n,) + first, dt)


def dim_of(v):
    if isinstance(v, PyInt):
        return v.dim
    if isinstance(v, AbsArray) and v.shape == () and v.dtype and \
            D.kind(v.dtype) in (D.KIND_UINT, D.KIND_INT):
        return None  # a traced scalar: valid as a (symbolic) dim
    return None


def shape_from(v) -> Optional[tuple]:
    if isinstance(v, PyTuple):
        return tuple(dim_of(e) for e in v.elts)
    if isinstance(v, PyInt):
        return (v.dim,)
    return None


def dtype_from(v) -> Optional[str]:
    if isinstance(v, PyDtype):
        return v.name
    if isinstance(v, PyStr) and isinstance(v.value, str):
        return D.canon_dtype(v.value)
    if isinstance(v, PyBuiltin):
        # dtype=bool / dtype=int / dtype=float with the Python builtin
        return {"bool": "bool", "int": "int32",
                "float": "float32"}.get(v.name)
    return None


def join_value(a, b):
    if a is b:
        return a
    if isinstance(a, AbsArray) and isinstance(b, AbsArray):
        return D.join_array(a, b)
    if isinstance(a, PyInt) and isinstance(b, PyInt):
        return PyInt(D.join_dim(a.dim, b.dim))
    if isinstance(a, PyTuple) and isinstance(b, PyTuple) \
            and len(a.elts) == len(b.elts):
        return PyTuple(tuple(join_value(x, y)
                             for x, y in zip(a.elts, b.elts)))
    if isinstance(a, PyNoneV) and isinstance(b, PyNoneV):
        return a
    if isinstance(a, PyStr) and isinstance(b, PyStr):
        return a if a.value == b.value else PyStr(None)
    if isinstance(a, PyFunc) and isinstance(b, PyFunc) \
            and a.qual == b.qual:
        return a
    if a == b:
        return a
    return Unknown


def _vkey(v, depth=0):
    """Canonical memo key for an abstract value."""
    if depth > 4:
        return "?"
    if isinstance(v, AbsArray):
        return ("A", v.shape, v.dtype, v.weak)
    if isinstance(v, PyInt):
        return ("I", v.dim)
    if isinstance(v, PyTuple):
        return ("T", tuple(_vkey(e, depth + 1) for e in v.elts))
    if isinstance(v, PyFunc):
        return ("F", v.qual, id(v.closure) if v.closure else 0)
    if isinstance(v, (PyStr, PyFloat, PyBool)):
        return ("C", type(v).__name__, getattr(v, "value", None))
    if isinstance(v, PyDtype):
        return ("D", v.name)
    if isinstance(v, PyNoneV):
        return "N"
    return "?"


def _explicit_cast(node) -> bool:
    """Syntactic escape hatch for VL202: the operand expression is an
    explicit cast (``x.astype(...)``, ``jnp.uint32(x)``, or any call
    carrying ``dtype=``)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and (
            f.attr == "astype" or D.canon_dtype(f.attr)):
        return True
    if isinstance(f, ast.Name) and D.canon_dtype(f.id):
        return True
    return any(kw.arg == "dtype" for kw in node.keywords)


_BIN_OPS = {
    ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
    ast.FloorDiv: "floordiv", ast.Mod: "mod", ast.Pow: "pow",
    ast.Div: "div", ast.BitAnd: "and", ast.BitOr: "or",
    ast.BitXor: "xor", ast.LShift: "shl", ast.RShift: "shr",
    ast.MatMult: "matmul",
}

_DIM_FOLDABLE = {"add", "sub", "mul", "floordiv", "mod"}


# -- the interpreter --------------------------------------------------------

class Interp:
    """One abstract interpretation of a ProjectIndex.

    Module bodies are interpreted first (building per-module constant
    environments: ``_H0``/``_K`` tables, masks, axis-name strings),
    then every function standalone with Unknown parameters. Calls to
    resolved project functions recurse with the caller's abstract
    arguments — memoized per ``(qualname, arg-signature)``, bounded by
    depth and a global call budget, cycle-guarded by an in-progress
    stack.
    """

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.found: list[Finding] = []
        self.summaries: dict[str, dict[str, str]] = {}
        self._seen: set = set()  # (sink_rel, sink_line, code) dedup
        self._menvs: dict[str, dict] = {}
        self._menv_wip: dict[str, dict] = {}
        self._memo: dict = {}
        self._in_progress: set[str] = set()
        self._calls = 0
        self._sym_counter = 0
        self._crashes = 0

    # -- entry points -------------------------------------------------------

    def run(self) -> None:
        mods = sorted(
            (m for m in self.index.modules.values() if self._relevant(m)),
            key=lambda m: m.relpath)
        for m in mods:
            self.module_env(m.name)
        funcs = sorted(
            (fi for fi in self.index.functions.values()
             if self._relevant_rel(fi.relpath)),
            key=lambda fi: (fi.relpath, fi.node.lineno))
        for fi in funcs:
            try:
                self.run_function(fi)
            except Exception:
                # an interpreter bug must degrade to missing findings,
                # never crash the lint run or fabricate results
                self._crashes += 1
        self.found.extend(check_mesh_axes(self.index))
        self.found.sort(key=lambda f: (f.path, f.line, f.code))

    def _relevant(self, mod) -> bool:
        return any(t == "jax" or t.startswith("jax.")
                   for t in mod.aliases.values())

    def _relevant_rel(self, relpath: str) -> bool:
        mod = self.index.by_relpath.get(relpath)
        return mod is not None and self._relevant(mod)

    def module_env(self, name: str) -> dict:
        if name in self._menvs:
            return self._menvs[name]
        if name in self._menv_wip:
            return self._menv_wip[name]  # import cycle: partial env
        mod = self.index.modules.get(name)
        if mod is None or not self._relevant(mod):
            self._menvs[name] = {}
            return self._menvs[name]
        env: dict = {}
        self._menv_wip[name] = env
        frame = Frame(self, mod, env, depth=0)
        try:
            frame.exec_block(mod.ctx.tree.body)
        except Exception:
            self._crashes += 1
        del self._menv_wip[name]
        self._menvs[name] = env
        return env

    def run_function(self, fi) -> None:
        mod = self.index.by_relpath.get(fi.relpath)
        if mod is None:
            return
        env = {p: Unknown for p in fi.params + fi.kwonly}
        a = fi.node.args
        if a.vararg:
            env[a.vararg.arg] = Unknown
        if a.kwarg:
            env[a.kwarg.arg] = Unknown
        frame = Frame(self, mod, env, depth=0, fn=fi)
        frame.exec_block(fi.node.body)
        ret = frame.returns[0] if len(frame.returns) == 1 else (
            _join_all(frame.returns) if frame.returns else PyNoneV())
        self.summaries.setdefault(fi.relpath, {})[fi.qualname] = \
            _render(ret)

    # -- emission -----------------------------------------------------------

    def emit(self, relpath: str, node, code: str, message: str,
             dedup_key=None) -> None:
        key = dedup_key or (relpath, getattr(node, "lineno", 0), code)
        if key in self._seen:
            return
        self._seen.add(key)
        self.found.append(finding_at(relpath, node, code, message,
                                     severity=_SEVERITY[code]))

    def fresh_sym(self):
        self._sym_counter += 1
        return D.sym(self._sym_counter)

    # -- interprocedural calls ----------------------------------------------

    def call_value(self, caller_frame, fv, args, kwargs, node):
        """Invoke an abstract callable; returns (ret, records) where
        records carry findings raised inside the callee with their
        hop chain already prepended."""
        self._calls += 1
        if self._calls > _MAX_CALLS or caller_frame.depth >= _MAX_DEPTH:
            return Unknown, ()
        if isinstance(fv, PyPartial):
            return self.call_value(
                caller_frame, fv.fn, list(fv.args) + list(args),
                {**fv.kwargs, **(kwargs or {})}, node)
        if isinstance(fv, PyLambda):
            return self._call_lambda(caller_frame, fv, args, node)
        if isinstance(fv, PyFunc):
            return self._call_func(caller_frame, fv, args, kwargs, node)
        return Unknown, ()

    def _call_lambda(self, caller_frame, lam: PyLambda, args, node):
        params = [p.arg for p in (lam.node.args.posonlyargs
                                  + lam.node.args.args)]
        env = {p: (args[i] if i < len(args) else Unknown)
               for i, p in enumerate(params)}
        frame = Frame(self, lam.mod, env, depth=caller_frame.depth + 1,
                      closure=lam.closure)
        ret = frame.eval(lam.node.body)
        recs = tuple(Rec(r.code, r.message, r.sink_rel, r.sink_line,
                         ("<lambda>",) + r.chain) for r in frame.records)
        return ret, recs

    def _call_func(self, caller_frame, fv: PyFunc, args, kwargs, node):
        fi = self.index.functions.get(fv.qual)
        if fi is None or fv.qual in self._in_progress:
            return Unknown, ()
        key = None
        if fv.closure is None:
            key = (fv.qual, tuple(_vkey(a) for a in args),
                   tuple(sorted((k, _vkey(v))
                                for k, v in (kwargs or {}).items())))
            if key in self._memo:
                return self._memo[key]
        params = list(fi.params)
        if fi.cls is not None and params and params[0] in ("self", "cls"):
            params = params[1:]
        env = {p: Unknown for p in params + fi.kwonly}
        for i, p in enumerate(params):
            if i < len(args):
                env[p] = args[i]
        for k, v in (kwargs or {}).items():
            if k in env:
                env[k] = v
        a = fi.node.args
        if a.vararg:
            env[a.vararg.arg] = Unknown
        if a.kwarg:
            env[a.kwarg.arg] = Unknown
        mod = self.index.by_relpath.get(fi.relpath)
        if mod is None:
            return Unknown, ()
        self._in_progress.add(fv.qual)
        try:
            frame = Frame(self, mod, env, depth=caller_frame.depth + 1,
                          fn=fi, closure=fv.closure)
            frame.exec_block(fi.node.body)
        finally:
            self._in_progress.discard(fv.qual)
        ret = _join_all(frame.returns) if frame.returns else PyNoneV()
        recs = tuple(Rec(r.code, r.message, r.sink_rel, r.sink_line,
                         (fv.qual,) + r.chain) for r in frame.records)
        if key is not None:
            self._memo[key] = (ret, recs)
        return ret, recs


def _join_all(values):
    if not values:
        return Unknown
    out = values[0]
    for v in values[1:]:
        out = join_value(out, v)
    return out


def _render(v) -> str:
    if isinstance(v, AbsArray):
        return f"{v.dtype or '?'}{D.shape_str(v.shape)}"
    if isinstance(v, PyTuple):
        return "(" + ", ".join(_render(e) for e in v.elts) + ")"
    if isinstance(v, PyInt):
        return f"int[{v.dim}]" if D.is_conc(v.dim) else "int"
    if isinstance(v, PyNoneV):
        return "None"
    return "?"


class Frame:
    """One function (or module body) being interpreted."""

    def __init__(self, interp: Interp, mod, env: dict, depth: int,
                 fn=None, closure: Optional[dict] = None):
        self.interp = interp
        self.mod = mod
        self.env = env
        self.depth = depth
        self.fn = fn
        self.closure = closure
        self.returns: list = []
        self.records: list[Rec] = []

    # -- finding plumbing ---------------------------------------------------

    def report(self, code: str, node, message: str) -> None:
        if self.depth == 0:
            self.interp.emit(self.mod.relpath, node, code, message)
        else:
            self.records.append(Rec(code, message, self.mod.relpath,
                                    getattr(node, "lineno", 0)))

    def invoke(self, fv, args, kwargs, node):
        ret, recs = self.interp.call_value(self, fv, args, kwargs, node)
        for r in recs:
            if self.depth == 0:
                msg = (f"{r.message} [{r.sink_rel}:{r.sink_line}]"
                       f" via {_chain_str(r.chain)}")
                self.interp.emit(self.mod.relpath, node, r.code, msg,
                                 dedup_key=(r.sink_rel, r.sink_line,
                                            r.code))
            else:
                self.records.append(r)
        return ret

    def in_hash_scope(self) -> bool:
        parts = self.mod.ctx.scope_dirs()
        return "ops" in parts or "kernels" in parts

    # -- statements ---------------------------------------------------------

    def exec_block(self, stmts) -> bool:
        """Interpret a statement list; True when control definitely
        left the block (return/raise/break)."""
        for stmt in stmts:
            if self.exec_stmt(stmt):
                return True
        return False

    def exec_stmt(self, stmt) -> bool:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._exec_assign(stmt)
        elif isinstance(stmt, ast.Expr):
            self._eval_effect(stmt.value)
        elif isinstance(stmt, ast.Return):
            self.returns.append(self.eval(stmt.value)
                                if stmt.value is not None else PyNoneV())
            return True
        elif isinstance(stmt, (ast.Raise,)):
            if stmt.exc is not None:
                self.eval(stmt.exc)
            return True
        elif isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        elif isinstance(stmt, ast.If):
            self.eval(stmt.test)
            pre = dict(self.env)
            t_done = self.exec_block(stmt.body)
            post_t = self.env
            self.env = pre if not stmt.orelse else dict(pre)
            f_done = self.exec_block(stmt.orelse) if stmt.orelse else False
            post_f = self.env
            if t_done and not f_done:
                self.env = post_f
            elif f_done and not t_done:
                self.env = post_t
            else:
                self.env = _join_env(post_t, post_f)
            return t_done and f_done
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval(stmt.iter)
            pre = dict(self.env)
            self._bind_target(stmt.target, _loop_elt(it))
            self.exec_block(stmt.body)
            self.exec_block(stmt.orelse)
            self.env = _join_env(pre, self.env)
        elif isinstance(stmt, ast.While):
            self.eval(stmt.test)
            pre = dict(self.env)
            self.exec_block(stmt.body)
            self.exec_block(stmt.orelse)
            self.env = _join_env(pre, self.env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, Unknown)
            return self.exec_block(stmt.body)
        elif isinstance(stmt, ast.Try):
            pre = dict(self.env)
            self.exec_block(stmt.body)
            merged = self.env
            for handler in stmt.handlers:
                self.env = dict(pre)
                if handler.name:
                    self.env[handler.name] = Unknown
                self.exec_block(handler.body)
                merged = _join_env(merged, self.env)
            self.env = merged
            self.exec_block(stmt.orelse)
            self.exec_block(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = self._nested_qual(stmt.name)
            if qual is not None:
                # late-binding closure: the env dict itself, so the
                # callee sees names bound after the def (like Python)
                self.env[stmt.name] = PyFunc(qual, closure=self.env)
            else:
                self.env[stmt.name] = Unknown
        elif isinstance(stmt, ast.ClassDef):
            self.env[stmt.name] = Unknown
        elif isinstance(stmt, ast.Assert):
            self.eval(stmt.test)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
        # Import/ImportFrom: the module-wide alias table (callgraph)
        # already covers these, including function-local imports.
        return False

    def _nested_qual(self, name: str) -> Optional[str]:
        if self.fn is not None and name in self.fn.nested:
            return self.fn.nested[name]
        if self.fn is None:  # module body
            return self.mod.functions.get(name)
        return None

    def _exec_assign(self, stmt) -> None:
        if isinstance(stmt, ast.AugAssign):
            val = self._arith(_BIN_OPS.get(type(stmt.op)),
                              self.eval(stmt.target), self.eval(stmt.value),
                              stmt, stmt.target, stmt.value)
            self._bind_target(stmt.target, val)
            return
        value_node = stmt.value
        if value_node is None:  # bare annotation
            return
        val = self.eval(value_node)
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for t in targets:
            self._bind_target(t, val)

    def _bind_target(self, target, val) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(val, PyTuple) and len(val.elts) == len(elts) \
                    and not any(isinstance(e, ast.Starred) for e in elts):
                for t, v in zip(elts, val.elts):
                    self._bind_target(t, v)
            elif isinstance(val, AbsArray) and val.shape is not None \
                    and len(val.shape) >= 1 \
                    and not any(isinstance(e, ast.Starred) for e in elts):
                # unpacking an array's leading axis: B, N = x.shape is
                # handled via PyTuple; `a, b = arr` peels axis 0
                sub = AbsArray(val.shape[1:], val.dtype, val.weak)
                for t in elts:
                    self._bind_target(t, sub)
            else:
                for t in elts:
                    inner = t.value if isinstance(t, ast.Starred) else t
                    self._bind_target(inner, Unknown)
        # Subscript/Attribute writes keep the binding's abstract value

    def _eval_effect(self, node) -> None:
        """Expression statement: evaluate for findings; additionally
        kill list variables mutated in place (``w.append(x)``) so a
        stale literal length can't fabricate a shape downstream."""
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            f = node.func
            if f.attr in ("append", "extend", "insert", "pop", "remove",
                          "clear") and isinstance(f.value, ast.Name):
                for a in node.args:
                    self.eval(a)
                if isinstance(self.env.get(f.value.id), PyTuple):
                    self.env[f.value.id] = Unknown
                return
        self.eval(node)

    # -- expressions --------------------------------------------------------

    def eval(self, node):
        try:
            return self._eval(node)
        except RecursionError:
            raise
        except Exception:
            self.interp._crashes += 1
            return Unknown

    def _eval(self, node):
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return PyBool(v)
            if isinstance(v, int):
                return PyInt(v)
            if isinstance(v, float):
                return PyFloat(v)
            if isinstance(v, str):
                return PyStr(v)
            if v is None:
                return PyNoneV()
            return Unknown
        if isinstance(node, ast.Name):
            return self.lookup(node.id)
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.BinOp):
            return self._arith(_BIN_OPS.get(type(node.op)),
                               self.eval(node.left), self.eval(node.right),
                               node, node.left, node.right)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand)
            if isinstance(node.op, ast.Not):
                return PyBool(None)
            if isinstance(node.op, ast.USub) and isinstance(v, PyInt):
                return PyInt(D.dim_binop("sub", 0, v.dim))
            if isinstance(v, AbsArray):
                return v
            return Unknown
        if isinstance(node, ast.Compare):
            left = self.eval(node.left)
            out = Unknown
            prev, prev_node = left, node.left
            for op, comp in zip(node.ops, node.comparators):
                cur = self.eval(comp)
                if isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)):
                    out = PyBool(None)
                else:
                    out = self._arith("cmp", prev, cur, node, prev_node,
                                      comp)
                prev, prev_node = cur, comp
            return out
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v) for v in node.values]
            return _join_all(vals)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return join_value(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            if any(isinstance(e, ast.Starred) for e in node.elts):
                for e in node.elts:
                    self.eval(e.value if isinstance(e, ast.Starred) else e)
                return Unknown
            return PyTuple(tuple(self.eval(e) for e in node.elts))
        if isinstance(node, ast.Lambda):
            return PyLambda(node, self.env, self.mod)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self._eval_comp(node)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.eval(v.value)
            return PyStr(None)
        if isinstance(node, ast.Slice):
            return Unknown  # handled structurally at Subscript
        if isinstance(node, ast.Starred):
            self.eval(node.value)
            return Unknown
        if isinstance(node, (ast.Dict, ast.DictComp, ast.Set,
                             ast.Await, ast.Yield, ast.YieldFrom,
                             ast.NamedExpr)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.eval(child)
            if isinstance(node, ast.NamedExpr) and \
                    isinstance(node.target, ast.Name):
                v = self.eval(node.value)
                self.env[node.target.id] = v
                return v
            return Unknown
        return Unknown

    def _eval_comp(self, node):
        gens = node.generators
        if len(gens) != 1 or gens[0].ifs or gens[0].is_async:
            self.eval(gens[0].iter)
            return Unknown
        it = self.eval(gens[0].iter)
        target = gens[0].target
        if not isinstance(it, PyTuple) or not isinstance(target, ast.Name) \
                or len(it.elts) > _MAX_LITERAL_ITER:
            # one symbolic pass so findings inside the element expr
            # still surface
            is_name = isinstance(target, ast.Name)
            saved = self.env.get(target.id, None) if is_name else None
            self._bind_target(target, _loop_elt(it))
            self.eval(node.elt)
            if is_name:
                if saved is None:
                    self.env.pop(target.id, None)
                else:
                    self.env[target.id] = saved
            return Unknown
        out = []
        saved = self.env.get(target.id, None)
        for e in it.elts:
            self.env[target.id] = e
            out.append(self.eval(node.elt))
        if saved is None:
            self.env.pop(target.id, None)
        else:
            self.env[target.id] = saved
        return PyTuple(tuple(out))

    # -- names --------------------------------------------------------------

    def lookup(self, name: str):
        if name in self.env:
            return self.env[name]
        if self.closure is not None and name in self.closure:
            return self.closure[name]
        menv = self.interp.module_env(self.mod.name) \
            if self.fn is not None else self.env
        if name in menv:
            return menv[name]
        if name in self.mod.functions:
            return PyFunc(self.mod.functions[name])
        if name in self.mod.classes:
            return Unknown
        if name in self.mod.aliases:
            return self.resolve_dotted_value(self.mod.aliases[name])
        if name in _BUILTINS:
            return PyBuiltin(name)
        return Unknown

    def resolve_dotted_value(self, dotted: str):
        idx = self.interp.index
        q = idx.resolve_dotted(dotted)
        if q in idx.functions:
            return PyFunc(q)
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            m = idx.modules.get(".".join(parts[:i]))
            if m is None:
                continue
            rest = parts[i:]
            if not rest:
                return PyModule(dotted)
            if len(rest) == 1:
                menv = self.interp.module_env(m.name)
                if rest[0] in menv:
                    return menv[rest[0]]
                if rest[0] in m.functions:
                    return PyFunc(m.functions[rest[0]])
            return Unknown
        root = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        leaf = dotted.rsplit(".", 1)[-1]
        if root in ("jax.numpy", "numpy", "jax") and D.canon_dtype(leaf):
            return PyDtype(D.canon_dtype(leaf))
        return PyModule(dotted)

    # -- attributes ---------------------------------------------------------

    def _eval_attr(self, node: ast.Attribute):
        base = self.eval(node.value)
        attr = node.attr
        if isinstance(base, PyModule):
            return self.resolve_dotted_value(base.dotted + "." + attr)
        if isinstance(base, AbsArray):
            if attr == "shape":
                if base.shape is None:
                    return Unknown
                return PyTuple(tuple(PyInt(d) for d in base.shape))
            if attr == "dtype":
                return PyDtype(base.dtype) if base.dtype else Unknown
            if attr == "ndim":
                return PyInt(base.rank())
            if attr == "size":
                return PyInt(D.numel(base.shape))
            if attr == "T":
                if base.shape is None:
                    return base
                return AbsArray(tuple(reversed(base.shape)), base.dtype,
                                base.weak)
            if attr == "at":
                return PyAt(base)
            if attr in ("real", "imag"):
                return base
            return Unknown
        return Unknown

    # -- subscripts ---------------------------------------------------------

    def _eval_subscript(self, node: ast.Subscript):
        base = self.eval(node.value)
        if isinstance(base, PyAt):
            return PyAtIndexed(base.arr)
        idx_node = node.slice
        if isinstance(base, PyTuple):
            if isinstance(idx_node, ast.Slice):
                lo = self._dim_or_none(idx_node.lower, 0)
                hi = self._dim_or_none(idx_node.upper, len(base.elts))
                step = self._dim_or_none(idx_node.step, 1)
                if all(D.is_conc(x) for x in (lo, hi, step)) and step:
                    return PyTuple(tuple(base.elts[lo:hi:step]))
                return Unknown
            iv = self.eval(idx_node)
            d = dim_of(iv)
            if D.is_conc(d) and -len(base.elts) <= d < len(base.elts):
                return base.elts[d]
            return Unknown
        if isinstance(base, AbsArray):
            return self._index_array(base, idx_node)
        if isinstance(idx_node, ast.Slice):
            for part in (idx_node.lower, idx_node.upper, idx_node.step):
                if part is not None:
                    self.eval(part)
        else:
            self.eval(idx_node)
        return Unknown

    def _dim_or_none(self, expr, default):
        if expr is None:
            return default
        return dim_of(self.eval(expr))

    def _slice_len(self, dim, lower, upper, step):
        lo = self._dim_or_none(lower, 0)
        hi = self._dim_or_none(upper, dim)
        st = self._dim_or_none(step, 1)
        if D.is_conc(dim) and D.is_conc(lo) and D.is_conc(hi) \
                and D.is_conc(st) and st != 0:
            return len(range(*slice(
                lo if lower is not None else None,
                hi if upper is not None else None,
                st).indices(dim)))
        if st == 1:
            if (lower is None or lo == 0) and upper is not None:
                # x[:k] -> min(k, dim); only safely k when k <= dim is
                # not provable, so keep a structural token
                return ("min", hi, dim) if hi != dim else dim
            if upper is None and lower is not None:
                return D.dim_binop("sub", dim, lo)
            if lower is None and upper is None:
                return dim
        return None

    def _index_array(self, base: AbsArray, idx_node):
        items = list(idx_node.elts) if isinstance(idx_node, ast.Tuple) \
            else [idx_node]
        if base.shape is None:
            for it in items:
                if not isinstance(it, ast.Slice):
                    self.eval(it)
            return AbsArray(None, base.dtype, base.weak)
        dims = list(base.shape)
        out: list = []
        # how many real axes the non-ellipsis items consume
        consumed = sum(
            1 for it in items
            if not (isinstance(it, ast.Constant)
                    and (it.value is None or it.value is Ellipsis)))
        axis = 0
        for it in items:
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(1)
                continue
            if isinstance(it, ast.Constant) and it.value is Ellipsis:
                take = len(dims) - consumed
                for _ in range(max(0, take)):
                    if axis < len(dims):
                        out.append(dims[axis])
                        axis += 1
                continue
            if axis >= len(dims):
                return AbsArray(None, base.dtype, base.weak)
            if isinstance(it, ast.Slice):
                out.append(self._slice_len(dims[axis], it.lower, it.upper,
                                           it.step))
                axis += 1
                continue
            iv = self.eval(it)
            if isinstance(iv, AbsArray) and iv.shape != ():
                if iv.dtype == "bool":
                    # boolean mask: flattens the masked axes
                    return AbsArray(None, base.dtype, base.weak)
                if iv.shape is None:
                    return AbsArray(None, base.dtype, base.weak)
                # integer gather on this axis
                out.extend(iv.shape)
                axis += 1
                continue
            d = dim_of(iv)
            if d is None and not isinstance(iv, (PyInt, AbsArray)):
                return AbsArray(None, base.dtype, base.weak)
            axis += 1  # integer index: axis dropped
        out.extend(dims[axis:])
        return AbsArray(tuple(out), base.dtype, base.weak)

    # -- arithmetic: the VL201/VL202 core -----------------------------------

    def _arith(self, op, lv, rv, node, lnode, rnode, opdesc=None):
        if op is None:
            return Unknown
        if op == "matmul":
            return self._dot(lv, rv, node, "matmul")
        if isinstance(lv, PyInt) and isinstance(rv, PyInt):
            if op in _DIM_FOLDABLE:
                return PyInt(D.dim_binop(op, lv.dim, rv.dim))
            if op == "cmp":
                return PyBool(None)
            if op == "div":
                return PyFloat(None)
            return PyInt(None)
        if isinstance(lv, (PyStr, PyTuple)) or isinstance(rv, (PyStr,
                                                               PyTuple)):
            # str/list concat & repeat stay at the Python level
            if op == "add" and isinstance(lv, PyTuple) \
                    and isinstance(rv, PyTuple):
                return PyTuple(lv.elts + rv.elts)
            if op == "mul":
                seq = lv if isinstance(lv, PyTuple) else (
                    rv if isinstance(rv, PyTuple) else None)
                n = dim_of(rv if seq is lv else lv)
                if seq is not None and D.is_conc(n) \
                        and 0 <= n * len(seq.elts) <= _MAX_LITERAL_ITER:
                    return PyTuple(seq.elts * n)
            if op == "cmp":
                return PyBool(None)
            return Unknown
        if lv is Unknown and rv is Unknown:
            return Unknown
        la, ra = to_array(lv), to_array(rv)
        shape, conflict = D.broadcast_shapes(la.shape, ra.shape)
        if conflict is not None:
            da, db, ax = conflict
            self.report(
                "VL201", node,
                f"shape mismatch: {opdesc or 'elementwise'} operands "
                f"{D.shape_str(la.shape)} vs {D.shape_str(ra.shape)} "
                f"conflict on dim {da} vs {db} (axis -{ax + 1}); "
                f"these can never broadcast")
        if op == "cmp":
            return AbsArray(shape, "bool" if (la.dtype and ra.dtype)
                            else None)
        dtype, weak = D.promote(la.dtype, la.weak, ra.dtype, ra.weak)
        if op == "div" and dtype is not None \
                and D.kind(dtype) < D.KIND_FLOAT:
            dtype, weak = "float32", False
        if dtype is not None and self.in_hash_scope():
            for arr, n_own, n_other in ((la, lnode, rnode),
                                        (ra, rnode, lnode)):
                if D.is_uint(arr.dtype) and not arr.weak \
                        and dtype != arr.dtype:
                    if _explicit_cast(lnode) or _explicit_cast(rnode):
                        break
                    self.report(
                        "VL202", node,
                        f"implicit dtype promotion {arr.dtype} -> "
                        f"{dtype} in hash arithmetic; unsigned "
                        f"wraparound semantics are lost — pin dtype= "
                        f"or add an explicit .astype")
                    break
        return AbsArray(shape, dtype, weak)

    def _dot(self, lv, rv, node, opdesc):
        la, ra = to_array(lv), to_array(rv)
        dtype, weak = D.promote(la.dtype, la.weak, ra.dtype, ra.weak)
        if la.shape is None or ra.shape is None \
                or len(la.shape) == 0 or len(ra.shape) == 0:
            return AbsArray(None, dtype, weak)
        contract_l = la.shape[-1]
        contract_r = ra.shape[-2] if len(ra.shape) >= 2 else ra.shape[0]
        if D.is_conc(contract_l) and D.is_conc(contract_r) \
                and contract_l != contract_r:
            self.report(
                "VL201", node,
                f"shape mismatch: {opdesc} contracting dims "
                f"{contract_l} vs {contract_r} "
                f"({D.shape_str(la.shape)} @ {D.shape_str(ra.shape)})")
        if len(la.shape) == 1 and len(ra.shape) == 1:
            return AbsArray((), dtype, weak)
        if len(ra.shape) == 1:
            return AbsArray(la.shape[:-1], dtype, weak)
        if len(la.shape) == 1:
            return AbsArray(ra.shape[:-2] + ra.shape[-1:], dtype, weak)
        return AbsArray(la.shape[:-1] + ra.shape[-1:], dtype, weak)

    # -- calls --------------------------------------------------------------

    def _eval_call(self, node: ast.Call):
        has_star = any(isinstance(a, ast.Starred) for a in node.args)
        args = []
        arg_nodes = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                self.eval(a.value)
            else:
                args.append(self.eval(a))
                arg_nodes.append(a)
        kwargs = {}
        for kw in node.keywords:
            v = self.eval(kw.value)
            if kw.arg is not None:
                kwargs[kw.arg] = v
        if has_star:
            args, arg_nodes = [], []  # positions unreliable: all-Unknown

        f = node.func
        if isinstance(f, ast.Attribute):
            base = self.eval(f.value)
            if isinstance(base, AbsArray):
                return self._array_method(base, f.attr, args, kwargs,
                                          node, arg_nodes)
            if isinstance(base, PyAtIndexed):
                if f.attr in ("set", "add", "max", "min", "mul",
                              "multiply", "divide", "power"):
                    return base.arr
                return Unknown
            if isinstance(base, PyTuple):
                return Unknown  # list/tuple methods
            if isinstance(base, PyDtype):
                return Unknown
            if isinstance(base, PyModule):
                fv = self.resolve_dotted_value(base.dotted + "." + f.attr)
            else:
                return Unknown
        else:
            fv = self.eval(f)

        return self._dispatch(fv, args, kwargs, node, arg_nodes)

    def _dispatch(self, fv, args, kwargs, node, arg_nodes):
        if isinstance(fv, PyFunc) or isinstance(fv, PyLambda):
            return self.invoke(fv, args, kwargs, node)
        if isinstance(fv, PyPartial):
            return self.invoke(fv, args, kwargs, node)
        if isinstance(fv, PyDtype):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return AbsArray(a.shape, fv.name, weak=False)
        if isinstance(fv, PyVmapped):
            return self._call_vmapped(fv, args, kwargs, node)
        if isinstance(fv, PyWrapped):
            inner_args = [Unknown] * len(args)
            if isinstance(fv.fn, (PyFunc, PyLambda, PyPartial)):
                self.invoke(fv.fn, inner_args, {}, node)
            return Unknown
        if isinstance(fv, PyBuiltin):
            return self._builtin(fv.name, args, kwargs, node)
        if isinstance(fv, PyModule):
            return self._api_call(fv.dotted, args, kwargs, node, arg_nodes)
        return Unknown

    # -- array methods ------------------------------------------------------

    def _array_method(self, arr: AbsArray, name: str, args, kwargs,
                      node, arg_nodes):
        if name == "astype":
            dt = dtype_from(args[0]) if args else dtype_from(
                kwargs.get("dtype", Unknown))
            return AbsArray(arr.shape, dt, weak=False)
        if name == "reshape":
            if len(args) == 1 and isinstance(args[0], (PyTuple, PyInt)):
                new = shape_from(args[0])
            else:
                new = tuple(dim_of(a) for a in args) if args else None
            return self._reshape(arr, new, node)
        if name in ("sum", "max", "min", "prod", "mean", "all", "any",
                    "argmax", "argmin", "std", "var"):
            return self._reduce(arr, name, args, kwargs)
        if name == "cumsum":
            dt = dtype_from(kwargs.get("dtype", Unknown)) or arr.dtype
            return AbsArray(arr.shape, dt, arr.weak)
        if name in ("ravel", "flatten"):
            return AbsArray((D.numel(arr.shape),), arr.dtype, arr.weak)
        if name == "transpose":
            if not args and arr.shape is not None:
                return AbsArray(tuple(reversed(arr.shape)), arr.dtype,
                                arr.weak)
            return AbsArray(None, arr.dtype, arr.weak)
        if name == "squeeze":
            if arr.shape is not None and not args and "axis" not in kwargs:
                return AbsArray(tuple(d for d in arr.shape if d != 1),
                                arr.dtype, arr.weak)
            return AbsArray(None, arr.dtype, arr.weak)
        if name in ("copy", "block_until_ready", "round", "conj"):
            return arr
        if name == "clip":
            return arr
        if name == "item":
            return Unknown
        if name == "tobytes" or name == "tolist" or name == "view":
            return Unknown
        return Unknown

    def _reshape(self, arr: AbsArray, new: Optional[tuple], node):
        if new is None:
            return AbsArray(None, arr.dtype, arr.weak)
        old_n = D.numel(arr.shape)
        minus_one = sum(1 for d in new if d == -1)
        rest = [d for d in new if d != -1]
        rest_n = 1
        for d in rest:
            if not D.is_conc(d):
                rest_n = None
                break
            rest_n *= d
        if old_n is not None and rest_n is not None:
            if minus_one == 0 and old_n != rest_n:
                self.report(
                    "VL201", node,
                    f"shape mismatch: reshape of {old_n} element(s) "
                    f"{D.shape_str(arr.shape)} to {D.shape_str(new)} "
                    f"({rest_n} element(s))")
            elif minus_one == 1 and (rest_n == 0 or old_n % rest_n):
                self.report(
                    "VL201", node,
                    f"shape mismatch: reshape of {old_n} element(s) "
                    f"{D.shape_str(arr.shape)} to {D.shape_str(new)}; "
                    f"-1 cannot divide evenly")
        out = []
        for d in new:
            if d == -1:
                out.append(old_n // rest_n
                           if (old_n is not None and rest_n) else None)
            else:
                out.append(d)
        return AbsArray(tuple(out), arr.dtype, arr.weak)

    def _reduce(self, arr: AbsArray, name: str, args, kwargs):
        axis_v = kwargs.get("axis", args[0] if args else None)
        keep = isinstance(kwargs.get("keepdims"), PyBool) \
            and kwargs["keepdims"].value is True
        dt = dtype_from(kwargs.get("dtype", Unknown))
        if dt is None:
            if name in ("argmax", "argmin"):
                dt = "int32"
            elif name in ("all", "any"):
                dt = "bool"
            elif arr.dtype is None:
                dt = None
            elif name in ("sum", "prod") and arr.dtype == "bool":
                dt = "int32"
            elif name in ("sum", "prod") and D.kind(arr.dtype) in (
                    D.KIND_UINT, D.KIND_INT) and D.width(arr.dtype) < 32:
                dt = ("uint32" if D.is_uint(arr.dtype) else "int32")
            else:
                dt = arr.dtype
        if arr.shape is None:
            return AbsArray(None, dt)
        if axis_v is None or isinstance(axis_v, PyNoneV):
            return AbsArray(tuple(1 for _ in arr.shape) if keep else (),
                            dt)
        axes = []
        if isinstance(axis_v, PyTuple):
            for e in axis_v.elts:
                d = dim_of(e)
                if not D.is_conc(d):
                    return AbsArray(None, dt)
                axes.append(d)
        else:
            d = dim_of(axis_v)
            if not D.is_conc(d):
                return AbsArray(None, dt)
            axes.append(d)
        rank = len(arr.shape)
        norm = {a % rank for a in axes if -rank <= a < rank}
        out = tuple(1 if i in norm else d
                    for i, d in enumerate(arr.shape)
                    if keep or i not in norm)
        return AbsArray(out, dt)

    # -- builtins -----------------------------------------------------------

    def _builtin(self, name: str, args, kwargs, node):
        a0 = args[0] if args else Unknown
        if name == "len":
            if isinstance(a0, PyTuple):
                return PyInt(len(a0.elts))
            if isinstance(a0, AbsArray) and a0.shape is not None \
                    and len(a0.shape) >= 1:
                return PyInt(a0.shape[0])
            return PyInt(None)
        if name == "range":
            dims = [dim_of(a) for a in args]
            if all(D.is_conc(d) for d in dims) and 1 <= len(dims) <= 3:
                r = range(*dims)
                if 0 <= len(r) <= _MAX_LITERAL_ITER:
                    return PyTuple(tuple(PyInt(i) for i in r))
            return Unknown
        if name == "int":
            d = dim_of(a0)
            return PyInt(d)
        if name == "float":
            return PyFloat(None)
        if name == "bool":
            return PyBool(None)
        if name in ("tuple", "list"):
            return a0 if isinstance(a0, PyTuple) else Unknown
        if name in ("min", "max"):
            if len(args) >= 2 and all(isinstance(a, PyInt) for a in args):
                dims = [a.dim for a in args]
                if all(D.is_conc(d) for d in dims):
                    return PyInt(min(dims) if name == "min" else max(dims))
                return PyInt(None)
            return Unknown
        if name == "abs":
            return a0 if isinstance(a0, (PyInt, AbsArray)) else Unknown
        if name == "isinstance":
            return PyBool(None)
        if name == "str":
            return PyStr(None)
        return Unknown

    # -- external API dispatch ----------------------------------------------

    def _api_call(self, dotted: str, args, kwargs, node, arg_nodes):
        root, _, fname = dotted.rpartition(".")
        if root in ("jax.numpy", "numpy"):
            return self._numpy_call(fname, args, kwargs, node, arg_nodes)
        if root == "jax.lax":
            return self._lax_call(fname, args, kwargs, node)
        if root == "jax":
            return self._jax_call(fname, args, kwargs, node)
        if dotted == "functools.partial":
            if args and isinstance(args[0], (PyFunc, PyLambda, PyModule,
                                             PyPartial)):
                return PyPartial(args[0], tuple(args[1:]), dict(kwargs))
            return Unknown
        if fname == "shard_map" and "shard_map" in dotted:
            if args and isinstance(args[0], (PyFunc, PyLambda, PyPartial)):
                return PyWrapped(args[0])
            f = kwargs.get("f")
            if isinstance(f, (PyFunc, PyLambda, PyPartial)):
                return PyWrapped(f)
            return Unknown
        return Unknown

    def _arg_or_kw(self, args, kwargs, i, name, default=Unknown):
        if i < len(args):
            return args[i]
        return kwargs.get(name, default)

    def _numpy_call(self, fname, args, kwargs, node, arg_nodes):
        if fname in ("zeros", "ones", "empty"):
            shape = shape_from(self._arg_or_kw(args, kwargs, 0, "shape"))
            dt = dtype_from(self._arg_or_kw(args, kwargs, 1, "dtype")) \
                or "float32"
            return AbsArray(shape, dt)
        if fname == "full":
            shape = shape_from(self._arg_or_kw(args, kwargs, 0, "shape"))
            fill = to_array(self._arg_or_kw(args, kwargs, 1, "fill_value"))
            dt = dtype_from(self._arg_or_kw(args, kwargs, 2, "dtype")) \
                or fill.dtype
            return AbsArray(shape, dt)
        if fname in ("zeros_like", "ones_like", "full_like", "empty_like"):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            dt = dtype_from(kwargs.get("dtype", Unknown)) or a.dtype
            return AbsArray(a.shape, dt)
        if fname == "arange":
            return self._arange(args, kwargs)
        if fname in ("asarray", "array", "ascontiguousarray"):
            v = args[0] if args else Unknown
            dt = dtype_from(self._arg_or_kw(args, kwargs, 1, "dtype"))
            if isinstance(v, PyTuple):
                return _literal_array(v, dt)
            a = to_array(v)
            return AbsArray(a.shape, dt or a.dtype, weak=False)
        if fname == "astype":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            dt = dtype_from(self._arg_or_kw(args, kwargs, 1, "dtype"))
            return AbsArray(a.shape, dt, weak=False)
        if fname == "frombuffer":
            dt = dtype_from(self._arg_or_kw(args, kwargs, 1, "dtype"))
            return AbsArray((None,), dt)
        if fname in ("cumsum", "cumprod"):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            dt = dtype_from(kwargs.get("dtype", Unknown)) or a.dtype
            return AbsArray(a.shape, dt)
        if fname == "pad":
            return self._pad(args, kwargs)
        if fname == "concatenate":
            return self._concatenate(args, kwargs, node)
        if fname == "stack":
            return self._stack(args, kwargs, node)
        if fname == "reshape":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            spec = self._arg_or_kw(args, kwargs, 1, "shape",
                                   kwargs.get("newshape", Unknown))
            return self._reshape(a, shape_from(spec), node)
        if fname == "broadcast_to":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            shape = shape_from(self._arg_or_kw(args, kwargs, 1, "shape"))
            if shape is not None and a.shape is not None:
                _, conflict = D.broadcast_shapes(a.shape, shape)
                if conflict is not None:
                    da, db, _ax = conflict
                    self.report(
                        "VL201", node,
                        f"shape mismatch: broadcast_to "
                        f"{D.shape_str(a.shape)} -> {D.shape_str(shape)} "
                        f"conflicts on dim {da} vs {db}")
            return AbsArray(shape, a.dtype, a.weak)
        if fname == "where":
            if len(args) < 3:
                return Unknown
            # x/y promote like an elementwise op; the condition only
            # broadcasts (it never participates in dtype promotion)
            out = self._arith("add", args[1], args[2], node,
                              arg_nodes[1], arg_nodes[2], opdesc="where")
            cond = to_array(args[0])
            oa = to_array(out)
            shape, conflict = D.broadcast_shapes(cond.shape, oa.shape)
            if conflict is not None:
                da, db, ax = conflict
                self.report(
                    "VL201", node,
                    f"shape mismatch: where condition "
                    f"{D.shape_str(cond.shape)} vs operands "
                    f"{D.shape_str(oa.shape)} conflict on dim {da} vs "
                    f"{db} (axis -{ax + 1}); these can never broadcast")
            if isinstance(out, AbsArray):
                return AbsArray(shape, out.dtype, out.weak)
            return out
        if fname in ("minimum", "maximum", "add", "multiply", "subtract",
                     "bitwise_and", "bitwise_or", "bitwise_xor",
                     "left_shift", "right_shift", "mod", "remainder"):
            if len(args) < 2:
                return Unknown
            op = {"add": "add", "multiply": "mul", "subtract": "sub",
                  "left_shift": "shl", "right_shift": "shr",
                  "mod": "mod", "remainder": "mod"}.get(fname, "and")
            return self._arith(op, args[0], args[1], node, arg_nodes[0],
                               arg_nodes[1], opdesc=fname)
        if fname in ("dot", "matmul"):
            if len(args) < 2:
                return Unknown
            return self._dot(args[0], args[1], node, fname)
        if fname in ("sum", "max", "min", "prod", "mean", "all", "any",
                     "argmax", "argmin"):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return self._reduce(a, fname, args[1:], kwargs)
        if fname == "clip":
            return to_array(args[0]) if args else Unknown
        if fname == "nonzero":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            size = dim_of(kwargs.get("size", Unknown))
            rank = a.rank() or 1
            return PyTuple(tuple(AbsArray((size,), "int32")
                                 for _ in range(rank)))
        if fname == "searchsorted":
            v = to_array(args[1]) if len(args) > 1 else UNKNOWN_ARRAY
            return AbsArray(v.shape, "int32")
        if fname in ("sort",):
            return to_array(args[0]) if args else Unknown
        if fname == "argsort":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return AbsArray(a.shape, "int32")
        if fname == "take_along_axis":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            idx = to_array(args[1]) if len(args) > 1 else UNKNOWN_ARRAY
            return AbsArray(idx.shape, a.dtype)
        if fname == "transpose":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            perm = shape_from(self._arg_or_kw(args, kwargs, 1, "axes"))
            if a.shape is not None and perm is not None \
                    and all(D.is_conc(p) for p in perm) \
                    and len(perm) == len(a.shape) \
                    and sorted(perm) == list(range(len(a.shape))):
                return AbsArray(tuple(a.shape[p] for p in perm), a.dtype,
                                a.weak)
            if a.shape is not None and len(args) == 1 and not kwargs:
                return AbsArray(tuple(reversed(a.shape)), a.dtype, a.weak)
            return AbsArray(None, a.dtype, a.weak)
        if fname == "moveaxis":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            s = dim_of(args[1]) if len(args) > 1 else None
            d = dim_of(args[2]) if len(args) > 2 else None
            if a.shape is not None and D.is_conc(s) and D.is_conc(d):
                dims = list(a.shape)
                try:
                    dims.insert(d if d >= 0 else len(dims) + d + 1,
                                dims.pop(s))
                    return AbsArray(tuple(dims), a.dtype, a.weak)
                except IndexError:
                    return AbsArray(None, a.dtype, a.weak)
            return AbsArray(None, a.dtype, a.weak)
        if fname in ("uint8", "uint16", "uint32", "uint64", "int8",
                     "int16", "int32", "int64", "float16", "float32",
                     "float64", "bool_"):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return AbsArray(a.shape, D.canon_dtype(fname), weak=False)
        return Unknown

    def _arange(self, args, kwargs):
        dims = [dim_of(a) for a in args[:3]]
        dt = dtype_from(self._arg_or_kw(args, kwargs, 3, "dtype"))
        if dt is None:
            dt = "float32" if any(isinstance(a, PyFloat)
                                  for a in args[:3]) else "int32"
        if len(dims) == 1:
            return AbsArray((dims[0],), dt)
        if len(dims) >= 2:
            start, stop = dims[0], dims[1]
            step = dims[2] if len(dims) > 2 else 1
            if all(D.is_conc(x) for x in (start, stop, step)) and step:
                return AbsArray((len(range(start, stop, step)),), dt)
            if step == 1:
                return AbsArray((D.dim_binop("sub", stop, start),), dt)
            return AbsArray((None,), dt)
        return AbsArray((None,), dt)

    def _pad(self, args, kwargs):
        a = to_array(args[0]) if args else UNKNOWN_ARRAY
        spec = args[1] if len(args) > 1 else Unknown
        if a.shape is None:
            return AbsArray(None, a.dtype, a.weak)
        if isinstance(spec, PyTuple) and len(a.shape) == 1 \
                and len(spec.elts) == 2 \
                and all(isinstance(e, PyInt) for e in spec.elts):
            lo, hi = (e.dim for e in spec.elts)
            d = D.dim_binop("add", D.dim_binop("add", a.shape[0], lo), hi)
            return AbsArray((d,), a.dtype, a.weak)
        if isinstance(spec, PyTuple) \
                and len(spec.elts) == len(a.shape) \
                and all(isinstance(e, PyTuple) and len(e.elts) == 2
                        for e in spec.elts):
            out = []
            for d, e in zip(a.shape, spec.elts):
                lo, hi = (dim_of(x) for x in e.elts)
                out.append(D.dim_binop("add", D.dim_binop("add", d, lo),
                                       hi))
            return AbsArray(tuple(out), a.dtype, a.weak)
        return AbsArray(None, a.dtype, a.weak)

    def _concatenate(self, args, kwargs, node):
        seq = args[0] if args else Unknown
        axis = dim_of(self._arg_or_kw(args, kwargs, 1, "axis", PyInt(0)))
        if not isinstance(seq, PyTuple):
            return Unknown
        arrs = [to_array(e) for e in seq.elts]
        dts = {a.dtype for a in arrs if a.dtype is not None}
        dt = dts.pop() if len(dts) == 1 else None
        shapes = [a.shape for a in arrs]
        if any(s is None for s in shapes) or not shapes \
                or not D.is_conc(axis):
            return AbsArray(None, dt)
        rank = len(shapes[0])
        if any(len(s) != rank for s in shapes) \
                or not (-rank <= axis < rank):
            return AbsArray(None, dt)
        ax = axis % rank
        for i in range(rank):
            if i == ax:
                continue
            dims = [s[i] for s in shapes]
            conc = [d for d in dims if D.is_conc(d)]
            if len(set(conc)) > 1:
                self.report(
                    "VL201", node,
                    f"shape mismatch: concatenate along axis {ax} "
                    f"requires equal non-axis dims, got "
                    f"{' vs '.join(D.shape_str(s) for s in shapes)}")
                return AbsArray(None, dt)
        total = 0
        for s in shapes:
            total = D.dim_binop("add", total, s[ax])
        out = list(shapes[0])
        out[ax] = total
        return AbsArray(tuple(out), dt)

    def _stack(self, args, kwargs, node):
        seq = args[0] if args else Unknown
        axis = dim_of(self._arg_or_kw(args, kwargs, 1, "axis", PyInt(0)))
        if not isinstance(seq, PyTuple):
            return Unknown
        arrs = [to_array(e) for e in seq.elts]
        dts = {a.dtype for a in arrs if a.dtype is not None}
        dt = dts.pop() if len(dts) == 1 else None
        shapes = [a.shape for a in arrs]
        if any(s is None for s in shapes) or not shapes:
            return AbsArray(None, dt)
        first = shapes[0]
        for s in shapes[1:]:
            if len(s) != len(first):
                return AbsArray(None, dt)
            for da, db in zip(first, s):
                if D.is_conc(da) and D.is_conc(db) and da != db:
                    self.report(
                        "VL201", node,
                        f"shape mismatch: stack requires equal shapes, "
                        f"got {D.shape_str(first)} vs {D.shape_str(s)}")
                    return AbsArray(None, dt)
        if not D.is_conc(axis) or not (-len(first) - 1 <= axis
                                       <= len(first)):
            return AbsArray(None, dt)
        out = list(first)
        out.insert(axis if axis >= 0 else len(first) + 1 + axis,
                   len(arrs))
        return AbsArray(tuple(out), dt)

    # -- lax ----------------------------------------------------------------

    def _lax_call(self, fname, args, kwargs, node):
        if fname == "scan":
            return self._lax_scan(args, kwargs, node)
        if fname == "fori_loop":
            return self._fori_loop(args, kwargs, node)
        if fname == "while_loop":
            return self._while_loop(args, kwargs, node)
        if fname == "cond":
            return self._lax_cond(args, kwargs, node)
        if fname in ("select", "select_n"):
            branches = args[1:] if len(args) > 1 else []
            return self._join_all([to_array(b) if not isinstance(b, PyTuple)
                                   else b for b in branches]) \
                if branches else Unknown
        if fname == "slice_in_dim":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return AbsArray(None, a.dtype, a.weak) if a.shape is None \
                else AbsArray(tuple(None for _ in a.shape), a.dtype, a.weak)
        if fname == "dynamic_index_in_dim":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            keep = kwargs.get("keepdims", Unknown)
            if a.shape is None or len(a.shape) == 0:
                return UNKNOWN_ARRAY
            if isinstance(keep, PyBool) and keep.value is False:
                return AbsArray(a.shape[1:], a.dtype, a.weak)
            return AbsArray((1,) + a.shape[1:], a.dtype, a.weak)
        if fname == "dynamic_slice_in_dim":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            size = dim_of(args[2]) if len(args) > 2 else None
            axis = dim_of(self._arg_or_kw(args, kwargs, 3, "axis",
                                          PyInt(0)))
            if a.shape is not None and D.is_conc(axis) \
                    and -len(a.shape) <= axis < len(a.shape):
                dims = list(a.shape)
                dims[axis] = size
                return AbsArray(tuple(dims), a.dtype, a.weak)
            return AbsArray(None, a.dtype, a.weak)
        if fname in ("psum", "pmax", "pmin", "pmean", "ppermute"):
            return args[0] if args else Unknown
        if fname == "all_gather":
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            return AbsArray(None, a.dtype, a.weak)
        if fname == "axis_index":
            return AbsArray((), "int32")
        if fname == "axis_size":
            return PyInt(None)
        if fname in ("bitcast_convert_type", "convert_element_type"):
            a = to_array(args[0]) if args else UNKNOWN_ARRAY
            dt = dtype_from(self._arg_or_kw(args, kwargs, 1, "new_dtype"))
            return AbsArray(a.shape, dt, weak=False)
        if fname == "stop_gradient":
            return args[0] if args else Unknown
        return Unknown

    def _compare_carry(self, init, out, node, where):
        """VL203 core: the carry out of a loop body must structurally
        match its init — tuple arity, dtype, rank, and concrete dims.
        Unknown on either side keeps quiet."""
        if isinstance(init, PyTuple) and isinstance(out, PyTuple):
            if len(init.elts) != len(out.elts):
                self.report(
                    "VL203", node,
                    f"{where} carry arity changed: init has "
                    f"{len(init.elts)} element(s), body returns "
                    f"{len(out.elts)}")
                return
            for i, (a, b) in enumerate(zip(init.elts, out.elts)):
                self._compare_carry(a, b, node,
                                    f"{where} carry[{i}]")
            return
        if isinstance(init, PyTuple) or isinstance(out, PyTuple):
            return  # mixed tuple/array: structure not tracked — silent
        ia, oa = to_array(init), to_array(out)
        if ia is UNKNOWN_ARRAY and init is Unknown:
            return
        if oa is UNKNOWN_ARRAY and out is Unknown:
            return
        if ia.dtype is not None and oa.dtype is not None \
                and ia.dtype != oa.dtype and not (ia.weak or oa.weak):
            self.report(
                "VL203", node,
                f"{where} dtype drifts from init {ia.dtype} to "
                f"{oa.dtype}; every step retraces (or the carry NaNs) — "
                f"cast the body result back to the init dtype")
            return
        if ia.shape is not None and oa.shape is not None:
            if len(ia.shape) != len(oa.shape):
                self.report(
                    "VL203", node,
                    f"{where} rank drifts from init "
                    f"{D.shape_str(ia.shape)} to {D.shape_str(oa.shape)}")
                return
            for da, db in zip(ia.shape, oa.shape):
                if D.is_conc(da) and D.is_conc(db) and da != db:
                    self.report(
                        "VL203", node,
                        f"{where} shape drifts from init "
                        f"{D.shape_str(ia.shape)} to "
                        f"{D.shape_str(oa.shape)}")
                    return

    def _lax_scan(self, args, kwargs, node):
        f = self._arg_or_kw(args, kwargs, 0, "f")
        init = self._arg_or_kw(args, kwargs, 1, "init")
        xs = self._arg_or_kw(args, kwargs, 2, "xs")
        elt = Unknown
        xa = to_array(xs) if not isinstance(xs, PyTuple) else None
        if isinstance(xs, PyTuple):
            elt = PyTuple(tuple(_loop_elt(e) for e in xs.elts))
        elif xa is not None and xa.shape is not None and len(xa.shape):
            elt = AbsArray(xa.shape[1:], xa.dtype, xa.weak)
        ret = self._call_loop_body(f, [init, elt], node)
        carry_out = Unknown
        if isinstance(ret, PyTuple) and len(ret.elts) == 2:
            carry_out = ret.elts[0]
        elif ret is not Unknown and not isinstance(ret, PyTuple):
            carry_out = ret  # malformed body; compare what we have
        self._compare_carry(init, carry_out, node, "lax.scan")
        return PyTuple((join_value(init, carry_out), Unknown))

    def _fori_loop(self, args, kwargs, node):
        f = self._arg_or_kw(args, kwargs, 2, "body_fun")
        init = self._arg_or_kw(args, kwargs, 3, "init_val")
        ret = self._call_loop_body(f, [PyInt(None), init], node)
        self._compare_carry(init, ret, node, "lax.fori_loop")
        return join_value(init, ret)

    def _while_loop(self, args, kwargs, node):
        f = self._arg_or_kw(args, kwargs, 1, "body_fun")
        init = self._arg_or_kw(args, kwargs, 2, "init_val")
        ret = self._call_loop_body(f, [init], node)
        self._compare_carry(init, ret, node, "lax.while_loop")
        return join_value(init, ret)

    def _call_loop_body(self, f, args, node):
        if isinstance(f, (PyFunc, PyLambda, PyPartial)):
            return self.invoke(f, args, {}, node)
        return Unknown

    def _lax_cond(self, args, kwargs, node):
        if len(args) >= 3:
            operands = list(args[3:])
            t = self.invoke(args[1], operands, {}, node) \
                if isinstance(args[1], (PyFunc, PyLambda, PyPartial)) \
                else Unknown
            fv = self.invoke(args[2], operands, {}, node) \
                if isinstance(args[2], (PyFunc, PyLambda, PyPartial)) \
                else Unknown
            return join_value(t, fv)
        return Unknown

    # -- jax ----------------------------------------------------------------

    def _jax_call(self, fname, args, kwargs, node):
        if fname == "vmap":
            return self._make_vmapped(args, kwargs, node)
        if fname in ("jit", "checkpoint", "remat", "named_call"):
            fn = self._arg_or_kw(args, kwargs, 0, "fun")
            return fn if isinstance(fn, (PyFunc, PyLambda, PyPartial,
                                         PyVmapped, PyWrapped)) else Unknown
        if fname in ("block_until_ready", "device_put", "device_get"):
            return args[0] if args else Unknown
        if fname == "default_backend":
            return PyStr(None)
        if fname in ("grad", "value_and_grad"):
            return Unknown
        return Unknown

    def _make_vmapped(self, args, kwargs, node):
        fn = self._arg_or_kw(args, kwargs, 0, "fun")
        if not isinstance(fn, (PyFunc, PyLambda, PyPartial)):
            return Unknown
        in_axes = self._arg_or_kw(args, kwargs, 1, "in_axes", PyInt(0))
        out_axes = kwargs.get("out_axes", PyInt(0))
        vm = PyVmapped(fn, in_axes, out_axes, node)
        self._check_vmap_arity(vm, node)
        return vm

    def _vmap_target_params(self, fn):
        """(min_args, max_args, fi) for a vmapped callee, or None when
        the signature isn't statically known (lambdas count, *args
        doesn't)."""
        offset = 0
        while isinstance(fn, PyPartial):
            offset += len(fn.args)
            fn = fn.fn
        if isinstance(fn, PyLambda):
            a = fn.node.args
            if a.vararg is not None:
                return None
            total = len(a.args)
            lo = total - len(a.defaults)
            return (max(0, lo - offset), max(0, total - offset), None)
        if isinstance(fn, PyFunc):
            fi = self.interp.index.functions.get(fn.qual)
            if fi is None:
                return None
            a = fi.node.args
            if a.vararg is not None:
                return None
            params = list(fi.params)
            if params and fi.cls is not None and params[0] in ("self",
                                                               "cls"):
                params = params[1:]
            total = len(params)
            lo = total - len(a.defaults)
            return (max(0, lo - offset), max(0, total - offset), fi)
        return None

    def _check_vmap_arity(self, vm, node):
        sig = self._vmap_target_params(vm.fn)
        if sig is None:
            return
        lo, hi, _fi = sig
        ax = vm.in_axes
        if isinstance(ax, PyTuple):
            n = len(ax.elts)
            if n < lo or n > hi:
                want = str(lo) if lo == hi else f"{lo}..{hi}"
                self.report(
                    "VL204", node,
                    f"vmap in_axes has {n} entr"
                    f"{'y' if n == 1 else 'ies'} but the mapped "
                    f"function takes {want} argument"
                    f"{'' if hi == 1 else 's'}")

    def _call_vmapped(self, vm, args, kwargs, node):
        sig = self._vmap_target_params(vm.fn)
        axes = None
        if isinstance(vm.in_axes, PyTuple):
            axes = [dim_of(e) if not isinstance(e, PyNoneV) else "none"
                    for e in vm.in_axes.elts]
        elif isinstance(vm.in_axes, PyInt):
            axes = [vm.in_axes.dim] * len(args)
        elif isinstance(vm.in_axes, PyNoneV):
            axes = ["none"] * len(args)
        if axes is not None and len(axes) < len(args):
            axes = axes + [None] * (len(args) - len(axes))
        inner_args = []
        mapped_dim = None
        for i, v in enumerate(args):
            ax = axes[i] if axes is not None else None
            if ax == "none" or ax is None and axes is None:
                inner_args.append(v)
                continue
            a = to_array(v)
            if ax is None or not D.is_conc(ax):
                inner_args.append(AbsArray(None, a.dtype, a.weak)
                                  if a is not UNKNOWN_ARRAY or
                                  isinstance(v, AbsArray) else Unknown)
                continue
            if isinstance(v, AbsArray) and v.shape is not None:
                rank = len(v.shape)
                if not (-rank <= ax < rank):
                    self.report(
                        "VL204", node,
                        f"vmap maps axis {ax} of argument {i} but the "
                        f"operand has shape {D.shape_str(v.shape)} "
                        f"(rank {rank})")
                    inner_args.append(UNKNOWN_ARRAY)
                    continue
                dims = list(v.shape)
                d = dims.pop(ax % rank)
                if mapped_dim is None:
                    mapped_dim = d
                elif D.is_conc(mapped_dim) and D.is_conc(d) \
                        and mapped_dim != d:
                    self.report(
                        "VL204", node,
                        f"vmap mapped axes disagree: argument {i} maps "
                        f"a dim of {d} but an earlier argument maps "
                        f"{mapped_dim}")
                    mapped_dim = None
                inner_args.append(AbsArray(tuple(dims), v.dtype, v.weak))
            else:
                inner_args.append(UNKNOWN_ARRAY if isinstance(v, AbsArray)
                                  else Unknown)
        ret = self.invoke(vm.fn, inner_args, dict(kwargs), node)
        out_axes = vm.out_axes
        if isinstance(out_axes, PyTuple) and isinstance(ret, PyTuple) \
                and len(out_axes.elts) != len(ret.elts):
            self.report(
                "VL204", node,
                f"vmap out_axes has {len(out_axes.elts)} entries but "
                f"the mapped function returns {len(ret.elts)} value(s)")
        def lift(v, ax):
            if isinstance(ax, PyNoneV):
                return v
            a = to_array(v)
            axd = dim_of(ax)
            if isinstance(v, AbsArray) and v.shape is not None \
                    and D.is_conc(axd) \
                    and 0 <= axd <= len(v.shape):
                dims = list(v.shape)
                dims.insert(axd, mapped_dim)
                return AbsArray(tuple(dims), v.dtype, v.weak)
            return AbsArray(None, a.dtype, a.weak) \
                if isinstance(v, AbsArray) else Unknown
        if isinstance(ret, PyTuple):
            if isinstance(out_axes, PyTuple) \
                    and len(out_axes.elts) == len(ret.elts):
                return PyTuple(tuple(lift(v, a) for v, a
                                     in zip(ret.elts, out_axes.elts)))
            return PyTuple(tuple(lift(v, out_axes) for v in ret.elts))
        return lift(ret, out_axes)


# -- iteration / env joins --------------------------------------------------

def _loop_elt(v):
    """Per-iteration element of an abstract iterable."""
    if isinstance(v, PyTuple):
        out = None
        for e in v.elts:
            out = e if out is None else join_value(out, e)
        return Unknown if out is None else out
    if isinstance(v, AbsArray):
        if v.shape is not None and len(v.shape) >= 1:
            return AbsArray(v.shape[1:], v.dtype, v.weak)
        return AbsArray(None, v.dtype, v.weak)
    return Unknown


def _join_env(a: dict, b: dict) -> dict:
    """Join two environments: a name bound in both joins pointwise; a
    name bound on only one path keeps that binding (reading it on the
    other path would have raised, which concrete execution surfaces)."""
    out = dict(a)
    for k, vb in b.items():
        if k in a:
            out[k] = join_value(a[k], vb)
        else:
            out[k] = vb
    return out


_BUILTINS = frozenset({
    "len", "range", "int", "float", "bool", "tuple", "list", "min",
    "max", "abs", "isinstance", "str", "sum", "enumerate", "zip",
    "sorted", "reversed", "any", "all", "repr", "divmod", "print",
})


# -- VL205: mesh axis names -------------------------------------------------

_COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "axis_index": 0, "axis_size": 0,
}


def _is_mesh_module(relpath: str) -> bool:
    return relpath.replace("\\", "/").endswith("parallel/mesh.py")


class _ModNames:
    """Per-module name facts for the VL205 pass: which local names mean
    ``PartitionSpec`` / ``Mesh`` / a ``jax.lax`` collective, and an
    unambiguous local-name -> string-constant map."""

    def __init__(self, mod):
        self.mod = mod
        self.pspec: set = set()
        self.mesh: set = set()
        self.collective: dict = {}   # local name -> collective fname
        self.consts: dict = {}       # local name -> str (unambiguous)
        ambiguous: set = set()
        for name, target in mod.aliases.items():
            leaf = target.rpartition(".")[2]
            if leaf == "PartitionSpec":
                self.pspec.add(name)
            elif leaf == "Mesh":
                self.mesh.add(name)
            elif leaf in _COLLECTIVE_AXIS_ARG and target.startswith("jax"):
                self.collective[name] = leaf
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "PartitionSpec":
                        self.pspec.add(bound)
                    elif alias.name == "Mesh":
                        self.mesh.add(bound)
                    elif alias.name in _COLLECTIVE_AXIS_ARG \
                            and node.module.startswith("jax"):
                        self.collective[bound] = alias.name
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    if tgt in self.consts and self.consts[tgt] \
                            != node.value.value:
                        ambiguous.add(tgt)
                    else:
                        self.consts[tgt] = node.value.value
                elif isinstance(node.value, ast.Name):
                    if node.value.id in self.pspec:
                        self.pspec.add(tgt)
                    elif node.value.id in self.mesh:
                        self.mesh.add(tgt)
        for tgt in ambiguous:
            self.consts.pop(tgt, None)


def _collect_declared_axes(index: ProjectIndex, names_of) -> set:
    """Axis names declared in ``parallel/mesh.py`` modules: module-level
    ``*_AXIS = "..."`` constants plus the axis-name tuples of ``Mesh``
    constructor calls there."""
    axes: set = set()
    found_mesh_module = False
    for mod in index.modules.values():
        if not _is_mesh_module(mod.relpath):
            continue
        found_mesh_module = True
        mn = names_of(mod)
        for stmt in mod.ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id.endswith("_AXIS") \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                axes.add(stmt.value.value)
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                nm = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if nm in mn.mesh:
                    for ax in _mesh_call_axes(node, mn, index, names_of):
                        axes.add(ax)
    return axes if found_mesh_module else None


def _resolve_axis_str(node, mn, index, names_of):
    """Resolve an axis-name expression to a string, or None."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.Name):
        target = mn.mod.aliases.get(node.id)
        if target is not None:
            return _dotted_const(target, index, names_of)
        return mn.consts.get(node.id)
    if isinstance(node, ast.Attribute):
        chain = attr_chain(node)
        if chain and chain[0] in mn.mod.aliases:
            dotted = ".".join([mn.mod.aliases[chain[0]]] + chain[1:])
            return _dotted_const(dotted, index, names_of)
    return None


def _dotted_const(dotted: str, index, names_of):
    """``pkg.mod.NAME`` -> the string NAME is bound to in pkg.mod."""
    parts = dotted.split(".")
    for cut in range(len(parts) - 1, 0, -1):
        mod = index.modules.get(".".join(parts[:cut]))
        if mod is not None:
            rest = parts[cut:]
            if len(rest) == 1:
                return names_of(mod).consts.get(rest[0])
            return None
    return None


def _axis_operands(node, mn, index, names_of):
    """Flatten an axis argument (string / name / tuple of those) to a
    list of (resolved_or_None, expr_node)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_axis_operands(e, mn, index, names_of))
        return out
    if isinstance(node, ast.Constant) and node.value is None:
        return []
    return [(_resolve_axis_str(node, mn, index, names_of), node)]


def _mesh_call_axes(call, mn, index, names_of):
    """Resolved axis-name strings of a Mesh(...) constructor call."""
    spec = None
    if len(call.args) >= 2:
        spec = call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_names":
            spec = kw.value
    if spec is None:
        return []
    return [ax for ax, _ in _axis_operands(spec, mn, index, names_of)
            if ax is not None]


def check_mesh_axes(index: ProjectIndex):
    """VL205 — every PartitionSpec entry, collective axis name, and
    out-of-mesh-module Mesh axis tuple must use an axis declared in
    ``parallel/mesh.py``. Silent when the project has no mesh module
    or a name doesn't resolve to a string."""
    cache: dict = {}

    def names_of(mod):
        got = cache.get(mod.name)
        if got is None:
            got = _ModNames(mod)
            cache[mod.name] = got
        return got

    declared = _collect_declared_axes(index, names_of)
    findings: list = []
    if not declared:
        return findings

    def flag(mod, expr, ax, what):
        findings.append(finding_at(
            mod.relpath, expr, "VL205",
            f"unknown mesh axis '{ax}' in {what}; declared axes are "
            f"{sorted(declared)} (parallel/mesh.py)",
            severity=_SEVERITY["VL205"]))

    for mod in index.modules.values():
        mn = names_of(mod)
        in_mesh_mod = _is_mesh_module(mod.relpath)
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            nm = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if nm is None:
                continue
            if nm in mn.pspec:
                for arg in node.args:
                    for ax, expr in _axis_operands(arg, mn, index,
                                                   names_of):
                        if ax is not None and ax not in declared:
                            flag(mod, expr, ax, "PartitionSpec")
                continue
            if nm in mn.mesh and not in_mesh_mod:
                for kw_or_pos in ([node.args[1]] if len(node.args) >= 2
                                  else []) + \
                        [kw.value for kw in node.keywords
                         if kw.arg == "axis_names"]:
                    for ax, expr in _axis_operands(kw_or_pos, mn, index,
                                                   names_of):
                        if ax is not None and ax not in declared:
                            flag(mod, expr, ax, "Mesh axis_names")
                continue
            cname = mn.collective.get(nm)
            if cname is None and isinstance(fn, ast.Attribute):
                chain = attr_chain(fn)
                if chain and chain[0] in mn.mod.aliases:
                    dotted = ".".join([mn.mod.aliases[chain[0]]]
                                      + chain[1:])
                    if dotted.startswith("jax"):
                        leaf = dotted.rpartition(".")[2]
                        if leaf in _COLLECTIVE_AXIS_ARG:
                            cname = leaf
            if cname is not None:
                idx = _COLLECTIVE_AXIS_ARG[cname]
                spec = node.args[idx] if len(node.args) > idx else None
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        spec = kw.value
                if spec is None:
                    continue
                for ax, expr in _axis_operands(spec, mn, index,
                                               names_of):
                    if ax is not None and ax not in declared:
                        flag(mod, expr, ax, f"lax.{cname}")
    return findings


# -- rule classes -----------------------------------------------------------

_SHAPE_RESULTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _interp_for(index: ProjectIndex) -> "Interp":
    """Run the abstract interpreter once per ProjectIndex; the five
    VL2xx rules (and the cache's summary snapshot) share the result."""
    got = _SHAPE_RESULTS.get(index)
    if got is None:
        got = Interp(index)
        got.run()
        _SHAPE_RESULTS[index] = got
    return got


def _analysis_for(index: ProjectIndex) -> list:
    return _interp_for(index).found


def summaries_for(index: ProjectIndex) -> dict:
    """{relpath: {qualname: rendered return summary}} — what the
    incremental cache stores per file."""
    return _interp_for(index).summaries


class _ShapeRule:
    def check_project(self, index: ProjectIndex):
        for f in _analysis_for(index):
            if f.code == self.code:
                yield f


class ShapeMismatchRule(_ShapeRule):
    """VL201 — statically incompatible operand shapes."""

    code = "VL201"
    name = "shape-mismatch"
    severity = "error"
    description = ("elementwise/dot/reshape/concatenate operands with "
                   "statically incompatible shapes (concrete dims that "
                   "can never broadcast or contract)")


class DtypePromotionRule(_ShapeRule):
    """VL202 — implicit unsigned promotion in hash arithmetic."""

    code = "VL202"
    name = "implicit-dtype-promotion"
    severity = "warning"
    description = ("implicit dtype promotion crossing a width or "
                   "signedness boundary on uint hash state in ops/ "
                   "kernels without an explicit .astype/dtype=")


class CarryDriftRule(_ShapeRule):
    """VL203 — scan/loop carry drifts from its init."""

    code = "VL203"
    name = "carry-drift"
    severity = "error"
    description = ("lax.scan/fori_loop/while_loop body returns a carry "
                   "whose shape or dtype differs from the init (retrace "
                   "storm / NaN trap)")


class VmapAxesRule(_ShapeRule):
    """VL204 — vmap in_axes/out_axes inconsistent with the callee."""

    code = "VL204"
    name = "vmap-axes"
    severity = "error"
    description = ("vmap in_axes/out_axes arity disagrees with the "
                   "mapped function's signature, or a mapped axis is "
                   "out of range / inconsistent across operands")


class MeshAxisRule(_ShapeRule):
    """VL205 — undeclared mesh axis name."""

    code = "VL205"
    name = "mesh-axis"
    severity = "error"
    description = ("PartitionSpec / collective / Mesh axis name not "
                   "declared in parallel/mesh.py")


def default_shape_rules() -> list:
    return [ShapeMismatchRule(), DtypePromotionRule(), CarryDriftRule(),
            VmapAxesRule(), MeshAxisRule()]
