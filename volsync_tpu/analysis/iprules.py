"""Interprocedural lint rules over the project call graph.

These rules see what the per-file rules (analysis/rules.py) structurally
cannot: a blocking call two call-hops below a ``with lock:`` region, a
thread started in ``start()`` and joined (or not) in ``stop()``, tracer
taint flowing through a helper into a Python branch. Each consumes the
``ProjectIndex`` from analysis/callgraph.py and the fixpoints from
analysis/dataflow.py.

VL101  blocking-call-under-lock: any path from a lockcheck-built lock
       region in repo/engine/objstore to store I/O, socket/HTTP, or
       time.sleep. Messages carry the lockcheck lock NAME so a static
       finding correlates with a runtime LockOrderError on the same
       name. Suppressible on the sink line or on the region's ``with``
       header (one reviewed justification covers the region).
VL102  thread/future lifecycle: threads started without a name,
       non-daemon threads with no reachable join, executors with no
       reachable shutdown (with-statement and ownership-transfer-by-
       argument are fine).
VL103  exception-path resource leak: .acquire()/open() outside a with
       or try-finally in the data-plane modules.
VL104  interprocedural tracer-taint: a traced value inside a jit'd
       ops/ kernel passed to a helper whose parameter reaches a
       concretizing sink (Python branch, int()/float()/bool()),
       or a Python branch on a tainted local derived from traced args.
       VL004 remains the per-function fallback for unresolved calls.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from volsync_tpu.analysis.callgraph import (
    ModuleInfo,
    ProjectIndex,
    attr_chain,
)
from volsync_tpu.analysis.dataflow import (
    ParamSink,
    map_call_args,
    param_sink_fixpoint,
    reverse_reach,
)
from volsync_tpu.analysis.engine import Finding
from volsync_tpu.analysis.rules import TracerSafetyRule, _const_str

_LOCK_CTORS = {"make_lock", "make_rlock"}


def _in_scope(mod: ModuleInfo, parts: tuple[str, ...]) -> bool:
    return any(p in mod.ctx.scope_dirs() for p in parts)


def _dotted_for(mod: ModuleInfo, chain: list[str]) -> Optional[str]:
    """Expand the leading alias of an attribute chain, e.g. with
    ``import time as t``, ["t", "sleep"] -> "time.sleep"."""
    if chain and chain[0] in mod.aliases:
        return ".".join([mod.aliases[chain[0]]] + chain[1:])
    return None


class _ScopeMaps:
    """Parent / enclosing-function / enclosing-class maps for one
    module — shared plumbing for VL101/VL102/VL103."""

    def __init__(self, mod: ModuleInfo):
        self.parent: dict[int, ast.AST] = {}
        self.encl_fn: dict[int, Optional[ast.AST]] = {}
        self.encl_cls: dict[int, Optional[str]] = {}

        def walk(node: ast.AST, fn: Optional[ast.AST],
                 cq: Optional[str], prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node
                self.encl_fn[id(child)] = fn
                self.encl_cls[id(child)] = cq
                nfn, ncq, nprefix = fn, cq, prefix
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nfn = child
                    nprefix = f"{prefix}.{child.name}"
                elif isinstance(child, ast.ClassDef):
                    ncq = f"{prefix}.{child.name}"
                    nprefix = ncq
                walk(child, nfn, ncq, nprefix)

        walk(mod.ctx.tree, None, None, mod.name)

    def stmt_of(self, node: ast.AST) -> Optional[ast.stmt]:
        while node is not None and not isinstance(node, ast.stmt):
            node = self.parent.get(id(node))
        return node

    def block_of(self, stmt: ast.stmt) -> Optional[list[ast.stmt]]:
        p = self.parent.get(id(stmt))
        if p is None:
            return None
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(p, attr, None)
            if isinstance(sub, list) and stmt in sub:
                return sub
        return None

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parent.get(id(cur))


def _walk_skip_defs(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root``'s body without descending into nested def/class/
    lambda bodies (they execute later, on their own call sites)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _lock_bindings(
        mod: ModuleInfo) -> tuple[dict[str, str], dict[str, dict[str, str]]]:
    """(module_locks {var: lockname}, class_locks {class_qual: {attr:
    lockname}}) for locks built via lockcheck.make_lock/make_rlock."""
    module_locks: dict[str, str] = {}
    class_locks: dict[str, dict[str, str]] = {}

    def lock_name(call: ast.Call) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain or chain[-1] not in _LOCK_CTORS:
            return None
        name = _const_str(call.args[0]) if call.args else None
        return name or "<unnamed>"

    def walk(body: list[ast.stmt], cls_qual: Optional[str],
             prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk(node.body, f"{prefix}.{node.name}",
                     f"{prefix}.{node.name}")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(node.body, cls_qual, f"{prefix}.{node.name}")
            else:
                for sub in ast.walk(node):
                    if not (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    name = lock_name(sub.value)
                    if name is None:
                        continue
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            module_locks[t.id] = name
                        elif (isinstance(t, ast.Attribute)
                              and isinstance(t.value, ast.Name)
                              and t.value.id == "self" and cls_qual):
                            class_locks.setdefault(
                                cls_qual, {})[t.attr] = name
                walk([s for s in ast.iter_child_nodes(node)
                      if isinstance(s, ast.stmt)], cls_qual, prefix)

    walk(mod.ctx.tree.body, None, mod.name)
    return module_locks, class_locks


class LockRegionRule:
    """VL101 — no blocking I/O while holding a lockcheck-built lock."""

    code = "VL101"
    name = "blocking-call-under-lock"
    severity = "error"
    description = ("store I/O, socket/HTTP, or time.sleep reachable "
                   "(directly or through calls) inside a lock region in "
                   "repo/engine/objstore")

    SCOPE_PARTS = ("repo", "engine", "objstore")
    STORE_METHODS = {"put", "put_if_absent", "get", "get_range",
                     "put_file", "get_file", "list", "delete", "exists",
                     "size"}
    NET_ATTRS = {"urlopen", "getresponse", "create_connection", "request",
                 "connect", "sendall", "recv", "accept"}

    # -- direct sink classification ----------------------------------------

    def _direct_sink(self, call: ast.Call,
                     mod: ModuleInfo) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if _dotted_for(mod, chain) == "time.sleep":
            return "time.sleep()"
        attr = chain[-1]
        if len(chain) >= 2:
            recv = chain[-2]
            if attr in self.STORE_METHODS and recv.lower().endswith("store"):
                return f"{recv}.{attr}() object-store I/O"
            if attr in self.NET_ATTRS:
                return f".{attr}() network I/O"
        elif chain[0] == "urlopen":
            return "urlopen() network I/O"
        return None

    def _blocking_seeds(self, index: ProjectIndex) -> dict[str, str]:
        seeds: dict[str, str] = {}
        for qual in sorted(index.functions):
            fi = index.functions[qual]
            mod = index.modules.get(fi.module)
            if mod is None:
                continue
            for node in _walk_skip_defs(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                desc = self._direct_sink(node, mod)
                if desc is not None:
                    seeds[qual] = f"{desc} at {fi.relpath}:{node.lineno}"
                    break
        return seeds

    # -- region discovery ---------------------------------------------------

    def _region_lock_name(self, expr: ast.AST, mod: ModuleInfo,
                          cls_qual: Optional[str], index: ProjectIndex,
                          module_locks: dict[str, str],
                          class_locks: dict[str, dict[str, str]],
                          ) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return module_locks.get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls_qual):
            seen: set[str] = set()
            q: Optional[str] = cls_qual
            while q and q not in seen:
                seen.add(q)
                name = class_locks.get(q, {}).get(expr.attr)
                if name:
                    return name
                ci = index.classes.get(q)
                q = ci.bases[0] if ci and ci.bases else None
        return None

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        bindings = {relpath: _lock_bindings(mod)
                    for relpath, mod in index.by_relpath.items()}
        seeds = self._blocking_seeds(index)
        reach = reverse_reach(index, seeds)
        for relpath in sorted(index.by_relpath):
            mod = index.by_relpath[relpath]
            if not _in_scope(mod, self.SCOPE_PARTS):
                continue
            yield from self._check_module(index, mod, bindings[relpath],
                                          reach)

    def _check_module(self, index: ProjectIndex, mod: ModuleInfo,
                      bindings, reach) -> Iterator[Finding]:
        module_locks, class_locks = bindings
        maps = _ScopeMaps(mod)

        regions: list[tuple[int, str, list[ast.stmt]]] = []
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cq = maps.encl_cls.get(id(node))
                for item in node.items:
                    lock = self._region_lock_name(
                        item.context_expr, mod, cq, index, module_locks,
                        class_locks)
                    if lock:
                        regions.append((node.lineno, lock, node.body))
            elif isinstance(node, ast.Expr):
                # bare ``X.acquire()`` statement: region runs to the
                # matching ``X.release()`` in the same block
                call = node.value
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "acquire"):
                    continue
                base = attr_chain(call.func.value)
                if base is None:
                    continue
                lock = None
                if len(base) == 1:
                    lock = module_locks.get(base[0])
                elif base[0] == "self" and len(base) == 2:
                    cq = maps.encl_cls.get(id(node))
                    if cq:
                        lock = class_locks.get(cq, {}).get(base[1])
                if not lock:
                    continue
                block = maps.block_of(node)
                if block is None:
                    continue
                tail: list[ast.stmt] = []
                for stmt in block[block.index(node) + 1:]:
                    # the statement CONTAINING the release (usually a
                    # try/finally) still runs under the lock up to that
                    # point — it belongs to the region
                    tail.append(stmt)
                    if any(isinstance(s, ast.Call)
                           and isinstance(s.func, ast.Attribute)
                           and s.func.attr == "release"
                           and attr_chain(s.func.value) == base
                           for s in ast.walk(stmt)):
                        break
                regions.append((node.lineno, lock, tail))

        for header_line, lock, body in regions:
            if _suppressed_on(mod, header_line, self.code):
                continue
            seen: set[tuple] = set()
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    desc = self._direct_sink(node, mod)
                    if desc is not None:
                        key = (node.lineno, "direct", desc)
                        if key in seen:
                            continue
                        seen.add(key)
                        yield Finding(
                            mod.relpath, node.lineno, self.code,
                            f"{desc} while holding lock '{lock}' "
                            f"(region at line {header_line}) — move the "
                            f"blocking call out of the lock scope",
                            severity=self.severity)
                        continue
                    site = index.site_by_node.get(id(node))
                    if site is None or site.callee is None:
                        continue
                    r = reach.get(site.callee)
                    if r is None:
                        continue
                    key = (node.lineno, "chain", site.callee)
                    if key in seen:
                        continue
                    seen.add(key)
                    hops = " -> ".join(
                        q.rsplit(".", 1)[-1] + "()" for q in r.path)
                    yield Finding(
                        mod.relpath, node.lineno, self.code,
                        f"call reaches blocking {r.desc} while holding "
                        f"lock '{lock}' (region at line {header_line}; "
                        f"via {hops})",
                        severity=self.severity)


def _suppressed_on(mod: ModuleInfo, lineno: int, code: str) -> bool:
    """Region suppression: on the ``with``-header line itself, or on a
    comment-only line directly above it (lock headers are often too
    crowded for an inline comment)."""
    from volsync_tpu.analysis.engine import _SUPPRESS_RE

    candidates = [mod.ctx.line_text(lineno)]
    above = mod.ctx.line_text(lineno - 1).strip()
    if above.startswith("#"):
        candidates.append(above)
    for text in candidates:
        m = _SUPPRESS_RE.search(text)
        if m:
            codes = m.group(1)
            if codes is None or code in {c.strip()
                                         for c in codes.split(",")}:
                return True
    return False


class ThreadLifecycleRule:
    """VL102 — threads are named, non-daemon threads are joined,
    executors are shut down (or ownership is clearly transferred)."""

    code = "VL102"
    name = "thread-lifecycle"
    severity = "warning"
    description = ("Thread() without name=, non-daemon thread without a "
                   "reachable join, executor without a reachable "
                   "shutdown")

    _EXECUTORS = ("concurrent.futures.ThreadPoolExecutor",
                  "concurrent.futures.ProcessPoolExecutor",
                  "concurrent.futures.thread.ThreadPoolExecutor",
                  "concurrent.futures.process.ProcessPoolExecutor")

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        for relpath in sorted(index.by_relpath):
            mod = index.by_relpath[relpath]
            yield from self._check_module(index, mod)

    @staticmethod
    def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _binding_of(self, call: ast.Call, maps: _ScopeMaps):
        """('local'|'attr'|'none', name) — where the object lands."""
        p = maps.parent.get(id(call))
        if isinstance(p, ast.Assign) and len(p.targets) == 1:
            t = p.targets[0]
            if isinstance(t, ast.Name):
                return "local", t.id
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                return "attr", t.attr
        return "none", ""

    @staticmethod
    def _search_scope(kind: str, name: str, call: ast.Call,
                      maps: _ScopeMaps, mod: ModuleInfo,
                      index: ProjectIndex) -> Optional[ast.AST]:
        """The AST region in which a join/shutdown on the binding would
        count as reachable: the enclosing function for locals (module
        when declared global), the class body for self attributes, the
        whole module otherwise."""
        if kind == "local":
            fn = maps.encl_fn.get(id(call))
            if fn is None:
                return mod.ctx.tree
            for node in ast.walk(fn):
                if isinstance(node, ast.Global) and name in node.names:
                    return mod.ctx.tree
            return fn
        if kind == "attr":
            cq = maps.encl_cls.get(id(call))
            ci = index.classes.get(cq) if cq else None
            return ci.node if ci else mod.ctx.tree
        return None

    @staticmethod
    def _calls_method(scope: ast.AST, kind: str, name: str,
                      method: str) -> bool:
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == method):
                continue
            v = node.func.value
            if kind == "local" and isinstance(v, ast.Name) and v.id == name:
                return True
            if (kind == "attr" and isinstance(v, ast.Attribute)
                    and v.attr == name and isinstance(v.value, ast.Name)
                    and v.value.id == "self"):
                return True
        return False

    @staticmethod
    def _used_in_with(scope: ast.AST, kind: str, name: str) -> bool:
        for node in ast.walk(scope):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                e = item.context_expr
                if (kind == "local" and isinstance(e, ast.Name)
                        and e.id == name):
                    return True
                if (kind == "attr" and isinstance(e, ast.Attribute)
                        and e.attr == name):
                    return True
        return False

    def _check_module(self, index: ProjectIndex,
                      mod: ModuleInfo) -> Iterator[Finding]:
        maps = _ScopeMaps(mod)
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            dotted = _dotted_for(mod, chain) or ""
            if dotted == "threading.Thread":
                yield from self._check_thread(node, mod, maps, index)
            elif dotted in self._EXECUTORS:
                yield from self._check_executor(node, mod, maps, index)

    def _check_thread(self, call: ast.Call, mod: ModuleInfo,
                      maps: _ScopeMaps,
                      index: ProjectIndex) -> Iterator[Finding]:
        if self._kw(call, "name") is None:
            yield Finding(
                mod.relpath, call.lineno, self.code,
                "Thread() without name= — anonymous threads make "
                "stack dumps and the lock-order detector unreadable",
                severity=self.severity)
        daemon = self._kw(call, "daemon")
        if (isinstance(daemon, ast.Constant) and daemon.value is True):
            return  # daemon threads may outlive scope by design
        kind, name = self._binding_of(call, maps)
        scope = self._search_scope(kind, name, call, maps, mod, index)
        if scope is not None and self._calls_method(scope, kind, name,
                                                    "join"):
            return
        yield Finding(
            mod.relpath, call.lineno, self.code,
            "non-daemon thread with no reachable .join() — leaks at "
            "shutdown; join it, make it a daemon, or suppress with a "
            "reason", severity=self.severity)

    def _check_executor(self, call: ast.Call, mod: ModuleInfo,
                        maps: _ScopeMaps,
                        index: ProjectIndex) -> Iterator[Finding]:
        p = maps.parent.get(id(call))
        if isinstance(p, ast.withitem):
            return  # with ThreadPoolExecutor(...) as pool
        if isinstance(p, ast.Call) and call in p.args:
            return  # ownership transferred (e.g. grpc.server(pool))
        kind, name = self._binding_of(call, maps)
        scope = self._search_scope(kind, name, call, maps, mod, index)
        if scope is not None:
            if self._calls_method(scope, kind, name, "shutdown"):
                return
            if self._used_in_with(scope, kind, name):
                return
        yield Finding(
            mod.relpath, call.lineno, self.code,
            "executor with no reachable .shutdown() — worker threads "
            "leak; use a with-statement or shut it down explicitly",
            severity=self.severity)


class ResourceLeakRule:
    """VL103 — acquire/open outside with/try-finally leaks the resource
    on any exception raised before the release/close."""

    code = "VL103"
    name = "exception-path-leak"
    severity = "warning"
    description = (".acquire() or open() outside a with-statement or "
                   "try-finally in the data-plane modules")

    SCOPE_PARTS = ("repo", "objstore", "engine", "obs", "io", "ops")

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        for relpath in sorted(index.by_relpath):
            mod = index.by_relpath[relpath]
            if not _in_scope(mod, self.SCOPE_PARTS):
                continue
            yield from self._check_module(mod)

    @staticmethod
    def _releases(node: ast.AST, base: list[str], method: str) -> bool:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == method
                    and attr_chain(sub.func.value) == base):
                return True
        return False

    def _protected(self, stmt: ast.stmt, maps: _ScopeMaps,
                   base: list[str], method: str) -> bool:
        """True when a release/close for ``base`` is structurally tied
        to the acquire: in the finally (or a re-raising except) of an
        ancestor try, or of the try that immediately follows."""
        def try_covers(t: ast.Try) -> bool:
            if any(self._releases(s, base, method) for s in t.finalbody):
                return True
            for h in t.handlers:
                body = ast.Module(body=h.body, type_ignores=[])
                if (any(self._releases(s, base, method) for s in h.body)
                        and any(isinstance(x, ast.Raise)
                                for x in ast.walk(body))):
                    return True
            return False

        for anc in maps.ancestors(stmt):
            if isinstance(anc, ast.Try) and try_covers(anc):
                return True
        block = maps.block_of(stmt)
        if block is not None:
            i = block.index(stmt)
            if i + 1 < len(block) and isinstance(block[i + 1], ast.Try):
                if try_covers(block[i + 1]):
                    return True
        return False

    def _check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        maps = _ScopeMaps(mod)
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            p = maps.parent.get(id(node))
            # .acquire() as a bare statement or assigned result
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                    and isinstance(p, (ast.Expr, ast.Assign))):
                base = attr_chain(node.func.value)
                stmt = maps.stmt_of(node)
                if base is None or stmt is None:
                    continue
                if not self._protected(stmt, maps, base, "release"):
                    yield Finding(
                        mod.relpath, node.lineno, self.code,
                        f"{'.'.join(base)}.acquire() outside "
                        f"with/try-finally — an exception before the "
                        f"release leaks the lock/slot",
                        severity=self.severity)
            # open() assigned to a name
            elif (isinstance(node.func, ast.Name)
                  and node.func.id == "open"
                  and isinstance(p, ast.Assign) and len(p.targets) == 1
                  and isinstance(p.targets[0], ast.Name)):
                base = [p.targets[0].id]
                stmt = maps.stmt_of(node)
                if stmt is None:
                    continue
                if not self._protected(stmt, maps, base, "close"):
                    yield Finding(
                        mod.relpath, node.lineno, self.code,
                        f"open() bound to {base[0]!r} outside "
                        f"with/try-finally — the handle leaks on an "
                        f"exception path",
                        severity=self.severity)


class TracerTaintRule:
    """VL104 — tracer taint followed through resolved helper calls."""

    code = "VL104"
    name = "interprocedural-tracer-taint"
    severity = "error"
    description = ("traced value from a jit'd ops/ kernel flows through "
                   "helper calls into Python control flow or an "
                   "int()/float()/bool() sink")

    SCOPE_PARTS = ("ops",)

    # -- taint-use policy ---------------------------------------------------

    @classmethod
    def _uses(cls, node: ast.AST, names: set) -> set:
        """Which of ``names`` are used as VALUES in ``node``. Exempt:
        .shape/.dtype/.ndim metadata, ``is (not) None`` checks, and
        len() (static on arrays — it is shape[0])."""
        if (isinstance(node, ast.Attribute)
                and node.attr in ("shape", "dtype", "ndim")):
            return set()
        if (isinstance(node, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops)):
            return set()
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "len"):
            return set()
        if isinstance(node, ast.Name):
            return {node.id} & names
        out: set = set()
        for child in ast.iter_child_nodes(node):
            out |= cls._uses(child, names)
        return out

    # -- per-function direct sinks -----------------------------------------

    def _direct_param_sinks(self, fi) -> dict[str, ParamSink]:
        params = {p for p in fi.params + fi.kwonly
                  if p not in ("self", "cls")}
        if not params:
            return {}
        out: dict[str, ParamSink] = {}

        def add(names: set, desc: str, lineno: int) -> None:
            for pname in sorted(names):
                out.setdefault(pname, ParamSink(
                    desc, fi.relpath, lineno, (fi.qualname,)))

        for node in _walk_skip_defs(fi.node):
            if isinstance(node, (ast.If, ast.While)):
                add(self._uses(node.test, params),
                    f"branches on it ({fi.relpath}:{node.lineno})",
                    node.lineno)
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Name)
                        and f.id in ("float", "int", "bool")
                        and len(node.args) == 1):
                    add(self._uses(node.args[0], params),
                        f"concretizes it with {f.id}() "
                        f"({fi.relpath}:{node.lineno})", node.lineno)
                elif (isinstance(f, ast.Attribute)
                      and f.attr in ("item", "tolist")):
                    add(self._uses(f.value, params),
                        f"host-transfers it with .{f.attr}() "
                        f"({fi.relpath}:{node.lineno})", node.lineno)
        return out

    # -- driver -------------------------------------------------------------

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        jit_statics: dict[str, Optional[set]] = {}
        for qual, fi in index.functions.items():
            if isinstance(fi.node, ast.FunctionDef):
                jit_statics[qual] = TracerSafetyRule._jit_static_names(
                    fi.node)
            else:
                jit_statics[qual] = None

        direct: dict[str, dict[str, ParamSink]] = {}
        for qual in sorted(index.functions):
            if jit_statics.get(qual) is not None:
                continue  # jit'd bodies are VL004's jurisdiction
            d = self._direct_param_sinks(index.functions[qual])
            if d:
                direct[qual] = d

        sinks = param_sink_fixpoint(
            index, direct, self._uses,
            skip=lambda q: jit_statics.get(q) is not None)

        for qual in sorted(index.functions):
            statics = jit_statics.get(qual)
            if statics is None:
                continue
            fi = index.functions[qual]
            mod = index.modules.get(fi.module)
            if mod is None or not _in_scope(mod, self.SCOPE_PARTS):
                continue
            yield from self._check_jit_fn(index, mod, fi, statics, sinks,
                                          jit_statics)

    def _check_jit_fn(self, index: ProjectIndex, mod: ModuleInfo, fi,
                      statics: set, sinks, jit_statics
                      ) -> Iterator[Finding]:
        traced = {p for p in fi.params + fi.kwonly
                  if p not in statics and p not in ("self", "cls")}
        if not traced:
            return

        # forward pass: locals derived from traced values are tainted
        tainted = set(traced)

        def scan_stmts(body: list[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Assign):
                    if self._uses(stmt.value, tainted):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)
                            elif isinstance(t, ast.Tuple):
                                tainted.update(
                                    e.id for e in t.elts
                                    if isinstance(e, ast.Name))
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    if (stmt.value is not None
                            and self._uses(stmt.value, tainted)
                            and isinstance(stmt.target, ast.Name)):
                        tainted.add(stmt.target.id)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list):
                        scan_stmts(sub)
                for handler in getattr(stmt, "handlers", []) or []:
                    scan_stmts(handler.body)

        scan_stmts(fi.node.body)
        derived = tainted - traced

        # (a) tainted arguments into helpers whose params reach a sink
        reported: set[tuple] = set()
        for site in index.calls.get(fi.qualname, ()):
            if site.callee is None:
                continue  # unresolved: VL004's in-function fallback
            if jit_statics.get(site.callee) is not None:
                continue
            callee_sinks = sinks.get(site.callee)
            if not callee_sinks:
                continue
            for pname, arg in map_call_args(site, index):
                ps = callee_sinks.get(pname)
                if ps is None or not self._uses(arg, tainted):
                    continue
                key = (site.lineno, site.callee)
                if key in reported:
                    continue
                reported.add(key)
                short = site.callee.rsplit(".", 1)[-1]
                via = ""
                if len(ps.chain) > 1:
                    via = (" via " + " -> ".join(
                        q.rsplit(".", 1)[-1] + "()" for q in ps.chain))
                yield Finding(
                    mod.relpath, site.lineno, self.code,
                    f"traced value passed to {short}(... {pname}=) "
                    f"inside jit'd {fi.node.name}() — it {ps.desc}"
                    f"{via}; hoist the host logic out of the kernel or "
                    f"mark the argument static",
                    severity=self.severity)
                break

        # (b) Python control flow / concretization on DERIVED taint
        # (direct traced-param uses are VL004's findings — no dupes)
        if not derived:
            return
        for node in _walk_skip_defs(fi.node):
            if isinstance(node, (ast.If, ast.While)):
                used = self._uses(node.test, derived)
                if used and not self._uses(node.test, traced):
                    yield Finding(
                        mod.relpath, node.lineno, self.code,
                        f"Python branch on tracer-derived value(s) "
                        f"{sorted(used)} inside jit'd {fi.node.name}() "
                        f"— use lax.cond/lax.select",
                        severity=self.severity)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "int", "bool")
                  and len(node.args) == 1):
                used = self._uses(node.args[0], derived)
                if used and not self._uses(node.args[0], traced):
                    yield Finding(
                        mod.relpath, node.lineno, self.code,
                        f"{node.func.id}() on tracer-derived value(s) "
                        f"{sorted(used)} inside jit'd {fi.node.name}() "
                        f"— forces a host sync or fails at trace time",
                        severity=self.severity)


def default_project_rules() -> list:
    from volsync_tpu.analysis.guards import (
        CheckThenActRule,
        GuardedFieldRule,
        UnsyncPublicationRule,
    )
    from volsync_tpu.analysis.bufflow import default_buf_rules
    from volsync_tpu.analysis.faultflow import default_fx_rules
    from volsync_tpu.analysis.lockflow import LockOrderRule

    return [LockRegionRule(), ThreadLifecycleRule(), ResourceLeakRule(),
            TracerTaintRule(), LockOrderRule(), GuardedFieldRule(),
            CheckThenActRule(), UnsyncPublicationRule(),
            *default_buf_rules(), *default_fx_rules()]
