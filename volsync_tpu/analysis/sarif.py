"""SARIF 2.1.0 emission for `volsync lint` findings.

Minimal but valid static-analysis result interchange: one run, one
tool (`volsync-lint`), a rule catalogue with default severity levels,
and one result per finding with a physical location. Unparsable files
surface as tool-execution notifications so a syntax error cannot read
as "clean" in a SARIF-consuming CI gate either (the CLI still exits
nonzero on them).
"""

from __future__ import annotations

from typing import Iterable, Optional

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

# Finding severities map 1:1 onto SARIF levels.
_LEVELS = {"error": "error", "warning": "warning", "note": "note"}


def _rule_descriptor(rule) -> dict:
    return {
        "id": rule.code,
        "name": getattr(rule, "name", rule.code),
        "shortDescription": {"text": getattr(rule, "description",
                                             rule.code)},
        "defaultConfiguration": {
            "level": _LEVELS.get(getattr(rule, "severity", "warning"),
                                 "warning"),
        },
    }


def _region(f) -> dict:
    """Full-span region when the finding carries one (column/end data
    is 1-based, 0 meaning unknown); point location otherwise, so
    editor integrations highlight the whole offending expression."""
    region = {"startLine": f.line}
    col = getattr(f, "col", 0)
    end_line = getattr(f, "end_line", 0)
    end_col = getattr(f, "end_col", 0)
    if col:
        region["startColumn"] = col
    if end_line:
        region["endLine"] = end_line
        # SARIF endColumn is exclusive; ours is the 1-based column just
        # past the node, which matches ast's end_col_offset + 1
        if end_col:
            region["endColumn"] = end_col
    return region


def to_sarif(findings: Iterable, errors: Iterable[str],
             rules: Optional[list] = None) -> dict:
    rules = rules or []
    rule_ids = [r.code for r in rules]
    results = []
    for f in findings:
        res = {
            "ruleId": f.code,
            "level": _LEVELS.get(getattr(f, "severity", "warning"),
                                 "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": _region(f),
                },
            }],
        }
        if f.code in rule_ids:
            res["ruleIndex"] = rule_ids.index(f.code)
        results.append(res)
    notifications = [
        {"level": "error", "message": {"text": err}} for err in errors]
    run = {
        "tool": {
            "driver": {
                "name": "volsync-lint",
                "informationUri":
                    "https://github.com/RobotSail/volsync",
                "rules": [_rule_descriptor(r) for r in rules],
            },
        },
        "results": results,
    }
    if notifications:
        run["invocations"] = [{
            "executionSuccessful": False,
            "toolExecutionNotifications": notifications,
        }]
    else:
        run["invocations"] = [{"executionSuccessful": True}]
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [run],
    }
