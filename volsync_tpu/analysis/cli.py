"""Command-line front end: ``python -m volsync_tpu.analysis`` and the
``volsync lint`` subcommand both land here.

Exit codes: 0 clean (stale baseline entries only warn), 1 new findings
or unparsable files, 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path
from typing import Optional

from volsync_tpu.analysis.engine import (
    apply_baseline,
    load_baseline,
    run_project,
    write_baseline,
)

DEFAULT_BASELINE = ".volsync-lint-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="volsync lint",
        description="Repo-invariant AST lint for volsync-tpu "
                    "(per-file rules VL001-VL005, VL105 and VL301, "
                    "interprocedural rules VL101-VL104, shape/dtype "
                    "rules VL201-VL205, static concurrency rules "
                    "VL401-VL404, buffer-provenance rules "
                    "VL501-VL505, fault-path rules VL601-VL605; "
                    "see docs/development.md)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the installed "
             "volsync_tpu package)")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help=f"baseline file of grandfathered findings "
             f"(default: ./{DEFAULT_BASELINE} when present)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file — report everything")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule codes/descriptions and exit")
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format for findings (default: text)")
    parser.add_argument(
        "--out", default=None, metavar="FILE",
        help="write json/sarif output to FILE instead of stdout")
    parser.add_argument(
        "--cache", default=None, metavar="FILE",
        help="incremental cache file: re-analyze only changed files "
             "and their reverse import dependencies")
    parser.add_argument(
        "--select", default=None, metavar="PREFIXES",
        help="comma-separated rule-code prefixes to run, e.g. "
             "'VL2' or 'VL001,VL10' — everything else is skipped "
             "(CI can stage a new rule family this way)")
    parser.add_argument(
        "--ignore", default=None, metavar="PREFIXES",
        help="comma-separated rule-code prefixes to skip; applied "
             "after --select")
    parser.add_argument(
        "--dump-lock-graph", default=None, metavar="FILE",
        help="also write the static lock-acquisition-order graph "
             "(VL401's evidence: nodes=lock names, edges with hop "
             "chains) to FILE as JSON, '-' for stdout")
    parser.add_argument(
        "--dump-provenance", default=None, metavar="FILE",
        help="also write the buffer-provenance graph (VL5xx "
             "evidence: sanctioned sites, per-function provenance "
             "nodes, interprocedural hop edges) to FILE as JSON, "
             "'-' for stdout")
    parser.add_argument(
        "--dump-effects", default=None, metavar="FILE",
        help="also write the fault-path effect graph (VL6xx "
             "evidence: resolved laws, per-function effect/raise "
             "summaries, retry-policy call edges) to FILE as JSON, "
             "'-' for stdout")
    parser.add_argument(
        "--stats", action="store_true",
        help="print per-rule-family finding and suppression-pragma "
             "counts as JSON instead of findings (CI asserts the "
             "committed suppression budget against this)")
    return parser


def _all_rules():
    from volsync_tpu.analysis.iprules import default_project_rules
    from volsync_tpu.analysis.rules import default_rules
    from volsync_tpu.analysis.shapes import default_shape_rules

    return default_rules(), default_project_rules() + default_shape_rules()


def _split_prefixes(raw: Optional[str]) -> Optional[list]:
    if raw is None:
        return None
    return [p.strip().upper() for p in raw.split(",") if p.strip()]


def filter_rules(rules: list, select: Optional[list],
                 ignore: Optional[list]) -> list:
    """Keep rules whose code starts with a --select prefix (all, when
    unset) and doesn't start with an --ignore prefix."""
    out = []
    for rule in rules:
        code = rule.code
        if select is not None and not any(code.startswith(p)
                                          for p in select):
            continue
        if ignore is not None and any(code.startswith(p)
                                      for p in ignore):
            continue
        out.append(rule)
    return out


def _family(code: str) -> str:
    """'VL601' -> 'VL6xx': the rule-family key used by --stats."""
    return code[:3] + "xx" if len(code) >= 3 else code


def lint_stats(paths: list, new: list, errors: list) -> dict:
    """Per-family counts of (post-baseline) findings and of
    ``# lint: ignore`` suppression pragmas across the linted files.
    The suppression counts are what static_check.sh asserts the
    committed budget against — a pragma with explicit codes is billed
    to each code's family, a bare ``# lint: ignore`` under "any"."""
    from volsync_tpu.analysis.engine import _SUPPRESS_RE, iter_py_files

    findings_by: dict = {}
    for f in new:
        fam = _family(f.code)
        findings_by[fam] = findings_by.get(fam, 0) + 1
    supp_by: dict = {}
    n_supp = 0
    for path in iter_py_files(paths):
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            continue
        for line in text.splitlines():
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            n_supp += 1
            codes = m.group(1)
            fams = ({"any"} if codes is None else
                    {_family(c.strip()) for c in codes.split(",")
                     if c.strip()})
            for fam in sorted(fams):
                supp_by[fam] = supp_by.get(fam, 0) + 1
    return {
        "findings": findings_by,
        "suppressions": supp_by,
        "total_findings": len(new),
        "total_suppressions": n_supp,
        "errors": len(errors),
    }


def main(argv: Optional[list] = None, out=print) -> int:
    args = build_parser().parse_args(argv)
    rules, project_rules = _all_rules()
    select = _split_prefixes(args.select)
    ignore = _split_prefixes(args.ignore)
    if select is not None or ignore is not None:
        rules = filter_rules(rules, select, ignore)
        project_rules = filter_rules(project_rules, select, ignore)
    if args.list_rules:
        for rule in rules + project_rules:
            out(f"{rule.code}  {rule.name}: {rule.description}")
        return 0

    paths = args.paths
    if not paths:
        paths = [str(Path(__file__).resolve().parent.parent)]

    result = run_project(paths, rules=rules, project_rules=project_rules,
                         cache_path=Path(args.cache) if args.cache
                         else None)
    findings, errors = result.findings, result.errors

    if args.dump_lock_graph:
        from volsync_tpu.analysis.lockflow import dump_for_paths

        graph = dump_for_paths(paths)
        text = json.dumps(graph, indent=2, sort_keys=True)
        if args.dump_lock_graph == "-":
            out(text)
        else:
            Path(args.dump_lock_graph).write_text(text + "\n",
                                                  encoding="utf-8")
            out(f"wrote lock graph to {args.dump_lock_graph} "
                f"({len(graph['edges'])} edge(s))")

    if args.dump_provenance:
        from volsync_tpu.analysis.bufflow import (
            dump_for_paths as dump_provenance,
        )

        prov = dump_provenance(paths)
        text = json.dumps(prov, indent=2, sort_keys=True)
        if args.dump_provenance == "-":
            out(text)
        else:
            Path(args.dump_provenance).write_text(text + "\n",
                                                  encoding="utf-8")
            out(f"wrote provenance graph to {args.dump_provenance} "
                f"({len(prov['edges'])} edge(s))")

    if args.dump_effects:
        from volsync_tpu.analysis.faultflow import (
            dump_for_paths as dump_effects,
        )

        fx = dump_effects(paths)
        text = json.dumps(fx, indent=2, sort_keys=True)
        if args.dump_effects == "-":
            out(text)
        else:
            Path(args.dump_effects).write_text(text + "\n",
                                               encoding="utf-8")
            out(f"wrote effect graph to {args.dump_effects} "
                f"({len(fx['edges'])} edge(s))")

    baseline_path = Path(args.baseline) if args.baseline else Path(
        DEFAULT_BASELINE)
    if args.write_baseline:
        for e in errors:
            out(f"error: {e}")
        write_baseline(findings, baseline_path)
        out(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)

    if args.stats:
        out(json.dumps(lint_stats(paths, new, errors), indent=2,
                       sort_keys=True))
        return 1 if (new or errors) else 0

    if args.format in ("json", "sarif"):
        if args.format == "sarif":
            from volsync_tpu.analysis.sarif import to_sarif

            payload = to_sarif(new, errors, rules + project_rules)
        else:
            payload = {
                "findings": [
                    {"path": f.path, "line": f.line, "code": f.code,
                     "message": f.message, "severity": f.severity}
                    for f in new],
                "errors": list(errors),
                "analyzed": result.analyzed,
                "total": result.total,
            }
        text = json.dumps(payload, indent=2, sort_keys=True)
        if args.out:
            Path(args.out).write_text(text + "\n", encoding="utf-8")
            out(f"wrote {args.format} report to {args.out} "
                f"({len(new)} finding(s))")
        else:
            out(text)
        if args.cache:
            out(f"cache: analyzed {len(result.analyzed)} of "
                f"{result.total} file(s)")
        return 1 if (new or errors) else 0

    for e in errors:
        out(f"error: {e}")
    for f in new:
        out(f.render())
    for k in stale:
        out(f"stale baseline entry (fixed? regenerate with "
            f"--write-baseline): {k}")
    if args.cache:
        out(f"cache: analyzed {len(result.analyzed)} of "
            f"{result.total} file(s)")
    if new or errors:
        out(f"{len(new)} new finding(s), {suppressed} baselined, "
            f"{len(errors)} file error(s)")
        return 1
    if suppressed or stale:
        out(f"clean: 0 new finding(s), {suppressed} baselined, "
            f"{len(stale)} stale baseline entr(y/ies)")
    return 0
