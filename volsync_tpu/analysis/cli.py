"""Command-line front end: ``python -m volsync_tpu.analysis`` and the
``volsync lint`` subcommand both land here.

Exit codes: 0 clean (stale baseline entries only warn), 1 new findings
or unparsable files, 2 usage errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional

from volsync_tpu.analysis.engine import (
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

DEFAULT_BASELINE = ".volsync-lint-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="volsync lint",
        description="Repo-invariant AST lint for volsync-tpu "
                    "(rules VL001-VL005; see docs/development.md)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the installed "
             "volsync_tpu package)")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help=f"baseline file of grandfathered findings "
             f"(default: ./{DEFAULT_BASELINE} when present)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file — report everything")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule codes/descriptions and exit")
    return parser


def main(argv: Optional[list] = None, out=print) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        from volsync_tpu.analysis.rules import default_rules

        for rule in default_rules():
            out(f"{rule.code}  {rule.name}: {rule.description}")
        return 0

    paths = args.paths
    if not paths:
        paths = [str(Path(__file__).resolve().parent.parent)]

    findings, errors = run_lint(paths)
    for e in errors:
        out(f"error: {e}")

    baseline_path = Path(args.baseline) if args.baseline else Path(
        DEFAULT_BASELINE)
    if args.write_baseline:
        write_baseline(findings, baseline_path)
        out(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)
    for f in new:
        out(f.render())
    for k in stale:
        out(f"stale baseline entry (fixed? regenerate with "
            f"--write-baseline): {k}")
    if new or errors:
        out(f"{len(new)} new finding(s), {suppressed} baselined, "
            f"{len(errors)} file error(s)")
        return 1
    if suppressed or stale:
        out(f"clean: 0 new finding(s), {suppressed} baselined, "
            f"{len(stale)} stale baseline entr(y/ies)")
    return 0
