"""Abstract domain for the shape/dtype interpreter (analysis/shapes.py).

The lattice is deliberately three-valued everywhere: a property is
either *known* (a concrete Python value), *symbolic* (a structural
token derived from an unknown quantity, so two occurrences of the same
expression compare equal), or *Unknown* (``None`` — no information).
Every rule built on top of this domain only fires on the *known*
tier: an Unknown or merely-symbolic disagreement can suppress a
finding but can never create one — the same false-negatives-only
bargain the per-file rules and the call-graph resolver make.

Dims
----
A dimension is ``int`` (concrete), a structural tuple like
``("add", ("sym", 3), 1)`` (symbolic — interned by construction so
``n + 1`` from two sites compares equal), or ``None`` (unknown).

Dtypes
------
Dtypes are canonical strings (``"uint32"``, ``"float32"``, ``"bool"``)
plus a *weak* flag mirroring JAX's weak-type promotion: a Python
scalar literal is weakly typed and adapts to the other operand's
dtype instead of promoting it — ``uint32_arr + 2`` stays ``uint32``,
while ``uint32_arr + int32_arr`` crosses the signedness boundary.
``promote`` follows the JAX lattice *before* 32-bit canonicalization
(``uint32 + int32 -> int64``): for lint purposes what matters is that
the result left ``uint32``, not which wider type it landed on.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

# -- dimensions -------------------------------------------------------------

# Dim = int | structural tuple | None (unknown)


def is_conc(d) -> bool:
    """Concrete dimension (a real int; bool is a Python int subtype
    and must not slip through)."""
    return isinstance(d, int) and not isinstance(d, bool)


def sym(token) -> tuple:
    """Opaque symbolic dim from a hashable token (the interpreter
    uses per-run counters / qualnames, so runs stay deterministic)."""
    return ("sym", token)


def dim_binop(op: str, a, b):
    """Structural arithmetic on dims. Concrete operands fold; anything
    touching Unknown stays Unknown; otherwise the expression tree is
    the value, so equal expressions compare equal."""
    if a is None or b is None:
        return None
    if is_conc(a) and is_conc(b):
        try:
            if op == "add":
                return a + b
            if op == "sub":
                return a - b
            if op == "mul":
                return a * b
            if op == "floordiv":
                return a // b
            if op == "mod":
                return a % b
        except (ZeroDivisionError, OverflowError):
            return None
        return None
    # tiny normalizations keep common slice arithmetic comparable
    if op == "add" and b == 0:
        return a
    if op in ("add", "mul") and a == 0 and op == "add":
        return b
    if op == "sub" and b == 0:
        return a
    if op == "mul" and (a == 1 or b == 1):
        return b if a == 1 else a
    return (op, a, b)


def join_dim(a, b):
    return a if a == b else None


# -- dtypes -----------------------------------------------------------------

_CANON = {
    "bool_": "bool",
    "bool": "bool",
    "uint8": "uint8", "uint16": "uint16",
    "uint32": "uint32", "uint64": "uint64",
    "int8": "int8", "int16": "int16",
    "int32": "int32", "int64": "int64",
    "float16": "float16", "bfloat16": "bfloat16",
    "float32": "float32", "float64": "float64",
    "complex64": "complex64", "complex128": "complex128",
}

KIND_BOOL, KIND_UINT, KIND_INT, KIND_FLOAT, KIND_COMPLEX = range(5)


def canon_dtype(name: str) -> Optional[str]:
    """Canonical dtype string or None for anything exotic (``">u4"``
    byte-order strings and friends stay Unknown on purpose)."""
    return _CANON.get(name)


def kind(dtype: str) -> int:
    if dtype == "bool":
        return KIND_BOOL
    if dtype.startswith("uint"):
        return KIND_UINT
    if dtype.startswith("int"):
        return KIND_INT
    if dtype.startswith("float") or dtype == "bfloat16":
        return KIND_FLOAT
    return KIND_COMPLEX


def width(dtype: str) -> int:
    digits = "".join(c for c in dtype if c.isdigit())
    return int(digits) if digits else 8  # bool


def is_uint(dtype: Optional[str]) -> bool:
    return bool(dtype) and dtype.startswith("uint")


def promote(d1: Optional[str], w1: bool,
            d2: Optional[str], w2: bool) -> Tuple[Optional[str], bool]:
    """JAX-style binary result type. Unknown in -> Unknown out."""
    if d1 is None or d2 is None:
        return None, False
    if d1 == d2:
        return d1, w1 and w2
    k1, k2 = kind(d1), kind(d2)
    if w1 != w2:
        # exactly one weak operand: a Python scalar adapts to the
        # strong dtype unless it is a float meeting an integer
        weak_d, weak_k = (d1, k1) if w1 else (d2, k2)
        strong_d, strong_k = (d2, k2) if w1 else (d1, k1)
        if weak_k == KIND_FLOAT and strong_k < KIND_FLOAT:
            return "float32", False
        if weak_k == KIND_INT and strong_k <= KIND_INT:
            return strong_d, False  # weak int never promotes an int/uint
        if weak_k <= strong_k:
            return strong_d, False
        return None, False
    if w1 and w2:
        return (d1 if k1 >= k2 else d2), True
    # both strong
    if k1 == KIND_BOOL:
        return d2, False
    if k2 == KIND_BOOL:
        return d1, False
    if k1 == k2:
        if width(d1) == width(d2):  # float16 vs bfloat16
            return "float32", False
        return (d1 if width(d1) > width(d2) else d2), False
    if KIND_COMPLEX in (k1, k2):
        return "complex64", False
    if KIND_FLOAT in (k1, k2):
        return (d1 if k1 == KIND_FLOAT else d2), False
    # uint vs int: the signed side wins when strictly wider, else the
    # next-wider signed integer (uint64 vs int64 falls off to float64)
    ud, sd = (d1, d2) if k1 == KIND_UINT else (d2, d1)
    if width(sd) > width(ud):
        return sd, False
    nw = width(ud) * 2
    return (f"int{nw}" if nw <= 64 else "float64"), False


def join_dtype(d1: Optional[str], w1: bool,
               d2: Optional[str], w2: bool) -> Tuple[Optional[str], bool]:
    if d1 == d2:
        return d1, w1 and w2
    return None, False


# -- abstract arrays --------------------------------------------------------

@dataclass(frozen=True)
class AbsArray:
    """An array (or scalar: ``shape == ()``) in the abstract domain.

    ``shape`` is a tuple of dims or ``None`` for unknown rank;
    ``dtype`` a canonical string or ``None``; ``weak`` mirrors JAX's
    weak-type flag for Python scalar literals.
    """

    shape: Optional[tuple]
    dtype: Optional[str]
    weak: bool = False

    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)


UNKNOWN_ARRAY = AbsArray(None, None)


def shape_str(shape: Optional[tuple]) -> str:
    if shape is None:
        return "(?)"

    def one(d):
        if is_conc(d):
            return str(d)
        return "?" if d is None else "s"

    return "(" + ", ".join(one(d) for d in shape) + ("," if len(shape) == 1
                                                     else "") + ")"


def broadcast_shapes(a: Optional[tuple],
                     b: Optional[tuple]) -> Tuple[Optional[tuple],
                                                  Optional[tuple]]:
    """NumPy broadcasting, three-valued.

    Returns ``(result_shape, conflict)`` where ``conflict`` is
    ``(dim_a, dim_b, axis_from_right)`` only when two CONCRETE dims
    disagree and neither is 1 — the only case a rule may report.
    Symbolic or unknown dims broadcast silently to Unknown.
    """
    if a is None or b is None:
        return None, None
    out = []
    conflict = None
    for i in range(1, max(len(a), len(b)) + 1):
        da = a[-i] if i <= len(a) else 1
        db = b[-i] if i <= len(b) else 1
        if da == db:
            out.append(da)
        elif da == 1:
            out.append(db)
        elif db == 1:
            out.append(da)
        elif is_conc(da) and is_conc(db):
            conflict = (da, db, i - 1)
            out.append(None)
        else:
            out.append(None)  # symbolic vs anything: silent
    return tuple(reversed(out)), conflict


def numel(shape: Optional[tuple]):
    if shape is None:
        return None
    n = 1
    for d in shape:
        if not is_conc(d):
            return None
        n *= d
    return n


def join_shape(a: Optional[tuple], b: Optional[tuple]) -> Optional[tuple]:
    if a is None or b is None or len(a) != len(b):
        return None
    return tuple(join_dim(x, y) for x, y in zip(a, b))


def join_array(a: AbsArray, b: AbsArray) -> AbsArray:
    d, w = join_dtype(a.dtype, a.weak, b.dtype, b.weak)
    return AbsArray(join_shape(a.shape, b.shape), d, w)
