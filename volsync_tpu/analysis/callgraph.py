"""Project-wide module/symbol resolver and call-graph builder.

This is the substrate the interprocedural rules (analysis/iprules.py)
and the shape/dtype abstract interpreter (analysis/shapes.py) stand
on: it turns a set of parsed files (engine.FileContext) into a
``ProjectIndex`` — modules with their import-alias tables, every
function/method/nested-def with a stable qualname, class method tables
with (single-level) base resolution, and one ``CallSite`` per call
expression with the best-effort resolved callee qualname.

Resolution is deliberately conservative: a call we cannot attribute to
a project symbol resolves to ``None`` and simply contributes no edge.
The rules are written so that an unresolved edge can only cause a
false NEGATIVE, never a false positive — the same bargain the per-file
rules make.

What resolves:

* bare names: nested defs of the enclosing function chain, then
  module-level functions/classes, then imported symbols
  (``from x import y as z`` included, relative imports included);
* ``self.m()`` / ``cls.m()``: methods on the enclosing class, then on
  resolvable base classes (transitively, cycle-guarded);
* attribute chains through module aliases: ``import a.b as c; c.f()``
  and ``c.Klass.method`` / ``c.Klass()`` (constructor -> ``__init__``);
* local variables shadowing any of the above resolve to ``None``.

Callbacks passed as arguments (``pool.submit(fn, ...)``) are
intentionally NOT call edges: the callee runs on another thread, so
e.g. lock-region reachability must not follow it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from volsync_tpu.analysis.engine import FileContext


def module_name_for(path: Path) -> str:
    """Dotted module name, derived by walking up while the parent
    directory is a package (has ``__init__.py``). Works for installed
    trees and for tmp-dir test fixtures alike."""
    path = Path(path)
    parts = [] if path.stem == "__init__" else [path.stem]
    d = path.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        parent = d.parent
        if parent == d:
            break
        d = parent
    return ".".join(parts) or path.stem


@dataclass
class FunctionInfo:
    qualname: str
    module: str
    relpath: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: Optional[str]  # enclosing ClassInfo qualname (lexical)
    parent: Optional[str]  # enclosing function qualname (nested defs)
    params: list[str]  # positional (posonly + args), in order
    kwonly: list[str]
    nested: dict[str, str] = field(default_factory=dict)  # name -> qualname


@dataclass
class ClassInfo:
    qualname: str
    module: str
    node: ast.ClassDef
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    base_exprs: list[ast.expr] = field(default_factory=list)
    bases: list[str] = field(default_factory=list)  # resolved qualnames


@dataclass
class CallSite:
    caller: str  # qualname of the enclosing function (or module)
    relpath: str
    lineno: int
    node: ast.Call
    callee: Optional[str]  # resolved qualname, or None


class ModuleInfo:
    def __init__(self, name: str, ctx: FileContext):
        self.name = name
        self.ctx = ctx
        self.relpath = ctx.relpath
        # local alias -> dotted target ("os", "a.b.c", "a.b.c.symbol")
        self.aliases: dict[str, str] = {}
        self.functions: dict[str, str] = {}  # top-level name -> qualname
        self.classes: dict[str, ClassInfo] = {}

    def package(self) -> str:
        if self.ctx.path.name == "__init__.py":
            return self.name
        return self.name.rsplit(".", 1)[0] if "." in self.name else ""


def attr_chain(node: ast.AST) -> Optional[list[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None for anything non-trivial."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _collect_imports(mod: ModuleInfo) -> None:
    """Record every import in the file (function-local ones too — the
    codebase imports lazily) into one module-wide alias table."""
    pkg = mod.package()
    for node in ast.walk(mod.ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mod.aliases[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    mod.aliases.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg.split(".") if pkg else []
                if node.level - 1:
                    base_parts = base_parts[:-(node.level - 1)]
                base = ".".join(base_parts)
            else:
                base = ""
            if node.module:
                base = f"{base}.{node.module}" if base else node.module
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{base}.{alias.name}" if base else alias.name
                mod.aliases[alias.asname or alias.name] = target


class ProjectIndex:
    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.by_relpath: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.calls: dict[str, list[CallSite]] = {}  # caller -> sites
        self.callers: dict[str, list[CallSite]] = {}  # callee -> sites
        self.site_by_node: dict[int, CallSite] = {}  # id(Call) -> site

    # -- construction -------------------------------------------------------

    def _collect_defs(self, mod: ModuleInfo) -> None:
        def visit(body: list[ast.stmt], cls: Optional[ClassInfo],
                  fn: Optional[FunctionInfo], prefix: str) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}.{node.name}"
                    a = node.args
                    fi = FunctionInfo(
                        qualname=qual, module=mod.name, relpath=mod.relpath,
                        node=node,
                        cls=cls.qualname if cls else None,
                        parent=fn.qualname if fn else None,
                        params=[p.arg for p in a.posonlyargs + a.args],
                        kwonly=[p.arg for p in a.kwonlyargs])
                    self.functions[qual] = fi
                    if fn is not None:
                        fn.nested[node.name] = qual
                    elif cls is not None:
                        cls.methods[node.name] = fi
                    else:
                        mod.functions[node.name] = qual
                    # keep ``cls`` visible inside nested defs: closures
                    # over ``self`` are everywhere in the data plane
                    visit(node.body, cls, fi, qual)
                elif isinstance(node, ast.ClassDef):
                    qual = f"{prefix}.{node.name}"
                    ci = ClassInfo(qualname=qual, module=mod.name, node=node,
                                   base_exprs=list(node.bases))
                    self.classes[qual] = ci
                    if cls is None and fn is None:
                        mod.classes[node.name] = ci
                    visit(node.body, ci, None, qual)
                else:
                    # conditional defs (if TYPE_CHECKING / try-import)
                    for attr in ("body", "orelse", "finalbody"):
                        sub = getattr(node, attr, None)
                        if isinstance(sub, list):
                            visit(sub, cls, fn, prefix)
                    for handler in getattr(node, "handlers", []) or []:
                        visit(handler.body, cls, fn, prefix)

        visit(mod.ctx.tree.body, None, None, mod.name)

    def _link_bases(self) -> None:
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for b in ci.base_exprs:
                    chain = attr_chain(b)
                    if not chain:
                        continue
                    target = self._resolve_class_ref(mod, chain)
                    if target:
                        ci.bases.append(target)

    def _resolve_class_ref(self, mod: ModuleInfo,
                           chain: list[str]) -> Optional[str]:
        head = chain[0]
        if len(chain) == 1:
            if head in mod.classes:
                return mod.classes[head].qualname
            if head in mod.aliases:
                q = self.resolve_dotted(mod.aliases[head])
                if q in self.classes:
                    return q
            return None
        if head in mod.aliases:
            dotted = ".".join([mod.aliases[head]] + chain[1:])
            q = self.resolve_dotted(dotted)
            if q in self.classes:
                return q
        return None

    # -- symbol resolution --------------------------------------------------

    def resolve_dotted(self, dotted: str,
                       _seen: Optional[set] = None) -> Optional[str]:
        """Map a fully-dotted reference onto a known function/class
        qualname (longest module prefix wins). Classes resolve to their
        ``__init__`` when one is reachable, else the class qualname."""
        if _seen is None:
            _seen = set()
        if dotted in _seen:
            return None
        _seen.add(dotted)
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            modname = ".".join(parts[:i])
            m = self.modules.get(modname)
            if m is None:
                continue
            rest = parts[i:]
            if not rest:
                return None  # bare module reference, not callable
            if len(rest) == 1:
                name = rest[0]
                if name in m.functions:
                    return m.functions[name]
                if name in m.classes:
                    return self._class_target(m.classes[name])
                if name in m.aliases:  # re-export chain
                    return self.resolve_dotted(m.aliases[name], _seen)
                return None
            if len(rest) == 2 and rest[0] in m.classes:
                return self._method_on_class(m.classes[rest[0]], rest[1])
            return None
        return None

    def _class_target(self, ci: ClassInfo) -> str:
        init = self._method_on_class(ci, "__init__")
        return init if init else ci.qualname

    def _method_on_class(self, ci: ClassInfo, name: str,
                         _seen: Optional[set] = None) -> Optional[str]:
        if _seen is None:
            _seen = set()
        if ci.qualname in _seen:
            return None
        _seen.add(ci.qualname)
        if name in ci.methods:
            return ci.methods[name].qualname
        for base in ci.bases:
            bc = self.classes.get(base)
            if bc is not None:
                found = self._method_on_class(bc, name, _seen)
                if found:
                    return found
        return None

    def _resolve_call(self, call: ast.Call, mod: ModuleInfo,
                      cls: Optional[ClassInfo],
                      fn_chain: list[FunctionInfo],
                      local_names: set[str]) -> Optional[str]:
        chain = attr_chain(call.func)
        if chain is None:
            return None
        head = chain[0]
        if len(chain) == 1:
            for enc in reversed(fn_chain):
                if head in enc.nested:
                    return enc.nested[head]
            if head in local_names:
                return None  # shadowed by a local binding
            if head in mod.functions:
                return mod.functions[head]
            if head in mod.classes:
                return self._class_target(mod.classes[head])
            if head in mod.aliases:
                return self.resolve_dotted(mod.aliases[head])
            return None
        if head in ("self", "cls") and cls is not None:
            if len(chain) == 2:
                return self._method_on_class(cls, chain[1])
            return None
        if head in local_names:
            return None
        if head in mod.aliases:
            return self.resolve_dotted(
                ".".join([mod.aliases[head]] + chain[1:]))
        return None

    # -- call-site collection -----------------------------------------------

    @staticmethod
    def _local_bindings(fn_node: ast.AST) -> set[str]:
        """Names bound inside the function (params, assignments, loop
        and with targets) — these shadow module scope for resolution."""
        names: set[str] = set()
        a = fn_node.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs):
            names.add(p.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)

        def targets(t: ast.AST) -> None:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    targets(e)
            elif isinstance(t, ast.Starred):
                targets(t.value)

        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    targets(t)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        targets(item.optional_vars)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                names.add(node.name)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    targets(gen.target)
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Global):
                # ``global X`` assignments bind module scope, not local
                for gname in node.names:
                    names.discard(gname)
        return names

    def _record(self, call: ast.Call, caller: str, mod: ModuleInfo,
                cls: Optional[ClassInfo], fn_chain: list[FunctionInfo],
                local_names: set[str]) -> None:
        callee = self._resolve_call(call, mod, cls, fn_chain, local_names)
        site = CallSite(caller=caller, relpath=mod.relpath,
                        lineno=call.lineno, node=call, callee=callee)
        self.calls.setdefault(caller, []).append(site)
        self.site_by_node[id(call)] = site
        if callee is not None:
            self.callers.setdefault(callee, []).append(site)

    def _collect_calls(self, mod: ModuleInfo) -> None:
        def visit(node: ast.AST, caller: str, prefix: str,
                  cls: Optional[ClassInfo], fn_chain: list[FunctionInfo],
                  local_names: set[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{node.name}"
                fi = self.functions.get(qual)
                # decorators/defaults evaluate in the ENCLOSING scope
                for dec in node.decorator_list:
                    visit(dec, caller, prefix, cls, fn_chain, local_names)
                for dflt in (node.args.defaults + node.args.kw_defaults):
                    if dflt is not None:
                        visit(dflt, caller, prefix, cls, fn_chain,
                              local_names)
                if fi is None:
                    return
                locs = self._local_bindings(node)
                for child in node.body:
                    visit(child, qual, qual, cls, fn_chain + [fi], locs)
                return
            if isinstance(node, ast.ClassDef):
                qual = f"{prefix}.{node.name}"
                ci = self.classes.get(qual)
                # class-body statements execute at import time: keep the
                # enclosing caller for them, but resolve self.* against
                # the class for the methods inside
                for child in node.body:
                    visit(child, caller, qual, ci, [], set())
                return
            if isinstance(node, ast.Call):
                self._record(node, caller, mod, cls, fn_chain, local_names)
            for child in ast.iter_child_nodes(node):
                visit(child, caller, prefix, cls, fn_chain, local_names)

        for stmt in mod.ctx.tree.body:
            visit(stmt, mod.name, mod.name, None, [], set())

    # -- cache support ------------------------------------------------------

    def file_deps(self) -> dict[str, set[str]]:
        """relpath -> set of project-internal relpaths it imports
        (direct edges; the cache takes the transitive reverse closure).
        """
        deps: dict[str, set[str]] = {}
        for mod in self.modules.values():
            out: set[str] = set()
            for dotted in mod.aliases.values():
                parts = dotted.split(".")
                for i in range(len(parts), 0, -1):
                    target = self.modules.get(".".join(parts[:i]))
                    if target is not None:
                        if target.relpath != mod.relpath:
                            out.add(target.relpath)
                        break
            deps[mod.relpath] = out
        return deps


def build_index(contexts: Iterable[FileContext]) -> ProjectIndex:
    idx = ProjectIndex()
    for ctx in contexts:
        mod = ModuleInfo(module_name_for(ctx.path), ctx)
        idx.modules[mod.name] = mod
        idx.by_relpath[ctx.relpath] = mod
    for mod in idx.modules.values():
        _collect_imports(mod)
        idx._collect_defs(mod)
    idx._link_bases()
    for mod in idx.modules.values():
        idx._collect_calls(mod)
    return idx
