"""Runtime lock-order / race detector for the pipelined data plane.

PR 1 left the backup path with four concurrent stages and ~10 lock
sites whose safety rests on two unwritten rules: locks nest in one
global order, and pipeline shared state (`_pl_open`, `_pl_inflight`,
the open-pack buffers) is only touched under the repository lock.
This module makes both rules executable.

With ``VOLSYNC_TPU_LOCKCHECK=1`` (envflags.lockcheck_enabled), the
data-plane modules construct their locks through :func:`make_lock` /
:func:`make_rlock`, which return instrumented wrappers that:

* keep a per-thread stack of held locks;
* record a directed edge ``A -> B`` (keyed by lock *name*, i.e. lock
  class, not instance) whenever B is acquired while A is held;
* raise :class:`LockOrderError` the moment a new edge closes a cycle
  in that graph — the AB/BA pattern that deadlocks only under the
  right interleaving is caught on ANY interleaving;
* raise on a blocking re-acquire of a non-reentrant lock the current
  thread already holds (guaranteed self-deadlock);
* back :func:`assert_held`, the guard the pipeline stages place in
  front of shared-state mutation.

Without the flag, ``make_lock``/``make_rlock`` return plain
``threading.Lock``/``RLock`` objects and :func:`assert_held` is a
no-op — zero cost on the hot path.

Every violation is BOTH raised in the offending thread and appended to
a module-level list (:func:`violations`): pipeline workers swallow
exceptions into ``_pl_error`` by design, so the test fixture checks
the list at teardown rather than trusting propagation.
"""

from __future__ import annotations

import threading
from typing import Optional

from volsync_tpu import envflags


class LockOrderError(RuntimeError):
    """A lock acquisition would close a cycle in the lock-order graph
    (potential deadlock), or re-acquire a held non-reentrant lock
    (certain deadlock)."""


class LockGuardError(RuntimeError):
    """Shared state guarded by a lock was touched by a thread not
    holding it."""


# Graph + violation log, shared across all instrumented locks.
_state = threading.Lock()
_edges: dict[str, set[str]] = {}
_edge_sites: dict[tuple[str, str], str] = {}
_violations: list[str] = []

_tls = threading.local()


def enabled() -> bool:
    return envflags.lockcheck_enabled()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _record_violation(msg: str) -> None:
    with _state:
        _violations.append(msg)


def _reaches(src: str, dst: str) -> Optional[list[str]]:
    """Path src -> ... -> dst in the edge graph (caller holds _state);
    None if unreachable."""
    seen = {src}
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class _InstrumentedLock:
    """Lock/RLock drop-in recording acquisition order. ``name`` is the
    lock's CLASS (every Repository's state lock shares one name): the
    order invariant is between classes of lock, and an edge between two
    same-named instances is itself a hazard (two repos locked in
    opposite orders by two threads is a real ABBA)."""

    def __init__(self, name: str, *, reentrant: bool):
        self._name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0

    # -- bookkeeping ----------------------------------------------------

    def _check_order(self) -> None:
        """Pre-acquire: raise if taking this lock would deadlock or
        close an order cycle. Runs BEFORE the blocking acquire so the
        detector reports instead of hanging."""
        me = threading.get_ident()
        held = _held_stack()
        if self._owner == me:
            if self._reentrant:
                return  # re-entry: no new ordering information
            msg = (f"lockcheck: thread {threading.current_thread().name} "
                   f"re-acquiring non-reentrant lock '{self._name}' it "
                   f"already holds (self-deadlock)")
            _record_violation(msg)
            raise LockOrderError(msg)
        with _state:
            for holder in held:
                a, b = holder._name, self._name
                if holder is self or (a, b) in _edge_sites:
                    continue
                cycle = _reaches(b, a)
                if cycle is not None:
                    where = " ; ".join(
                        f"{x}->{y} first seen {_edge_sites[(x, y)]}"
                        for x, y in zip(cycle, cycle[1:]))
                    msg = (f"lockcheck: lock-order cycle: acquiring "
                           f"'{b}' while holding '{a}' in thread "
                           f"{threading.current_thread().name}, but "
                           f"{where}")
                    _violations.append(msg)
                    raise LockOrderError(msg)

    def _record_acquired(self) -> None:
        """Post-acquire: insert held->self edges, atomically re-checking
        acyclicity per insertion (closes the window between the
        pre-acquire check and this record — the graph is acyclic as an
        invariant, so a raise here is never stale). Raises with the
        inner lock still held; acquire() releases it."""
        me = threading.get_ident()
        held = _held_stack()
        with _state:
            for holder in held:
                if holder is self:
                    continue
                a, b = holder._name, self._name
                if (a, b) in _edge_sites:
                    continue
                cycle = _reaches(b, a)
                if cycle is not None:
                    where = " ; ".join(
                        f"{x}->{y} first seen {_edge_sites[(x, y)]}"
                        for x, y in zip(cycle, cycle[1:]))
                    msg = (f"lockcheck: lock-order cycle: acquiring "
                           f"'{b}' while holding '{a}' in thread "
                           f"{threading.current_thread().name}, but "
                           f"{where}")
                    _violations.append(msg)
                    raise LockOrderError(msg)
                _edges.setdefault(a, set()).add(b)
                _edge_sites[(a, b)] = (
                    f"thread {threading.current_thread().name}")
        if self._owner == me:
            self._count += 1
            return
        self._owner = me
        self._count = 1
        held.append(self)

    def _record_released(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            self._count = 0
            held = _held_stack()
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break

    # -- Lock API -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._record_acquired()
            except LockOrderError:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        if self._owner == threading.get_ident():
            self._record_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        if self._reentrant:
            return self._count > 0
        return self._inner.locked()

    # -- guard hook -----------------------------------------------------

    def _lc_assert_held(self, what: str) -> None:
        if self._owner != threading.get_ident():
            msg = (f"lockcheck: {what} mutated by thread "
                   f"{threading.current_thread().name} without holding "
                   f"'{self._name}'")
            _record_violation(msg)
            raise LockGuardError(msg)

    def __repr__(self):
        state = f"held by {self._owner}" if self._count else "unlocked"
        return f"<InstrumentedLock {self._name!r} {state}>"


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented when VOLSYNC_TPU_LOCKCHECK=1
    (read at construction: locks built before the flag flips stay
    plain, which is why the lockcheck suites set the flag before
    constructing their repositories/stores)."""
    if enabled():
        return _InstrumentedLock(name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """``threading.RLock`` variant of :func:`make_lock`."""
    if enabled():
        return _InstrumentedLock(name, reentrant=True)
    return threading.RLock()


def assert_held(lock, what: str) -> None:
    """Guard for lock-protected shared state: raises LockGuardError if
    the calling thread does not hold ``lock``. No-op on plain
    (uninstrumented) locks, so call sites don't need their own
    enabled() branches."""
    hook = getattr(lock, "_lc_assert_held", None)
    if hook is not None:
        hook(what)


# -- test / inspection hooks ------------------------------------------------

def reset() -> None:
    """Clear the order graph and violation log (test isolation)."""
    with _state:
        _edges.clear()
        _edge_sites.clear()
        _violations.clear()


def violations() -> list[str]:
    """Violations recorded so far (raises may have been swallowed by
    worker threads — this list never is)."""
    with _state:
        return list(_violations)


def order_graph() -> dict[str, set[str]]:
    """Copy of the observed lock-order edges (name -> successors)."""
    with _state:
        return {k: set(v) for k, v in _edges.items()}


def graph() -> set:
    """The runtime-observed acquisition edges as a flat ``(held,
    acquired)`` name-pair set — the shape the static cross-check
    compares against (every edge here must be covered by the VL401
    graph, wildcard lock names matching by prefix; see
    analysis/lockflow.py)."""
    with _state:
        return {(a, b) for a, succs in _edges.items() for b in succs}
